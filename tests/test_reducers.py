"""Reducer tests on an 8-device virtual CPU mesh.

Transplants the reference's two integration oracles
(/root/reference/test/test_cgx.py):
* ``test_compressed_exact`` (lines 69-78): allreduce of constant tensors
  (value rank+1) is bit-exact at 2/4/8 bits.
* ``test_compressed_non_exact`` (lines 80-93): for ``(rank+1) * arange(-n/2,
  n/2)`` data, ``|result - exact|_inf < 2*min(bucket,n)/(2^bits-1) *
  ws*(ws+1)``.
Plus invariants the reference never tested: all ranks receive identical
results (error symmetry), hierarchical 2-level reduction, dummy-codec and
uncompressed paths.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from torch_cgx_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.config import CompressionConfig, TopologyConfig
from torch_cgx_tpu.parallel import mesh as mesh_mod
from torch_cgx_tpu.parallel import reducers

WS = 8


def _flat_mesh():
    return mesh_mod.flat_mesh()


def run_flat(per_rank: np.ndarray, fn):
    """per_rank: (ws, n) row r = rank r's local tensor. Returns (ws, n) of
    per-rank results (rows should be identical for a correct allreduce)."""
    mesh = _flat_mesh()
    body = shard_map(
        lambda x: fn(x[0])[None],
        mesh=mesh,
        in_specs=P("dp"),
        out_specs=P("dp"),
    )
    arr = jax.device_put(
        jnp.asarray(per_rank), NamedSharding(mesh, P("dp"))
    )
    return np.asarray(jax.jit(body)(arr))


def run_hier(per_rank: np.ndarray, fn):
    mesh = mesh_mod.hierarchical_mesh(intra_size=4)  # (cross=2, intra=4)
    body = shard_map(
        lambda x: fn(x[0, 0])[None, None],
        mesh=mesh,
        in_specs=P("cross", "intra"),
        out_specs=P("cross", "intra"),
    )
    ws = WS
    arr = jax.device_put(
        jnp.asarray(per_rank).reshape(2, 4, -1),
        NamedSharding(mesh, P("cross", "intra")),
    )
    out = np.asarray(jax.jit(body)(arr))
    return out.reshape(ws, -1)


def constant_inputs(n, dtype=np.float32):
    return np.stack([np.full((n,), r + 1, dtype) for r in range(WS)])


def arange_inputs(n, dtype=np.float32):
    base = np.arange(-n / 2, n / 2, 1.0)
    return np.stack([(r + 1) * base for r in range(WS)]).astype(dtype)


EXPECT_CONST = WS * (WS + 1) // 2  # sum over ranks of (rank+1)


def check_exact(out, expected):
    for r in range(WS):
        np.testing.assert_array_equal(out[r], expected, err_msg=f"rank {r}")


@pytest.mark.parametrize("algo", ["sra", "ring", "alltoall"])
@pytest.mark.parametrize("size", [1, 1000, 8192])
def test_compressed_exact_constant(algo, size):
    cc = CompressionConfig(bits=4, bucket_size=512)
    fn = {
        "sra": lambda x: reducers.sra_allreduce(x, "dp", WS, cc),
        "ring": lambda x: reducers.ring_allreduce(x, "dp", WS, cc),
        "alltoall": lambda x: reducers.alltoall_allreduce(x, "dp", WS, cc),
    }[algo]
    out = run_flat(constant_inputs(size), fn)
    check_exact(out, np.full((size,), EXPECT_CONST, np.float32))


@pytest.mark.parametrize("bits", [2, 8])
def test_compressed_exact_constant_bits(bits):
    cc = CompressionConfig(bits=bits, bucket_size=1024)
    out = run_flat(
        constant_inputs(4096),
        lambda x: reducers.sra_allreduce(x, "dp", WS, cc),
    )
    check_exact(out, np.full((4096,), EXPECT_CONST, np.float32))


@pytest.mark.parametrize("algo", ["sra", "ring", "alltoall"])
@pytest.mark.parametrize("bits", [2, 4, 8])
@pytest.mark.parametrize("bucket_size", [64, 512])
def test_error_envelope(algo, bits, bucket_size):
    size = 16384
    cc = CompressionConfig(bits=bits, bucket_size=bucket_size)
    fn = {
        "sra": lambda x: reducers.sra_allreduce(x, "dp", WS, cc),
        "ring": lambda x: reducers.ring_allreduce(x, "dp", WS, cc),
        "alltoall": lambda x: reducers.alltoall_allreduce(x, "dp", WS, cc),
    }[algo]
    inputs = arange_inputs(size)
    out = run_flat(inputs, fn)
    expected = inputs.sum(axis=0)
    bound = 2 * min(bucket_size, size) / ((1 << bits) - 1) * WS * (WS + 1)
    for r in range(WS):
        err = np.max(np.abs(out[r] - expected))
        assert err < bound, (algo, bits, bucket_size, err, bound)
    # error symmetry: every rank decodes the same bytes
    for r in range(1, WS):
        np.testing.assert_array_equal(out[0], out[r])


def test_sra_scatter_reduce_keeps_own_chunk_exact():
    """Round 1 accumulates peers into the RAW own chunk (the reference keeps
    one's own data exact during scatter-reduce,
    scatter_reduce_allgather.cc:116-155): with every peer contribution
    constant (exact at any bits) and only the own chunk varying, the reduced
    chunk must be exact — r3's SPMD form quantized the own contribution too
    (VERDICT r3 weak #4)."""
    chunk = 64
    size = WS * chunk
    cc = CompressionConfig(bits=2, bucket_size=chunk)
    rng = np.random.default_rng(5)
    varying = rng.normal(size=(WS, chunk)).astype(np.float32)
    per_rank = np.ones((WS, size), np.float32)
    for r in range(WS):
        per_rank[r, r * chunk : (r + 1) * chunk] = varying[r]
    out = run_flat(
        per_rank,
        lambda x: reducers.reduce_scatter_quantized(x, "dp", WS, cc),
    )
    for r in range(WS):
        expect = varying[r].astype(np.float64) + (WS - 1)
        np.testing.assert_allclose(out[r], expect, rtol=0, atol=1e-5)


def test_sra_envelope_tightened_by_exact_own_chunk():
    """The SRA stage-1 error now sums over ws-1 peers (+ the stage-2
    requant), so the envelope factor drops from the reference's
    ws*(ws+1)-shape to ~ws*(ws+1)/2: stage 1 <= sum_{peers}(r+1)/2 and
    stage 2 <= sum_r(r+1)/2 bucket units."""
    size, bits, bucket = 16384, 4, 512
    cc = CompressionConfig(bits=bits, bucket_size=bucket)
    inputs = arange_inputs(size)
    out = run_flat(inputs, lambda x: reducers.sra_allreduce(x, "dp", WS, cc))
    expected = inputs.sum(axis=0)
    s = WS * (WS + 1) / 2
    bound = min(bucket, size) / ((1 << bits) - 1) * (1.2 * s)
    for r in range(WS):
        err = np.max(np.abs(out[r] - expected))
        assert err < bound, (err, bound)


def test_envelope_odd_size():
    size, bits, bucket = 1025, 4, 512
    cc = CompressionConfig(bits=bits, bucket_size=bucket)
    inputs = arange_inputs(size)
    out = run_flat(inputs, lambda x: reducers.sra_allreduce(x, "dp", WS, cc))
    expected = inputs.sum(axis=0)
    bound = 2 * min(bucket, size) / ((1 << bits) - 1) * WS * (WS + 1)
    assert np.max(np.abs(out[0] - expected)) < bound


@pytest.mark.parametrize("stochastic", [False, True])
def test_ring_scan_matches_unrolled(stochastic):
    """The scan-based ring must emit the same bytes hop for hop as the
    Python-unrolled oracle: identical outputs bit for bit, deterministic
    AND stochastic (fold_in on a scan-carried step equals fold_in on the
    static step of the same value)."""
    size = 4096
    cc = CompressionConfig(bits=4, bucket_size=64, stochastic=stochastic)
    key = jnp.asarray(jax.random.PRNGKey(7)) if stochastic else None
    inputs = arange_inputs(size)
    out_scan = run_flat(
        inputs, lambda x: reducers.ring_allreduce(x, "dp", WS, cc, key)
    )
    out_unrl = run_flat(
        inputs,
        lambda x: reducers._ring_allreduce_unrolled(x, "dp", WS, cc, key),
    )
    np.testing.assert_array_equal(out_scan, out_unrl)


def _count_eqns(jaxpr) -> int:
    n = 0
    for eqn in jaxpr.eqns:
        n += 1
        for v in eqn.params.values():
            if isinstance(v, jax.extend.core.ClosedJaxpr):
                n += _count_eqns(v.jaxpr)
            elif isinstance(v, jax.extend.core.Jaxpr):
                n += _count_eqns(v)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if isinstance(item, jax.extend.core.ClosedJaxpr):
                        n += _count_eqns(item.jaxpr)
                    elif isinstance(item, jax.extend.core.Jaxpr):
                        n += _count_eqns(item)
    return n


def test_ring_scan_program_size_constant_in_ws():
    """Compile-cost regression guard (VERDICT r4 weak #4): the traced ring
    program must not grow with world size — a v5p-64 ring would otherwise
    trace 126 codec invocations per fusion slice. Equation counts at ws=4
    and ws=8 must be identical (only scan trip counts differ), and far
    below the unrolled form's."""
    from jax.sharding import Mesh

    cc = CompressionConfig(bits=4, bucket_size=64)

    def trace(ws, fn):
        mesh = Mesh(np.array(jax.devices()[:ws]), ("dp",))
        body = shard_map(
            lambda x: fn(x[0], ws)[None], mesh=mesh,
            in_specs=P("dp"), out_specs=P("dp"),
        )
        return jax.make_jaxpr(body)(jnp.zeros((ws, 4096), jnp.float32))

    scan_fn = lambda x, ws: reducers.ring_allreduce(x, "dp", ws, cc)
    unrolled_fn = lambda x, ws: reducers._ring_allreduce_unrolled(x, "dp", ws, cc)
    n4 = _count_eqns(trace(4, scan_fn).jaxpr)
    n8 = _count_eqns(trace(8, scan_fn).jaxpr)
    assert n4 == n8, (n4, n8)
    n8_unrolled = _count_eqns(trace(8, unrolled_fn).jaxpr)
    assert n8 < n8_unrolled / 2, (n8, n8_unrolled)


def _pallas_kernel_counts(jaxpr):
    """kernel-function-name -> pallas_call count, walking nested jaxprs.
    Kernel closures in codec_pallas.py carry distinctive names
    (_quantize_flat_kernel, _sra_epilogue_kernel, ...) precisely so this
    guard can count codec invocations by identity."""
    from collections import Counter

    counts = Counter()

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "pallas_call":
                info = str(eqn.params.get("name_and_src_info", ""))
                counts[info.split(" ")[0]] += 1
            for v in eqn.params.values():
                for item in v if isinstance(v, (list, tuple)) else [v]:
                    if isinstance(item, jax.extend.core.ClosedJaxpr):
                        walk(item.jaxpr)
                    elif isinstance(item, jax.extend.core.Jaxpr):
                        walk(item)

    walk(jaxpr)
    return counts


def test_sra_codec_invocation_guard(monkeypatch):
    """Codec-invocation regression guard (ISSUE 4), alongside the ring
    jaxpr-size guard above: the fused SRA program must stage exactly ONE
    quantize kernel (stage 1) and ONE fused epilogue kernel per shard —
    plus a single decode for the allgather phase — and in particular no
    standalone peer-row dequantize and no standalone stage-2 quantize.
    A refactor that silently reintroduces the second codec round trip
    (the 25.5%-overhead shape PERF_NOTES.md round 5 measured) fails
    here at trace time, no hardware needed."""
    from jax.sharding import Mesh

    from torch_cgx_tpu.ops import codec as codec_mod

    monkeypatch.setenv("CGX_CODEC_IMPL", "pallas")
    monkeypatch.setenv("CGX_SRA_EPILOGUE", "fused")
    ws, b = 4, 128
    n = ws * 2 * codec_mod.CHUNK_BUCKETS * b  # whole chunks per shard row
    cc = CompressionConfig(bits=4, bucket_size=b)
    mesh = Mesh(np.array(jax.devices()[:ws]), ("dp",))
    body = shard_map(
        lambda x: reducers.sra_allreduce(x[0], "dp", ws, cc)[None],
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False,  # pallas_call has no shard_map replication rule
    )
    counts = _pallas_kernel_counts(
        jax.make_jaxpr(body)(jnp.zeros((ws, n), jnp.float32)).jaxpr
    )
    assert counts.get("_quantize_flat_kernel", 0) == 1, counts
    assert counts.get("_sra_epilogue_kernel", 0) == 1, counts
    # allgather decode only; the peer-row decode lives inside the epilogue
    assert counts.get("_dequantize_flat_kernel", 0) == 1, counts
    assert counts.get("_reduce_rows_kernel", 0) == 0, counts
    # nothing else codec-shaped hides elsewhere in the program
    assert sum(counts.values()) == 3, counts


def test_sra_fused_epilogue_matches_staged_end_to_end(monkeypatch):
    """sra_allreduce under forced-fused dispatch is bit-identical to the
    staged lowering, through the real shard_map collectives (the
    wire-identity acceptance criterion, CGX_CODEC_ENCODE=div default)."""
    ws, b = 8, 128
    n = ws * codec_chunked_n(b)
    data = (
        np.arange(ws * n, dtype=np.float32).reshape(ws, n) / (ws * n) - 0.5
    )
    cc = CompressionConfig(bits=4, bucket_size=b)

    def run(per_rank):
        mesh = _flat_mesh()
        body = shard_map(
            lambda x: reducers.sra_allreduce(x[0], "dp", WS, cc)[None],
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,  # pallas_call has no replication rule
        )
        arr = jax.device_put(
            jnp.asarray(per_rank), NamedSharding(mesh, P("dp"))
        )
        return np.asarray(jax.jit(body)(arr))

    monkeypatch.setenv("CGX_SRA_EPILOGUE", "staged")
    staged = run(data)
    monkeypatch.setenv("CGX_SRA_EPILOGUE", "fused")
    monkeypatch.setenv("CGX_CODEC_IMPL", "pallas")
    fused = run(data)
    np.testing.assert_array_equal(staged, fused)


def codec_chunked_n(b: int) -> int:
    """Per-rank chunk elements that keep every SRA row whole 32-bucket
    chunks at bucket size b (the fused fast-path geometry)."""
    from torch_cgx_tpu.ops import codec as codec_mod

    return codec_mod.CHUNK_BUCKETS * b


def test_uncompressed_psum_exact():
    cc = CompressionConfig(bits=32)
    inputs = arange_inputs(1000)
    out = run_flat(
        inputs,
        lambda x: reducers.quantized_allreduce(x, "dp", WS, cc, cgx_config.REDUCTION_SRA),
    )
    np.testing.assert_allclose(out[0], inputs.sum(axis=0), rtol=1e-6)


def test_dummy_compression_exact(monkeypatch):
    monkeypatch.setenv(cgx_config.DEBUG_DUMMY_COMPRESSION, "1")
    cc = CompressionConfig(bits=4)
    inputs = arange_inputs(500)
    out = run_flat(
        inputs,
        lambda x: reducers.quantized_allreduce(x, "dp", WS, cc, cgx_config.REDUCTION_SRA),
    )
    np.testing.assert_allclose(out[0], inputs.sum(axis=0), rtol=1e-6)


def test_stochastic_rounding_envelope():
    size, bits, bucket = 8192, 4, 512
    cc = CompressionConfig(bits=bits, bucket_size=bucket, stochastic=True)
    inputs = arange_inputs(size)
    key = jax.random.PRNGKey(7)
    out = run_flat(
        inputs, lambda x: reducers.sra_allreduce(x, "dp", WS, cc, key=key)
    )
    expected = inputs.sum(axis=0)
    bound = 2 * min(bucket, size) / ((1 << bits) - 1) * WS * (WS + 1)
    assert np.max(np.abs(out[0] - expected)) < bound
    for r in range(1, WS):
        np.testing.assert_array_equal(out[0], out[r])


@pytest.mark.parametrize("leader", [True, False])
def test_hierarchical_exact_constant(leader):
    cc = CompressionConfig(bits=4, bucket_size=512)
    topo = TopologyConfig(intra_broadcast=leader)
    out = run_hier(
        constant_inputs(2048),
        lambda x: reducers.hierarchical_allreduce(
            x,
            intra_axis="intra",
            cross_axis="cross",
            ws_intra=4,
            ws_cross=2,
            cc=cc,
            topology=topo,
        ),
    )
    check_exact(out, np.full((2048,), EXPECT_CONST, np.float32))


def test_hierarchical_envelope():
    size, bits, bucket = 16384, 4, 512
    cc = CompressionConfig(bits=bits, bucket_size=bucket)
    inputs = arange_inputs(size)
    out = run_hier(
        inputs,
        lambda x: reducers.hierarchical_allreduce(
            x,
            intra_axis="intra",
            cross_axis="cross",
            ws_intra=4,
            ws_cross=2,
            cc=cc,
            topology=TopologyConfig(),
        ),
    )
    expected = inputs.sum(axis=0)
    # Two quantization levels compound; double the flat envelope.
    bound = 4 * min(bucket, size) / ((1 << bits) - 1) * WS * (WS + 1)
    assert np.max(np.abs(out[0] - expected)) < bound
    for r in range(1, WS):
        np.testing.assert_array_equal(out[0], out[r])


def test_hierarchical_uncompressed_levels():
    # intra_compress=0: ICI level runs raw psum_scatter/all_gather.
    cc = CompressionConfig(bits=4, bucket_size=512)
    topo = TopologyConfig(intra_compress=False)
    inputs = constant_inputs(1024)
    out = run_hier(
        inputs,
        lambda x: reducers.hierarchical_allreduce(
            x,
            intra_axis="intra",
            cross_axis="cross",
            ws_intra=4,
            ws_cross=2,
            cc=cc,
            topology=topo,
        ),
    )
    check_exact(out, np.full((1024,), EXPECT_CONST, np.float32))


def test_bf16_constant_exact():
    cc = CompressionConfig(bits=4, bucket_size=512)
    inputs = constant_inputs(1024)
    out = run_flat(
        inputs.astype(jnp.bfloat16),
        lambda x: reducers.sra_allreduce(x, "dp", WS, cc),
    )
    check_exact(out.astype(np.float32), np.full((1024,), EXPECT_CONST, np.float32))


def test_fake_ratio_traffic_shaping(monkeypatch):
    # CGX_COMPRESSION_FAKE_RATIO=0.5: only the leading half of the slice is
    # reduced; the tail keeps each rank's local (pre-divided) values
    # (mpi_allreduce_operations.cc:130-144 — debug knob, breaks correctness
    # by design).
    from torch_cgx_tpu.parallel.allreduce import allreduce_flat

    monkeypatch.setenv("CGX_COMPRESSION_FAKE_RATIO", "0.5")
    cc = CompressionConfig(bits=4, bucket_size=512)
    n = 2048
    inputs = constant_inputs(n)
    mesh = _flat_mesh()
    out = run_flat(
        inputs,
        lambda x: allreduce_flat(x, cc, mesh=mesh, axes=("dp",)),
    )
    head, tail = out[:, : n // 2], out[:, n // 2 :]
    assert np.array_equal(
        head, np.full((WS, n // 2), EXPECT_CONST, np.float32)
    ), "reduced head must be exact on constants"
    assert np.array_equal(tail, inputs[:, n // 2 :]), "tail must stay local"


def test_quantized_ppermute_envelope():
    """Quantized point-to-point hop: payload decodes within the per-bucket
    envelope, and constant payloads travel bit-exactly."""
    from torch_cgx_tpu.parallel.reducers import quantized_ppermute

    ws, n = WS, 8192
    mesh = mesh_mod.flat_mesh()
    perm = [(i, (i + 1) % ws) for i in range(ws)]
    cc = CompressionConfig(bits=8, bucket_size=512)

    def hop(x):
        return quantized_ppermute(x, "dp", perm, cc)

    x = jnp.stack([
        jnp.linspace(-1.0, 1.0, n, dtype=jnp.float32) * (r + 1)
        for r in range(ws)
    ])
    got = jax.jit(
        shard_map(lambda v: hop(v[0])[None], mesh=mesh,
                  in_specs=(P("dp"),), out_specs=P("dp"), check_vma=False)
    )(x)
    want = np.roll(np.asarray(x), 1, axis=0)  # right rotation
    err = np.abs(np.asarray(got) - want).max()
    unit = 2.0 * (2 * ws) / 255 / (n // 512)  # loose per-bucket bound
    assert err <= unit, (err, unit)

    const = jnp.stack([
        jnp.full((n,), float(r + 1), jnp.float32) for r in range(ws)
    ])
    got_c = jax.jit(
        shard_map(lambda v: hop(v[0])[None], mesh=mesh,
                  in_specs=(P("dp"),), out_specs=P("dp"), check_vma=False)
    )(const)
    np.testing.assert_array_equal(
        np.asarray(got_c), np.roll(np.asarray(const), 1, axis=0)
    )


def test_quantized_ppermute_ste_gradient():
    """STE backward: cotangent rides the inverse permutation through the
    codec; a constant cotangent (from sum) survives bit-exactly, weighted
    cotangents land on the inverse-permuted device."""
    from torch_cgx_tpu.parallel.reducers import quantized_ppermute

    ws, n = WS, 2048
    mesh = mesh_mod.flat_mesh()
    perm = [(i, (i + 1) % ws) for i in range(ws)]
    cc = CompressionConfig(bits=8, bucket_size=512)
    x = jnp.stack([
        jnp.linspace(0.0, 1.0, n, dtype=jnp.float32) * (r + 1)
        for r in range(ws)
    ])

    def loss(v):
        rank_w = jax.lax.axis_index("dp").astype(jnp.float32) + 1.0
        return jnp.sum(quantized_ppermute(v[0], "dp", perm, cc) * rank_w)

    g = jax.jit(
        shard_map(lambda v: jax.grad(loss)(v), mesh=mesh,
                  in_specs=(P("dp"),), out_specs=P("dp"), check_vma=False)
    )(x)
    # d(loss)/dx on device r = weight of the device its activation went TO
    # (r+1 -> weight r+2, wrapping); constant planes quantize exactly.
    g = np.asarray(g)
    for r in range(ws):
        want = float((r + 1) % ws + 1)
        np.testing.assert_allclose(g[r], want, rtol=0, atol=0)


def test_quantized_all_to_all_matches_plain_within_envelope():
    """The quantized Ulysses reshard must produce the plain all_to_all's
    layout, within the per-slice quantization envelope; constant payloads
    travel bit-exactly; STE gradients flow through the inverse reshard."""
    from torch_cgx_tpu.parallel.reducers import quantized_all_to_all

    ws = WS
    mesh = mesh_mod.flat_mesh()
    cc = CompressionConfig(bits=8, bucket_size=64)
    b, h, s, d = 2, ws, ws * 16, 8  # heads split, sequence gathered
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(ws, b, h, s // ws, d)), jnp.float32)

    def q_fn(v):
        return quantized_all_to_all(
            v[0], "dp", split_axis=1, concat_axis=2, cc=cc
        )[None]

    def p_fn(v):
        from jax import lax

        return lax.all_to_all(
            v[0], "dp", split_axis=1, concat_axis=2, tiled=True
        )[None]

    run = lambda f: np.asarray(  # noqa: E731
        jax.jit(shard_map(f, mesh=mesh, in_specs=(P("dp"),),
                          out_specs=P("dp"), check_vma=False))(x)
    )
    got, want = run(q_fn), run(p_fn)
    assert got.shape == want.shape
    err = np.abs(got - want).max()
    assert 0 < err < 8 / 255 * 2, err  # ~range/(2^8-1) per 64-bucket

    # constant payload: bit-exact
    xc = jnp.ones_like(x) * 3.0
    got_c = np.asarray(
        jax.jit(shard_map(q_fn, mesh=mesh, in_specs=(P("dp"),),
                          out_specs=P("dp"), check_vma=False))(xc)
    )
    np.testing.assert_array_equal(got_c, np.full_like(got_c, 3.0))

    # STE gradient: constant cotangent survives the inverse reshard exactly
    def loss(v):
        return jnp.sum(
            quantized_all_to_all(v[0], "dp", split_axis=1, concat_axis=2,
                                 cc=cc)
        )

    g = np.asarray(
        jax.jit(shard_map(lambda v: jax.grad(loss)(v), mesh=mesh,
                          in_specs=(P("dp"),), out_specs=P("dp"),
                          check_vma=False))(x)
    )
    np.testing.assert_array_equal(g, np.ones_like(g))


# ---------------------------------------------------------------------------
# Shared-wire (quantize-once) EF variants: bit-identical to reducer + mirror.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("red", ["SRA", "ALLTOALL", "RING", "PSUM"])
def test_allreduce_with_wire_matches_reducer_and_mirror(red):
    """quantized_allreduce_with_wire must return (a) exactly the reducer's
    output and (b) exactly the wire decode the old stand-alone mirror
    computed — under STOCHASTIC rounding, so any drift in key derivation
    (the bug class the shared-payload design removes) changes bytes and
    fails loudly. PSUM: exact wire, rt == x."""
    cc = CompressionConfig(
        bits=4, bucket_size=128, stochastic=(red != "PSUM")
    )
    n = 1000
    xs = arange_inputs(n)
    key = jax.random.PRNGKey(3)

    def with_wire(x):
        out, rt = reducers.quantized_allreduce_with_wire(
            x, "dp", WS, cc, red, key
        )
        return jnp.stack([out, rt.astype(out.dtype)])

    both = run_flat(xs, with_wire)  # (ws, 2, n)
    out, rt = both[:, 0], both[:, 1]

    plain = run_flat(
        xs, lambda x: reducers.quantized_allreduce(x, "dp", WS, cc, red, key)
    )
    np.testing.assert_array_equal(out, plain)

    if red == "PSUM":
        np.testing.assert_array_equal(rt, xs)
        return

    # The mirror the shared-payload path replaced: quantize this device's
    # stage-1 contribution with the wire's exact key derivation, decode.
    def mirror(x):
        if red == "ALLTOALL":
            k = jax.random.fold_in(key, jax.lax.axis_index("dp"))
            q = reducers._quantize_1d(x, cc, k)
            return reducers._dequantize_1d(q).astype(x.dtype)
        if red == "RING":
            # Re-derive the hop-0 decode independently (NOT via
            # _ring_hop0_wire, which the implementation itself returns):
            # own outgoing segment = row `rank`, keyed like
            # ring_allreduce's first scatter step.
            rank = jax.lax.axis_index("dp")
            chunk = reducers._chunk_size(n, WS)
            rows = reducers._pad_rows(x, WS, chunk)
            seg = jax.lax.dynamic_slice(rows, (rank, 0), (1, chunk))
            k = jax.random.fold_in(jax.random.fold_in(key, 0), rank)
            q = reducers._quantize_rows(seg, cc, k)
            dec = reducers._dequantize_rows(q).astype(x.dtype)
            rows = jax.lax.dynamic_update_slice(rows, dec, (rank, 0))
            return rows.reshape(-1)[:n]
        chunk = reducers._chunk_size(n, WS)
        rows = reducers._pad_rows(x, WS, chunk)
        q = reducers._quantize_rows(
            rows, cc, reducers._phase_key(key, 1, "dp")
        )
        vals = reducers._dequantize_rows(q)
        own = (jnp.arange(WS) == jax.lax.axis_index("dp"))[:, None]
        vals = jnp.where(own, rows.astype(vals.dtype), vals)
        return vals.reshape(-1)[:n].astype(x.dtype)

    rt_mirror = run_flat(xs, mirror)
    np.testing.assert_array_equal(rt, rt_mirror)
    # and the residual is genuinely nonzero for quantized wires
    assert np.abs(rt - xs).max() > 0
