"""Whole-step mega-schedule planner (ISSUE 12 — ``parallel/planner.py``).

Covers the cost model (calibration from synthetic span files, prediction
shape), the joint solve (production solver == brute force on small
instances), the plan LRU (keying, hit/miss accounting, invalidation
through BOTH ``allreduce.invalidate_layout_cache`` and
``supervisor.invalidate_trace_caches``), knob-off inertness (jaxpr- and
value-identity with ``CGX_PLANNER`` unset/off), idempotent re-planning
(unchanged telemetry => no version bump, no retrace), and the e2e
2-device contract: the planner's staged program is bit-equal (and
jaxpr-equal) to the equivalent static-knob run, on both the tree plane
and the eager donated-buffer plane.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.config import CompressionConfig
from torch_cgx_tpu.parallel import planner, schedule
from torch_cgx_tpu.parallel.allreduce import (
    allreduce_tree,
    invalidate_layout_cache,
)
from torch_cgx_tpu.utils.compat import shard_map

BUCKET = 512


@pytest.fixture(autouse=True)
def _fresh_planner_state():
    planner.set_cost_model(None)
    planner._PLAN_VERSION = 0
    planner.plan_cache_clear()
    schedule.schedule_cache_clear()
    yield
    planner.set_cost_model(None)
    planner._PLAN_VERSION = 0
    planner.plan_cache_clear()
    schedule.schedule_cache_clear()


def _cc(bits=4):
    return CompressionConfig(bits=bits, bucket_size=BUCKET)


# ---------------------------------------------------------------------------
# Cost model: calibration + prediction shape.
# ---------------------------------------------------------------------------


def test_cost_model_calibrates_from_synthetic_spans(tmp_path):
    """Codec spans set the rates in f32-INPUT-byte units (from their
    ``elems`` f32 counts — their ``bytes`` field is wire bytes, ~bits/32
    of the input, and must not set the rate), wire spans the link rate,
    wait spans the per-chunk overhead, and the collective/compute
    interval overlap sets overlap_frac — the same measurement cgx_trace
    attribution reports."""
    rows = [
        {"kind": "meta", "rank": 0},
        # 5e8 f32 elems in 1 s => 2.0 GB/s of f32 input; the wire-byte
        # field is ~8x smaller and must be ignored for the rate.
        {"kind": "span", "name": "codec.compress", "cat": "quantize",
         "t_mono": 0.0, "dur_s": 1.0, "elems": 5e8, "bytes": 2.5e8},
        {"kind": "span", "name": "codec.decompress", "cat": "quantize",
         "t_mono": 1.0, "dur_s": 0.5, "elems": 5e8, "bytes": 2.5e8},
        # the fused epilogue pair is not attributable to either rate
        {"kind": "span", "name": "codec.sra_epilogue", "cat": "quantize",
         "t_mono": 2.0, "dur_s": 9.0, "elems": 9e9, "bytes": 9e9},
        {"kind": "span", "name": "shm.put", "cat": "wire",
         "t_mono": 1.0, "dur_s": 1.0, "bytes": 5e8},
        {"kind": "span", "name": "shm.take.wait", "cat": "wait",
         "t_mono": 2.0, "dur_s": 0.01},
        {"kind": "span", "name": "allreduce", "cat": "collective",
         "t_mono": 0.0, "dur_s": 1.0},
        {"kind": "span", "name": "backward", "cat": "span",
         "t_mono": 0.5, "dur_s": 1.0},
        {"kind": "instant", "name": "noise", "cat": "trace",
         "t_mono": 0.1},
    ]
    path = tmp_path / "spans-rank0.jsonl"
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        f.write('{"kind": "span", "torn tail')  # killed writer
    m = planner.CostModel.from_spans(str(tmp_path))
    assert m.quantize_gbps == pytest.approx(2.0)
    assert m.dequantize_gbps == pytest.approx(4.0)
    assert m.wire_gbps == pytest.approx(0.5)
    # mean WAIT-span duration (wire spans are rate-bearing, not overhead)
    assert m.chunk_overhead_s == pytest.approx(0.01)
    # collective [0,1) overlaps compute [0.5,1.5) for 0.5 of 1.0
    assert m.overlap_frac == pytest.approx(0.5)
    assert "codec" in m.source and "overlap" in m.source


def test_cost_model_overlap_is_per_rank(tmp_path):
    """Overlap is measured PER RANK then averaged — pooling would let
    rank B's concurrent compute blanket rank A's collectives (SPMD ranks
    share the clock, so pooled overlap is ~always ~1.0)."""
    # rank 0: collective [0,1), own compute [10,11) — zero overlap
    with open(tmp_path / "spans-rank0.jsonl", "w") as f:
        f.write(json.dumps({"kind": "span", "name": "ar", "cat": "collective",
                            "t_mono": 0.0, "dur_s": 1.0}) + "\n")
        f.write(json.dumps({"kind": "span", "name": "c", "cat": "span",
                            "t_mono": 10.0, "dur_s": 1.0}) + "\n")
    # rank 1: compute [0,1) — would fully blanket rank 0's collective
    # if intervals were pooled across ranks
    with open(tmp_path / "spans-rank1.jsonl", "w") as f:
        f.write(json.dumps({"kind": "span", "name": "c", "cat": "span",
                            "t_mono": 0.0, "dur_s": 1.0}) + "\n")
    m = planner.CostModel.from_spans(str(tmp_path))
    assert m.overlap_frac == pytest.approx(0.0)


def test_cost_model_empty_dir_keeps_defaults(tmp_path):
    m = planner.CostModel.from_spans(str(tmp_path))
    assert m == dataclasses.replace(
        planner.CostModel.default(), source=m.source
    )


def test_predict_slice_shape():
    m = planner.CostModel.default()
    n = 1 << 22
    t1 = m.predict_slice(n, 4, 4, BUCKET, chunks=1)
    t4 = m.predict_slice(n, 4, 4, BUCKET, chunks=4)
    # pipelining a large slice hides the non-bottleneck stage
    assert t4 < t1
    # a tiny slice only pays the per-chunk overhead
    assert m.predict_slice(4096, 4, 4, BUCKET, chunks=4) > \
        m.predict_slice(4096, 4, 4, BUCKET, chunks=1)
    # raw (32-bit) slices carry no codec cost but full wire bytes
    raw = m.predict_slice(n, 4, 32, BUCKET, chunks=1)
    assert raw > 0
    assert m.wire_bytes(n, 32, BUCKET) == 4.0 * n
    assert m.wire_bytes(n, 4, BUCKET) < 4.0 * n
    # ws=1 has no wire at all
    assert m.predict_slice(n, 1, 32, BUCKET) == 0.0


def test_predict_step_overlap_credit():
    m = dataclasses.replace(
        planner.CostModel.default(), overlap_frac=0.5, compute_s=1.0
    )
    coll = [0.4, 0.2]
    assert m.predict_step(coll) == pytest.approx(1.0 + 0.6 - 0.5 * 0.6)
    assert m.predict_step(coll, reverse_order=False) == pytest.approx(1.6)


# ---------------------------------------------------------------------------
# Joint solve == brute force on small instances.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("overhead_us", [5, 100, 2000])
def test_solve_matches_bruteforce(overhead_us):
    model = dataclasses.replace(
        planner.CostModel.default(), chunk_overhead_s=overhead_us * 1e-6
    )
    slices = [
        (1 << 22, _cc(4)),
        (1 << 18, _cc(8)),
        (4096, _cc(4)),
        (1 << 20, CompressionConfig(bits=32)),  # raw: never pipelines
    ]
    got = planner.solve(slices, 4, model=model)
    ref = planner.solve_bruteforce(slices, 4, model=model)
    assert [(d.chunks, d.bits) for d in got] == [
        (d.chunks, d.bits) for d in ref
    ]
    # raw slice pinned to depth 1
    assert got[3].chunks == 1
    # predicted costs agree too
    for a, b in zip(got, ref):
        assert a.predicted_s == pytest.approx(b.predicted_s)


def test_solve_bit_budget_reallocates():
    """CGX_PLANNER_AVG_BITS: the payload-weighted marginal allocation
    (the WireController's solver, planner-driven) gives big slices fewer
    bits and small slices more, averaging to the budget."""
    model = planner.CostModel.default()
    slices = [(1 << 22, _cc(4)), (1 << 14, _cc(4))]
    decs = planner.solve(slices, 4, model=model, avg_bits=4)
    total = sum(d.n for d in decs)
    avg = sum(d.bits * d.n for d in decs) / total
    assert avg <= 4 + 1e-6
    assert all(
        planner.BITS_RANGE[0] <= d.bits <= planner.BITS_RANGE[1]
        for d in decs
    )


# ---------------------------------------------------------------------------
# Plan LRU: keying + invalidation through both entry points.
# ---------------------------------------------------------------------------


def _groups(n=1 << 22, bits=4):
    return [planner._OneGroup(cc=_cc(bits), slices=((0, n),))]


def test_plan_lru_hits_and_registry_keying(monkeypatch):
    monkeypatch.setenv("CGX_PLANNER", "on")
    g = _groups()
    p1 = planner.plan_for_layout(g, 4, route="staged", reduction="SRA")
    assert p1 is not None
    p2 = planner.plan_for_layout(g, 4, route="staged", reduction="SRA")
    assert p2 is p1
    stats = planner.plan_cache_stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    # a registry bump (re-registration) must re-derive, never hit stale
    cgx_config.set_layer_pattern_config(".*", _cc(4))
    planner.plan_for_layout(g, 4, route="staged", reduction="SRA")
    assert planner.plan_cache_stats()["misses"] == 2


def test_plan_gates(monkeypatch):
    monkeypatch.setenv("CGX_PLANNER", "on")
    assert planner.plan_for_layout(_groups(), 1, route="staged",
                                   reduction="SRA") is None
    assert planner.plan_for_layout(_groups(), 4, route="staged",
                                   reduction="RING") is None
    raw = [planner._OneGroup(cc=CompressionConfig(bits=32),
                             slices=((0, 4096),))]
    assert planner.plan_for_layout(raw, 4, route="staged",
                                   reduction="SRA") is None
    monkeypatch.setenv("CGX_DEBUG_DUMMY_COMPRESSION", "1")
    assert planner.plan_for_layout(_groups(), 4, route="staged",
                                   reduction="SRA") is None


def test_invalidation_through_layout_cache(monkeypatch):
    monkeypatch.setenv("CGX_PLANNER", "on")
    planner.plan_for_layout(_groups(), 4, route="staged", reduction="SRA")
    assert len(planner._PLAN_CACHE) == 1
    invalidate_layout_cache("test")
    assert len(planner._PLAN_CACHE) == 0


def test_invalidation_through_supervisor(monkeypatch):
    from torch_cgx_tpu.robustness import supervisor

    monkeypatch.setenv("CGX_PLANNER", "on")
    planner.plan_for_layout(_groups(), 4, route="staged", reduction="SRA")
    assert len(planner._PLAN_CACHE) == 1
    supervisor.invalidate_trace_caches()
    assert len(planner._PLAN_CACHE) == 0


def test_decide_slice_respects_engagement(monkeypatch):
    monkeypatch.setenv("CGX_PLANNER", "off")
    assert planner.decide_slice(1 << 22, 4, _cc(), "SRA") is None
    monkeypatch.delenv("CGX_PLANNER", raising=False)
    if jax.default_backend() != "tpu":  # auto = TPU only
        assert planner.decide_slice(1 << 22, 4, _cc(), "SRA") is None
    monkeypatch.setenv("CGX_PLANNER", "on")
    dec = planner.decide_slice(1 << 22, 4, _cc(), "SRA")
    assert dec is not None and dec.chunks >= 2


def test_backend_bridge_mirror_matches_planner(monkeypatch):
    """The bridge keeps a dependency-light duplicate of the DEFAULT-model
    depth argmin (``backend._plan_bridge_chunks`` — a pure-bridge rank
    must derive the same depth as a JAX-side rank, or mixed groups frame
    the collective differently and wedge); pinned here like the
    ``_sched_chunk_table`` duplicate."""
    from torch_cgx_tpu.torch_backend import backend as be

    monkeypatch.setenv("CGX_PLANNER", "on")
    for width in (0, 4096, 1 << 18, 1 << 20, 1 << 23):
        for ws in (1, 2, 4, 8):
            for bits in (2, 4, 8, 32):
                assert be._plan_bridge_chunks(
                    width, BUCKET, ws, bits
                ) == planner.bridge_chunks(
                    width, BUCKET, ws, bits, default=0
                ) or (width <= 0 or ws <= 1), (width, ws, bits)


def test_cost_model_file_resolution(tmp_path, monkeypatch):
    """CGX_PLANNER_MODEL: the persisted calibrated model wins over the
    default (but not over an in-process install), re-reads on file
    change, and a bad file falls back to default instead of crashing a
    decision site."""
    m = dataclasses.replace(
        planner.CostModel.default(), quantize_gbps=3.5, source="cal"
    )
    path = tmp_path / "model.json"
    m.save(str(path))
    monkeypatch.setenv("CGX_PLANNER_MODEL", str(path))
    assert planner.cost_model().quantize_gbps == 3.5
    # in-process install wins
    planner.set_cost_model(planner.CostModel.default())
    assert planner.cost_model().quantize_gbps == planner.CostModel.quantize_gbps
    planner.set_cost_model(None)
    # bad file: fall back, never raise
    path.write_text("{not json")
    # (stat cache keys on mtime; a rewrite is a new key)
    assert planner.cost_model() == planner.CostModel.default()


def test_backend_mirror_honors_model_file(tmp_path, monkeypatch):
    """The bridge mirror reads the SAME CGX_PLANNER_MODEL bytes the
    JAX-side planner loads — calibrated depth decisions stay
    group-consistent between pure-bridge and JAX-side ranks."""
    from torch_cgx_tpu.torch_backend import backend as be

    monkeypatch.setenv("CGX_PLANNER", "on")
    # a model with brutal per-chunk overhead must force depth 1 on both
    m = dataclasses.replace(
        planner.CostModel.default(), chunk_overhead_s=10.0, source="cal"
    )
    path = tmp_path / "model.json"
    m.save(str(path))
    monkeypatch.setenv("CGX_PLANNER_MODEL", str(path))
    width = 1 << 21
    assert be._plan_bridge_chunks(width, BUCKET, 4, 4) == 1
    assert planner.bridge_chunks(width, BUCKET, 4, 4, default=0) == 1
    # and without the file the default model pipelines this width
    monkeypatch.delenv("CGX_PLANNER_MODEL")
    assert be._plan_bridge_chunks(width, BUCKET, 4, 4) > 1


def test_bridge_chunks_engagement(monkeypatch):
    # bridge plane honors explicit "on" only (host plane: auto-means-TPU
    # cannot apply) and falls back to the caller's default otherwise
    monkeypatch.setenv("CGX_PLANNER", "on")
    c = planner.bridge_chunks(1 << 20, BUCKET, 4, 4, default=7)
    assert c >= 1 and c != 7
    monkeypatch.delenv("CGX_PLANNER", raising=False)
    assert planner.bridge_chunks(1 << 20, BUCKET, 4, 4, default=7) == 7
    monkeypatch.setenv("CGX_PLANNER", "off")
    assert planner.bridge_chunks(1 << 20, BUCKET, 4, 4, default=7) == 7


# ---------------------------------------------------------------------------
# Idempotent re-plan.
# ---------------------------------------------------------------------------


def test_replan_idempotent_and_adopts_on_change(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_PLANNER", "on")
    plr = planner.StepPlanner(every=2, spans_dir=str(tmp_path))
    # no telemetry at all: recalibration yields the default model — the
    # FIRST update is already a no-op (no version bump, no cache drop)
    v0 = planner._PLAN_VERSION
    planner.plan_for_layout(_groups(), 4, route="staged", reduction="SRA")
    assert plr.update() is False
    assert planner._PLAN_VERSION == v0
    assert len(planner._PLAN_CACHE) == 1  # no retrace storm
    # telemetry appears: adopt ONCE, then no-op again
    with open(tmp_path / "spans-rank0.jsonl", "w") as f:
        f.write(json.dumps({
            "kind": "span", "name": "codec.compress", "cat": "quantize",
            "t_mono": 0.0, "dur_s": 1.0, "elems": 7.5e8,
        }) + "\n")
    assert plr.update() is True
    assert planner._PLAN_VERSION == v0 + 1
    assert len(planner._PLAN_CACHE) == 0
    assert plr.update() is False
    assert planner._PLAN_VERSION == v0 + 1
    # step() cadence: every 2nd call updates
    assert plr.step() is False
    assert plr.step() is True


def test_cache_key_component_tracks_mode_and_version(monkeypatch):
    monkeypatch.setenv("CGX_PLANNER", "on")
    k1 = planner.cache_key_component()
    monkeypatch.setenv("CGX_PLANNER", "off")
    k2 = planner.cache_key_component()
    assert k1 != k2
    monkeypatch.setenv("CGX_PLANNER", "on")
    planner._PLAN_VERSION += 1
    assert planner.cache_key_component() != k1


# ---------------------------------------------------------------------------
# Inertness + e2e bit-equality (2-device run).
# ---------------------------------------------------------------------------

WS = 2
N = 1 << 21  # large enough that the default model picks depth > 1


def _mesh(ws=WS):
    return Mesh(np.asarray(jax.devices()[:ws]), ("dp",))


def _make_sm(mesh):
    def body(t):
        return allreduce_tree(
            {"a": t["a"][0].reshape(1024, -1)}, mesh=mesh, axes=("dp",)
        )["a"]

    return shard_map(
        body, mesh=mesh, in_specs=({"a": P("dp")},), out_specs=P(),
        check_vma=False,
    )


def _tree(mesh):
    rng = np.random.default_rng(0)
    return {
        "a": jax.device_put(
            jnp.asarray(rng.normal(size=(WS, N)), jnp.float32),
            NamedSharding(mesh, P("dp")),
        )
    }


def test_planner_unset_and_off_stage_identical_program(monkeypatch):
    """CGX_PLANNER unset ⇒ jaxpr-identical to off (and therefore to
    HEAD): the planner's inertness contract on every CPU/CI path."""
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    mesh = _mesh()
    tree = _tree(mesh)
    j_unset = str(jax.make_jaxpr(_make_sm(mesh))(tree))
    monkeypatch.setenv("CGX_PLANNER", "off")
    j_off = str(jax.make_jaxpr(_make_sm(mesh))(tree))
    assert j_unset == j_off


def test_planner_e2e_bit_equal_to_static_knobs(monkeypatch):
    """The acceptance pin: the planner's staged program (tree plane) is
    jaxpr-equal AND bit-equal to the static-knob run at the planner's
    own chosen depth — the planner picks knobs, never changes bytes."""
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    mesh = _mesh()
    tree = _tree(mesh)
    monkeypatch.setenv("CGX_PLANNER", "on")
    dec = planner.decide_slice(N, WS, _cc(), "SRA")
    assert dec is not None and dec.chunks >= 2
    j_plan = str(jax.make_jaxpr(_make_sm(mesh))(tree))
    out_plan = np.asarray(jax.jit(_make_sm(mesh))(tree))
    monkeypatch.delenv("CGX_PLANNER")
    j_base = str(jax.make_jaxpr(_make_sm(mesh))(tree))
    assert j_plan != j_base  # the plan actually pipelined
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    monkeypatch.setenv("CGX_SCHED_CHUNKS", str(dec.chunks))
    schedule.schedule_cache_clear()
    j_static = str(jax.make_jaxpr(_make_sm(mesh))(tree))
    out_static = np.asarray(jax.jit(_make_sm(mesh))(tree))
    assert j_plan == j_static
    np.testing.assert_array_equal(out_plan, out_static)


def test_planned_eager_program_bit_equal_and_donates(monkeypatch):
    """The eager donated-buffer plane: ``planned_allreduce`` output is
    bit-equal to ``staged_allreduce`` under the equivalent static knobs,
    and the planner program really donates its input stack."""
    from torch_cgx_tpu.parallel import xla_allreduce as xm

    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    mesh = _mesh()
    rng = np.random.default_rng(1)
    per = np.asarray(rng.normal(size=(WS, N)), np.float32)
    monkeypatch.setenv("CGX_PLANNER", "on")
    dec = planner.decide_slice(N, WS, _cc(), "SRA")
    assert dec is not None
    arr = jax.device_put(per, NamedSharding(mesh, P("dp")))
    out_plan = np.asarray(
        planner.planned_allreduce(arr, mesh=mesh, axis="dp", cc=_cc())
    )
    # donated: the input buffer was consumed by the planner program
    assert arr.is_deleted()
    monkeypatch.delenv("CGX_PLANNER")
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    monkeypatch.setenv("CGX_SCHED_CHUNKS", str(dec.chunks))
    schedule.schedule_cache_clear()
    out_static = np.asarray(
        xm.staged_allreduce(per, mesh=mesh, axis="dp", cc=_cc())
    )
    np.testing.assert_array_equal(out_plan, out_static)


def test_planner_values_invariant_under_engagement(monkeypatch):
    """Values are schedule-invariant by the bit-equality contract: the
    planner on vs fully off produces identical reduced bytes (the
    deterministic encode)."""
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    mesh = _mesh()
    tree = _tree(mesh)
    out_base = np.asarray(jax.jit(_make_sm(mesh))(tree))
    monkeypatch.setenv("CGX_PLANNER", "on")
    out_plan = np.asarray(jax.jit(_make_sm(mesh))(tree))
    np.testing.assert_array_equal(out_base, out_plan)


def test_train_step_cache_keys_planner(monkeypatch):
    """make_train_step's build cache keys the planner component: a mode
    flip or an adopted re-plan retraces; nothing else does."""
    import optax

    from torch_cgx_tpu.parallel.grad_sync import make_train_step
    from torch_cgx_tpu.utils.logging import metrics

    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    mesh = _mesh()

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"]) ** 2)

    params = {"w": jnp.ones((8, 4), jnp.float32)}
    opt = optax.sgd(1e-2)
    opt_state = opt.init(params)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    batch = {"x": jnp.ones((WS * 2, 8), jnp.float32)}
    before = metrics.get("cgx.trace.train_step_builds")
    step(params, opt_state, batch, 0)
    mid = metrics.get("cgx.trace.train_step_builds")
    assert mid == before + 1
    step(params, opt_state, batch, 1)
    assert metrics.get("cgx.trace.train_step_builds") == mid
    monkeypatch.setenv("CGX_PLANNER", "on")
    step(params, opt_state, batch, 2)
    assert metrics.get("cgx.trace.train_step_builds") == mid + 1
