"""Mixture-of-Experts / expert-parallelism tests (subsystem absent from the
reference — SURVEY.md §2.3 — designed fresh; see parallel/moe.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torch_cgx_tpu.models import GPT2, GPT2Config, lm_loss
from torch_cgx_tpu.utils.compat import set_mesh
from torch_cgx_tpu.parallel.moe import MoEMlp, aux_loss, moe_param_spec


def _init(module, x, seed=0):
    return module.init(jax.random.PRNGKey(seed), x)


def test_single_expert_matches_manual_ffn():
    """E=1, k=1, ample capacity: routing is the identity, so the MoE output
    must equal the expert FFN applied densely."""
    m = MoEMlp(d_model=16, n_experts=1, top_k=1, capacity_factor=4.0,
               dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 16)),
                    jnp.float32)
    params = _init(m, x)
    y = m.apply(params, x)
    p = params["params"]
    h = jax.nn.gelu(
        x.reshape(-1, 16) @ p["experts_in"][0] + p["experts_in_bias"][0]
    )
    want = h @ p["experts_out"][0] + p["experts_out_bias"][0]
    np.testing.assert_allclose(
        np.asarray(y).reshape(-1, 16), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_gates_and_shapes():
    m = MoEMlp(d_model=32, n_experts=4, top_k=2, dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 16, 32)),
                    jnp.float32)
    params = _init(m, x)
    y = m.apply(params, x)
    assert y.shape == x.shape
    assert jnp.isfinite(y).all()


def test_capacity_truncation_drops_tokens():
    """With capacity << tokens/expert, overflowing tokens must produce ZERO
    output (they ride the residual), not garbage."""
    m = MoEMlp(d_model=8, n_experts=2, top_k=1, capacity_factor=1e-6,
               dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(1, 32, 8)),
                    jnp.float32)
    params = _init(m, x)
    y = np.asarray(m.apply(params, x))[0]  # (32, 8)
    # capacity = 1 slot per expert -> at most 2 tokens (one per expert)
    # produce nonzero output.
    nonzero = (np.abs(y).max(axis=-1) > 1e-9).sum()
    assert nonzero <= 2, nonzero


def test_aux_loss_sown_and_differentiable():
    m = MoEMlp(d_model=16, n_experts=4, dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 8, 16)),
                    jnp.float32)
    params = _init(m, x)

    def loss(p):
        y, inter = m.apply(p, x, mutable=["intermediates"])
        return jnp.sum(y**2) + 0.01 * aux_loss(inter["intermediates"])

    val, grads = jax.value_and_grad(loss)(params)
    assert jnp.isfinite(val)
    g_router = grads["params"]["router"]
    assert float(jnp.abs(g_router).max()) > 0, "router got no gradient"
    # Aux loss for a 4-expert layer is >= 1 at balance, > 0 always.
    _, inter = m.apply(params, x, mutable=["intermediates"])
    assert float(aux_loss(inter["intermediates"])) > 0


def test_ep_sharded_matches_unsharded():
    """Expert-parallel execution over an 8-device 'ep' mesh axis must match
    the single-device result (GSPMD inserts the dispatch all_to_alls)."""
    m = MoEMlp(d_model=16, n_experts=8, top_k=2, dtype=jnp.float32,
               ep_axis="ep")
    x = jnp.asarray(np.random.default_rng(4).normal(size=(2, 16, 16)),
                    jnp.float32)
    params = _init(m, x)
    want = m.apply(params, x)

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("ep",))
    from torch_cgx_tpu.utils.tree import path_str

    def shard_leaf(path, leaf):
        spec = moe_param_spec(path_str(path), leaf) or P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    sharded_params = jax.tree_util.tree_map_with_path(shard_leaf, params)
    x_sh = jax.device_put(x, NamedSharding(mesh, P()))
    with set_mesh(mesh):
        got = jax.jit(m.apply)(sharded_params, x_sh)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_gpt2_moe_forward_and_grad():
    cfg = GPT2Config.tiny(n_experts=4, moe_top_k=2)
    model = GPT2(cfg)
    tokens = jnp.zeros((2, 32), jnp.int32).at[:, 1:].set(
        jnp.asarray(np.random.default_rng(5).integers(0, 512, (2, 31)))
    )
    params = model.init(jax.random.PRNGKey(0), tokens)
    assert any("moe_mlp" in k for k in params["params"]["h_0"])

    def loss(p):
        return lm_loss(model.apply(p, tokens), tokens)

    val, grads = jax.value_and_grad(loss)(params)
    assert jnp.isfinite(val)
    g = grads["params"]["h_0"]["moe_mlp"]["experts_in"]
    assert float(jnp.abs(g).max()) > 0
