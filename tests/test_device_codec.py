"""Bridge <-> accelerator codec interop: frames encoded on either side must
be byte-identical (deterministic mode) and decode on the other (VERDICT r2
#5: the reference runs its codec on the device holding the gradients,
ProcessGroupCGX.cc:374-407; this is the TPU-host analogue via DLPack
staging into the jitted JAX codec)."""

import numpy as np
import pytest

from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.ops import codec_host as hcodec
from torch_cgx_tpu.torch_backend import device_codec


@pytest.fixture(autouse=True)
def _force_on(monkeypatch):
    # CPU suite: force the device path (auto only engages on real TPU).
    monkeypatch.setenv(cgx_config.BRIDGE_DEVICE_CODEC, "on")
    monkeypatch.setenv(cgx_config.BRIDGE_DEVICE_MIN_NUMEL, "1")


@pytest.mark.parametrize("bits,bucket,n", [(4, 512, 4096), (2, 128, 50_000), (8, 512, 512)])
def test_device_encode_matches_host_bytes(bits, bucket, n):
    x = np.random.default_rng(bits).normal(size=n).astype(np.float32)
    wire_dev = device_codec.quantize(x, bits, bucket)
    q_host = hcodec.quantize(x, bits, bucket)
    wire_host = q_host.to_bytes().tobytes()
    assert wire_dev == wire_host


def test_host_encode_device_decode_roundtrip():
    n, bits, bucket = 20_000, 4, 512
    x = np.random.default_rng(1).normal(size=n).astype(np.float32)
    wire = hcodec.quantize(x, bits, bucket).to_bytes()
    y_dev = device_codec.dequantize(wire, n, bits, bucket)
    y_host = hcodec.dequantize(
        hcodec.from_bytes(wire, n, bits, bucket, np.float32),
        out_dtype=np.float32,
    )
    # device decode is XLA (FMA) vs host mul+add: 1 ulp
    np.testing.assert_allclose(y_dev, y_host, rtol=2e-6, atol=5e-7)


def test_device_encode_host_decode_bf16_meta():
    import ml_dtypes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    n, bits, bucket = 8192, 4, 512
    x = np.random.default_rng(2).normal(size=n).astype(np.float32)
    wire = device_codec.quantize(x, bits, bucket, meta_dtype=bf16)
    assert len(wire) == hcodec.wire_layout(n, bits, bucket, bf16)[3]
    q = hcodec.from_bytes(
        np.frombuffer(wire, np.uint8), n, bits, bucket, bf16
    )
    y = hcodec.dequantize(q, out_dtype=np.float32)
    xb = x.reshape(-1, bucket)
    unit = (xb.max(1) - xb.min(1)) / ((1 << bits) - 1)
    err = np.abs(y - x).reshape(-1, bucket).max(1)
    assert (err <= unit * 1.01 + 1e-6).all()


def test_compress_frames_routes_through_device(monkeypatch):
    """The bridge's framing must actually take the device path when enabled
    (poisoned host codec proves routing), and its bytes must equal the host
    path's."""
    from torch_cgx_tpu.torch_backend.backend import _Segment, _compress_frames

    n, bits, bucket = 4096, 4, 512
    fused = np.random.default_rng(3).normal(size=n).astype(np.float32)
    segs = [_Segment(0, n, bits, bucket)]
    want = _compress_frames(fused, segs, False, None)  # device path (forced on)

    monkeypatch.setenv(cgx_config.BRIDGE_DEVICE_CODEC, "off")
    host = _compress_frames(fused, segs, False, None)
    assert want == host

    monkeypatch.setenv(cgx_config.BRIDGE_DEVICE_CODEC, "on")

    def _boom(*a, **k):
        raise AssertionError("expected the device codec, got the host codec")

    monkeypatch.setattr(
        "torch_cgx_tpu.torch_backend.backend.hcodec.quantize", _boom
    )
    again = _compress_frames(fused, segs, False, None)
    assert again == want


def test_small_segments_stay_on_host(monkeypatch):
    monkeypatch.setenv(cgx_config.BRIDGE_DEVICE_MIN_NUMEL, "1000000")
    assert not device_codec.enabled(4096)
    monkeypatch.setenv(cgx_config.BRIDGE_DEVICE_MIN_NUMEL, "1")
    assert device_codec.enabled(4096)


def test_stochastic_device_encode_envelope():
    n, bits, bucket = 16384, 4, 512
    x = np.random.default_rng(5).normal(size=n).astype(np.float32)
    wire = device_codec.quantize(x, bits, bucket, stochastic_seed=42)
    y = device_codec.dequantize(wire, n, bits, bucket)
    xb = x.reshape(-1, bucket)
    unit = (xb.max(1) - xb.min(1)) / ((1 << bits) - 1)
    err = np.abs(y - x).reshape(-1, bucket).max(1)
    assert (err <= unit * 1.01 + 1e-6).all()
