"""Unified wire plane (ISSUE 10): per-edge registry, dispatcher,
closed-loop controller, knob-off inertness, and the end-to-end
MoE + ring-attention + pipelined acceptance runs."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import torch_cgx_tpu as cgx
from torch_cgx_tpu import CompressionConfig
from torch_cgx_tpu.parallel.moe import ep_combine, ep_dispatch
from torch_cgx_tpu.parallel.pipeline import (
    merge_microbatches,
    spmd_pipeline,
    split_microbatches,
    stack_stage_params,
)
from torch_cgx_tpu.parallel.ring_attention import ring_attention
from torch_cgx_tpu.utils.compat import shard_map
from torch_cgx_tpu.utils.logging import metrics
from torch_cgx_tpu.wire import (
    EdgeConfig,
    WireController,
    dispatch as wdisp,
    edges as wedges,
)


@pytest.fixture(autouse=True)
def _clean_wire_state():
    wedges.clear_edges()
    wedges.reset_edge_state("test setup")
    metrics.reset()
    yield
    wedges.clear_edges()
    wedges.reset_edge_state("test teardown")


def _mesh(n, name="d"):
    return Mesh(np.asarray(jax.devices()[:n]), (name,))


def _ring_perm(ws):
    return [(i, (i + 1) % ws) for i in range(ws)]


# ---------------------------------------------------------------------------
# Edge registry.
# ---------------------------------------------------------------------------


def test_registry_later_registration_wins_and_version_bumps():
    v0 = cgx.config.registry_version()
    wedges.set_edge_config("ring_kv", ".*", EdgeConfig(cc=CompressionConfig(bits=8)))
    wedges.set_edge_config(
        "ring_kv", "^special$", EdgeConfig(cc=CompressionConfig(bits=2))
    )
    assert cgx.config.registry_version() > v0
    assert wedges.resolve_edge("ring_kv", "other").cc.bits == 8
    assert wedges.resolve_edge("ring_kv", "special").cc.bits == 2
    # unregistered kind resolves to nothing
    assert wedges.resolve_edge("pp_act", "special") is None


def test_registry_env_default_bits_cover_non_dp_edges(monkeypatch):
    assert wedges.resolve_edge("moe_a2a", "x") is None
    monkeypatch.setenv("CGX_WIRE_BITS", "6")
    ec = wedges.resolve_edge("moe_a2a", "x")
    assert ec is not None and ec.cc.bits == 6
    # dp_grad keeps its own env default (CGX_COMPRESSION_QUANTIZATION_BITS)
    assert wedges.resolve_edge("dp_grad", "layer/kernel") is None


def test_registry_backfills_env_defaults(monkeypatch):
    monkeypatch.setenv("CGX_COMPRESSION_BUCKET_SIZE", "128")
    wedges.set_edge_config(
        "pp_act", ".*", EdgeConfig(cc=CompressionConfig(bits=4, bucket_size=0))
    )
    assert wedges.resolve_edge("pp_act", "pipeline.act").cc.bucket_size == 128


def test_registry_validation():
    with pytest.raises(ValueError):
        wedges.set_edge_config("not_a_kind", ".*", EdgeConfig())
    with pytest.raises(ValueError):
        EdgeConfig(compressor="zstd")
    with pytest.raises(ValueError):
        EdgeConfig(ratio=1.5)
    with pytest.raises(TypeError):
        wedges.set_edge_config("pp_act", ".*", CompressionConfig(bits=4))


def test_dp_grad_edge_wins_over_pattern_registry(monkeypatch):
    from torch_cgx_tpu.parallel.allreduce import resolve_leaf_config

    leaf = jnp.zeros((64, 64), jnp.float32)
    cgx.set_layer_pattern_config(".*kernel.*", CompressionConfig(bits=8))
    assert resolve_leaf_config("h0/kernel", leaf).bits == 8
    wedges.set_edge_config(
        "dp_grad", ".*kernel.*", EdgeConfig(cc=CompressionConfig(bits=3))
    )
    # dp_grad edges obey the same CGX_WIRE gate as every other kind:
    # disengaged (unset on CPU / off), the entry is inert and the legacy
    # pattern registry still answers — the knob can bisect.
    assert resolve_leaf_config("h0/kernel", leaf).bits == 8
    monkeypatch.setenv("CGX_WIRE", "off")
    assert resolve_leaf_config("h0/kernel", leaf).bits == 8
    monkeypatch.setenv("CGX_WIRE", "on")
    assert resolve_leaf_config("h0/kernel", leaf).bits == 3
    # non-matching leaves fall through to the pattern registry / default
    assert resolve_leaf_config("h0/bias_matrix", leaf).bits == 32


# ---------------------------------------------------------------------------
# Knob-off inertness: with CGX_WIRE unset (conftest clears env) and the
# registry empty, every routed call site lowers to the plain collective.
# ---------------------------------------------------------------------------


def test_unset_wire_ppermute_bit_identical():
    ws = 4
    mesh = _mesh(ws)
    perm = _ring_perm(ws)
    x = np.random.default_rng(0).normal(size=(ws, 256)).astype(np.float32)

    def via_wire(xs):
        return wdisp.wire_ppermute(xs, "d", perm, kind="ring_kv", name="t")

    def plain(xs):
        return lax.ppermute(xs, "d", perm)

    sh = dict(mesh=mesh, in_specs=P("d"), out_specs=P("d"), check_vma=False)
    got = jax.jit(shard_map(via_wire, **sh))(x)
    want = jax.jit(shard_map(plain, **sh))(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def _ring_jaxpr():
    ws = 2
    mesh = _mesh(ws)
    q = jnp.ones((1, 2, 4, 4), jnp.float32)

    def body(qq):
        return ring_attention(qq, qq, qq, axis_name="d")

    return str(
        jax.make_jaxpr(
            shard_map(
                body, mesh=mesh, in_specs=P(None, None, "d"),
                out_specs=P(None, None, "d"), check_vma=False,
            )
        )(q)
    )


def _pipeline_jaxpr():
    ws = 4
    mesh = _mesh(ws, "pp")
    stages = [
        {"w": jnp.eye(8, dtype=jnp.float32)} for _ in range(ws)
    ]
    stacked = stack_stage_params(stages)
    x = jnp.ones((8, 8), jnp.float32)

    def run(stacked_local, xfull):
        micro = split_microbatches(xfull, 4)
        out = spmd_pipeline(
            lambda p, t: jnp.tanh(t @ p["w"]), stacked_local, micro,
            axis_name="pp", n_stages=ws,
        )
        return merge_microbatches(out)

    return str(
        jax.make_jaxpr(
            shard_map(
                run, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
                check_vma=False,
            )
        )(stacked, x)
    )


def _moe_jaxpr():
    ws = 2
    mesh = _mesh(ws)
    buf = jnp.ones((4, 8, 16), jnp.float32)

    def run(t):
        return ep_combine(ep_dispatch(t, "d"), "d")

    return str(
        jax.make_jaxpr(
            shard_map(run, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
        )(buf)
    )


@pytest.mark.parametrize(
    "jaxpr_fn", [_ring_jaxpr, _pipeline_jaxpr, _moe_jaxpr],
    ids=["ring", "pipeline", "moe"],
)
def test_staged_programs_pinned_with_knob_unset(jaxpr_fn, monkeypatch):
    """unset == off (the knob is the only gate), and flipping it on with a
    registered edge genuinely changes the staged program — proof the
    unset path stages zero wire machinery."""
    unset = jaxpr_fn()
    monkeypatch.setenv("CGX_WIRE", "off")
    assert jaxpr_fn() == unset
    monkeypatch.setenv("CGX_WIRE", "on")
    for kind in ("ring_kv", "pp_act", "moe_a2a"):
        wedges.set_edge_config(kind, ".*", EdgeConfig(cc=CompressionConfig(bits=4)))
    engaged = jaxpr_fn()
    assert engaged != unset
    # zero host callbacks inside the compressed staged program
    assert "callback" not in engaged


# ---------------------------------------------------------------------------
# Dispatcher mechanics.
# ---------------------------------------------------------------------------


def test_quantized_ppermute_edge_within_envelope(monkeypatch):
    monkeypatch.setenv("CGX_WIRE", "on")
    ws, n, bits = 4, 1024, 8
    mesh = _mesh(ws)
    perm = _ring_perm(ws)
    wedges.set_edge_config("ring_kv", ".*", EdgeConfig(cc=CompressionConfig(bits=bits)))
    x = np.random.default_rng(1).normal(size=(ws, n)).astype(np.float32)

    def via_wire(xs):
        return wdisp.wire_ppermute(xs, "d", perm, kind="ring_kv", name="t")

    sh = dict(mesh=mesh, in_specs=P("d"), out_specs=P("d"), check_vma=False)
    got = np.asarray(jax.jit(shard_map(via_wire, **sh))(x))
    want = np.asarray(
        jax.jit(shard_map(lambda xs: lax.ppermute(xs, "d", perm), **sh))(x)
    )
    env = 2.0 * np.abs(x).max() / (2**bits - 1)
    assert not np.array_equal(got, want)
    np.testing.assert_allclose(got, want, atol=env)
    snap = metrics.snapshot("cgx.wire.")
    assert snap.get("cgx.wire.edges_compressed", 0) >= 1
    assert snap.get("cgx.wire.bytes_raw.ring_kv", 0) > 0
    assert 0 < snap["cgx.wire.bytes_wire.ring_kv"] < snap["cgx.wire.bytes_raw.ring_kv"]


def test_edge_error_feedback_residual_mechanics(monkeypatch):
    """EF residual = payload - own wire decode, and carrying it into the
    next hop corrects the quantization bias (mean of repeated hops
    approaches the true value)."""
    monkeypatch.setenv("CGX_WIRE", "on")
    ws, n, bits = 2, 512, 2
    mesh = _mesh(ws)
    perm = _ring_perm(ws)
    wedges.set_edge_config(
        "pp_act", ".*",
        EdgeConfig(cc=CompressionConfig(bits=bits), error_feedback=True),
    )
    x = np.random.default_rng(2).normal(size=(ws, n)).astype(np.float32)

    def hop_ef(xs, e):
        return wdisp.wire_ppermute(
            xs, "d", perm, kind="pp_act", name="t", ef=e
        )

    sh = dict(
        mesh=mesh, in_specs=(P("d"), P("d")), out_specs=(P("d"), P("d")),
        check_vma=False,
    )
    f = jax.jit(shard_map(hop_ef, **sh))
    out, e1 = f(x, np.zeros_like(x))
    # residual == x - decode(quantize(x)): reconstruct from the hop output
    # (the ring shifted device r's decode to device r+1).
    rt = np.roll(np.asarray(out), -1, axis=0)
    np.testing.assert_allclose(np.asarray(e1), x - rt, atol=1e-6)
    assert float(np.abs(np.asarray(e1)).max()) > 0  # 2-bit really lossy
    # EF accumulates: sending the SAME payload repeatedly with the carried
    # residual makes the time-average of the decodes approach x.
    e = np.zeros_like(x)
    acc = np.zeros_like(x)
    steps = 24
    for _ in range(steps):
        out, e = f(x, e)
        acc += np.roll(np.asarray(out), -1, axis=0)
    ef_err = np.abs(acc / steps - x).max()
    one_shot = np.abs(rt - x).max()
    assert ef_err < one_shot * 0.35, (ef_err, one_shot)


def test_raw_edge_passes_ef_through():
    ws = 2
    mesh = _mesh(ws)
    perm = _ring_perm(ws)
    x = np.random.default_rng(3).normal(size=(ws, 64)).astype(np.float32)
    e0 = np.random.default_rng(4).normal(size=(ws, 64)).astype(np.float32)

    def hop_ef(xs, e):
        return wdisp.wire_ppermute(xs, "d", perm, kind="pp_act", name="t", ef=e)

    f = jax.jit(shard_map(
        hop_ef, mesh=mesh, in_specs=(P("d"), P("d")),
        out_specs=(P("d"), P("d")), check_vma=False,
    ))
    out, e1 = f(x, e0)
    np.testing.assert_array_equal(np.asarray(e1), e0)


def test_powersgd_and_topk_peer_compressors(monkeypatch):
    monkeypatch.setenv("CGX_WIRE", "on")
    ws = 2
    mesh = _mesh(ws)
    perm = _ring_perm(ws)
    rng = np.random.default_rng(5)
    # low-rank payload: rank-2 matrix + small noise -> rank-8 factors
    # reconstruct it nearly exactly on the receiving device.
    base = rng.normal(size=(ws, 64, 2)) @ rng.normal(size=(ws, 2, 32))
    x = (base + 0.01 * rng.normal(size=base.shape)).astype(np.float32)
    wedges.set_edge_config(
        "pp_act", "^lowrank$", EdgeConfig(compressor="powersgd", rank=8)
    )
    wedges.set_edge_config(
        "pp_act", "^sparse$", EdgeConfig(compressor="topk", ratio=0.25)
    )

    def hop(xs, name):
        return wdisp.wire_ppermute(xs, "d", perm, kind="pp_act", name=name)

    sh = dict(mesh=mesh, in_specs=P("d"), out_specs=P("d"), check_vma=False)
    got = np.asarray(jax.jit(shard_map(lambda t: hop(t, "lowrank"), **sh))(x))
    want = np.asarray(
        jax.jit(shard_map(lambda t: lax.ppermute(t, "d", perm), **sh))(x)
    )
    rel = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert rel < 0.05, rel

    got_tk = np.asarray(jax.jit(shard_map(lambda t: hop(t, "sparse"), **sh))(x))
    nz = np.abs(got_tk.reshape(ws, -1)) > 0
    assert abs(nz.mean() - 0.25) < 0.02  # exactly the top quarter ships
    # shipped coordinates carry exact values
    mask = np.abs(got_tk) > 0
    np.testing.assert_allclose(got_tk[mask], want[mask], rtol=1e-6)
    # gradient flows straight-through for both
    def loss(t):
        return jnp.sum(hop(t, "lowrank") ** 2)

    g = jax.jit(shard_map(jax.grad(loss), **sh))(x)
    assert np.isfinite(np.asarray(g)).all()


def test_a2a_rejects_p2p_only_compressors(monkeypatch):
    monkeypatch.setenv("CGX_WIRE", "on")
    ws = 2
    mesh = _mesh(ws)
    wedges.set_edge_config(
        "moe_a2a", ".*", EdgeConfig(compressor="topk", ratio=0.1)
    )
    buf = jnp.ones((4, 8, 32), jnp.float32)

    def run(t):
        return ep_dispatch(t, "d")

    with pytest.raises(ValueError, match="p2p-only"):
        jax.jit(shard_map(
            run, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
        ))(buf)


def test_a2a_raw_fallbacks_are_unaccounted(monkeypatch):
    """Every case where the quantized reshard lowers to (or fails like)
    the plain all_to_all must record NO cgx.wire accounting — counters
    claiming compression for raw bytes would mislead cgx_top/cgx_report
    and feed the controller a width that was never used."""
    monkeypatch.setenv("CGX_WIRE", "on")
    ws = 4
    mesh = _mesh(ws)
    wedges.set_edge_config("moe_a2a", ".*", EdgeConfig(cc=CompressionConfig(bits=4)))

    def run(t, split=0, concat=1):
        return wdisp.wire_all_to_all(
            t, "d", split_axis=split, concat_axis=concat,
            kind="moe_a2a", name="m",
        )

    sh = dict(mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
    # (a) indivisible split axis: the dispatcher classifies the edge RAW
    # before any accounting, and the failure (lax.all_to_all requires
    # divisibility) is exactly the pre-wire one.
    bad = np.random.default_rng(8).normal(size=(6, 8, 32)).astype(np.float32)
    with pytest.raises(Exception):
        jax.jit(shard_map(run, **sh))(bad)
    assert metrics.snapshot("cgx.wire.").get("cgx.wire.bytes_wire.moe_a2a", 0) == 0
    assert "wire:moe_a2a:m" not in wdisp.edge_info()
    # (b) payload below the minimal-size floor: raw, bit-equal, unaccounted.
    monkeypatch.setenv("CGX_COMPRESSION_MINIMAL_SIZE", "100000")
    ok = np.random.default_rng(9).normal(size=(8, 8, 32)).astype(np.float32)
    got = np.asarray(jax.jit(shard_map(run, **sh))(ok))
    want = np.asarray(jax.jit(shard_map(
        lambda t: lax.all_to_all(t, "d", split_axis=0, concat_axis=1,
                                 tiled=True), **sh,
    ))(ok))
    np.testing.assert_array_equal(got, want)
    assert metrics.snapshot("cgx.wire.").get("cgx.wire.bytes_wire.moe_a2a", 0) == 0
    assert "wire:moe_a2a:m" not in wdisp.edge_info()


def test_factor_edge_rejects_p2p_only_compressors(monkeypatch):
    monkeypatch.setenv("CGX_WIRE", "on")
    ws = 2
    mesh = _mesh(ws)
    wedges.set_edge_config(
        "powersgd_factor", ".*", EdgeConfig(compressor="topk", ratio=0.1)
    )
    x = jnp.ones((32, 4), jnp.float32)

    def run(t):
        return wdisp.wire_factor_allreduce(t, ("d",), mesh, name="p")

    with pytest.raises(ValueError, match="p2p-only"):
        jax.jit(shard_map(
            run, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
        ))(x)


def test_moe_ep_dispatch_combine_roundtrip(monkeypatch):
    monkeypatch.setenv("CGX_WIRE", "on")
    ws = 4
    mesh = _mesh(ws)
    rng = np.random.default_rng(6)
    buf = rng.normal(size=(8, 16, 32)).astype(np.float32)
    wedges.set_edge_config("moe_a2a", ".*", EdgeConfig(cc=CompressionConfig(bits=8)))

    def run(t):
        return ep_combine(ep_dispatch(t, "d"), "d")

    got = np.asarray(jax.jit(shard_map(
        run, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
    ))(buf))
    env = 2 * (2.0 * np.abs(buf).max() / (2**8 - 1))  # two quantized hops
    np.testing.assert_allclose(got, buf, atol=env)
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(shard_map(
            lambda t: ep_dispatch(t, "d"), mesh=mesh, in_specs=P(),
            out_specs=P(), check_vma=False,
        ))(jnp.ones((6, 4, 32), jnp.float32))


def test_powersgd_factor_edge(monkeypatch):
    """The powersgd_factor edge quantizes the P/Q factor allreduce; the
    transform's output stays close to the exact-psum run and replicas
    stay identical (error symmetry of the quantized allreduce)."""
    from torch_cgx_tpu.parallel.powersgd import (
        init_powersgd, powersgd_transform,
    )

    ws = 4
    mesh = _mesh(ws, "dp")
    rng = np.random.default_rng(7)
    grads = {"w": jnp.asarray(rng.normal(size=(32, 48)), jnp.float32)}
    params = {"w": jnp.zeros((32, 48), jnp.float32)}

    def run_once():
        tx = powersgd_transform(mesh=mesh, axes=("dp",), rank=4,
                                placement_warning=False)

        def body(g):
            st = init_powersgd(params, 4)
            red, _ = tx.update(g, st)
            return red["w"]

        return np.asarray(jax.jit(shard_map(
            body, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
        ))({"w": grads["w"]}))

    exact = run_once()
    os.environ["CGX_WIRE"] = "on"
    try:
        wedges.set_edge_config(
            "powersgd_factor", ".*", EdgeConfig(cc=CompressionConfig(bits=8))
        )
        quant = run_once()
    finally:
        os.environ.pop("CGX_WIRE", None)
    assert not np.array_equal(exact, quant)
    rel = np.linalg.norm(exact - quant) / np.linalg.norm(exact)
    assert rel < 0.05, rel
    snap = metrics.snapshot("cgx.wire.")
    assert snap.get("cgx.wire.bytes_wire.powersgd_factor", 0) > 0


# ---------------------------------------------------------------------------
# Closed-loop controller.
# ---------------------------------------------------------------------------


def _seed_qerr(label, rel, n=8):
    for _ in range(n):
        metrics.observe(f"cgx.qerr.{label}", rel)


def test_controller_reallocates_from_live_qerr(monkeypatch):
    # Two edges at 4 bits, one 10x noisier: under an avg-bits budget the
    # noisy one must end up wider than the quiet one.
    monkeypatch.setenv("CGX_WIRE", "on")
    wedges.set_edge_config(
        "ring_kv", "^noisy$", EdgeConfig(cc=CompressionConfig(bits=4))
    )
    wedges.set_edge_config(
        "ring_kv", "^quiet$", EdgeConfig(cc=CompressionConfig(bits=4))
    )
    wdisp._EDGE_INFO["wire:ring_kv:noisy"] = {"numel": 4096, "bits": 4}
    wdisp._EDGE_INFO["wire:ring_kv:quiet"] = {"numel": 4096, "bits": 4}
    _seed_qerr("wire:ring_kv:noisy", 0.2)
    _seed_qerr("wire:ring_kv:quiet", 0.02)
    v0 = cgx.config.registry_version()
    ctl = WireController(avg_bits=4, every=0)
    alloc = ctl.update()
    assert set(alloc) == {"wire:ring_kv:noisy", "wire:ring_kv:quiet"}
    assert alloc["wire:ring_kv:noisy"] > alloc["wire:ring_kv:quiet"]
    # written back into the edge registry + version bumped (retrace)
    assert (
        wedges.resolve_edge("ring_kv", "noisy").cc.bits
        == alloc["wire:ring_kv:noisy"]
    )
    assert cgx.config.registry_version() > v0
    assert metrics.get("cgx.wire.controller_updates") == 1
    assert metrics.get("cgx.wire.bits.wire:ring_kv:noisy") == float(
        alloc["wire:ring_kv:noisy"]
    )


def test_controller_covers_dp_grad_layers():
    from torch_cgx_tpu.parallel import allreduce

    allreduce._QERR_INFO["h0/kernel"] = {"numel": 1 << 16, "bits": 4}
    allreduce._QERR_INFO["h1/kernel"] = {"numel": 1 << 16, "bits": 4}
    _seed_qerr("h0/kernel", 0.3)
    _seed_qerr("h1/kernel", 0.03)
    ctl = WireController(avg_bits=4, every=0)
    alloc = ctl.update()
    assert alloc["h0/kernel"] > alloc["h1/kernel"]
    # dp layers land in the pattern registry (exact-path pattern)
    assert cgx.config.resolve_pattern_config("h0/kernel").bits == alloc[
        "h0/kernel"
    ]


def test_controller_cadence_and_idempotence():
    wdisp._EDGE_INFO["wire:pp_act:t"] = {"numel": 1024, "bits": 4}
    _seed_qerr("wire:pp_act:t", 0.1)
    ctl = WireController(avg_bits=4, every=3)
    assert ctl.step() is None
    assert ctl.step() is None
    alloc = ctl.step()
    assert alloc  # fired on the 3rd call
    v = cgx.config.registry_version()
    assert ctl.step() is None
    assert ctl.step() is None
    ctl.step()
    # identical telemetry -> identical allocation -> NO second registry
    # bump (no retrace storm)
    assert cgx.config.registry_version() == v
    assert ctl.updates == 2


def test_controller_ignores_unknown_and_sparse_labels():
    _seed_qerr("wire:pp_act:unknown", 0.5)  # no side-table entry
    wdisp._EDGE_INFO["wire:pp_act:thin"] = {"numel": 256, "bits": 4}
    _seed_qerr("wire:pp_act:thin", 0.5, n=1)
    ctl = WireController(avg_bits=4, every=0, min_observations=4)
    assert ctl.update() == {}


# ---------------------------------------------------------------------------
# Reset / recovery wiring (satellite: stale post-recovery edge state).
# ---------------------------------------------------------------------------


def test_invalidate_trace_caches_resets_edge_state_not_configs():
    from torch_cgx_tpu.robustness.supervisor import invalidate_trace_caches

    wedges.set_edge_config("pp_act", ".*", EdgeConfig(cc=CompressionConfig(bits=4)))
    wdisp._EDGE_INFO["wire:pp_act:t"] = {"numel": 1024, "bits": 4}
    ctl = WireController(avg_bits=4, every=5)
    ctl._count = 4
    ctl.last_alloc = {"wire:pp_act:t": 4}
    invalidate_trace_caches()
    # derived state cleared...
    assert wdisp.edge_info() == {}
    assert ctl._count == 0 and ctl.last_alloc == {}
    assert metrics.get("cgx.wire.state_resets") >= 1
    # ...but the registered config survives (it is configuration)
    assert wedges.resolve_edge("pp_act", "x").cc.bits == 4


def test_reset_registries_clears_edges_too():
    wedges.set_edge_config("pp_act", ".*", EdgeConfig(cc=CompressionConfig(bits=4)))
    cgx.set_layer_pattern_config(".*", CompressionConfig(bits=4))
    wdisp._EDGE_INFO["wire:pp_act:t"] = {"numel": 1024, "bits": 4}
    cgx.reset_registries()
    assert wedges.resolve_edge("pp_act", "x") is None
    assert cgx.config.resolve_pattern_config("anything") is None
    assert wdisp.edge_info() == {}


# ---------------------------------------------------------------------------
# End-to-end acceptance: MoE + ring-attention train step and a pipelined
# train step on a CPU-forced multi-device mesh, CGX_WIRE=on, loss allclose
# to the raw run at >= 4 bits, counters + controller observed, jaxpr
# guards proving in-program compression with zero host callbacks.
# ---------------------------------------------------------------------------

B, S, D, H, E = 2, 8, 16, 2, 4  # batch, seq, model, heads, experts


def _e2e_init(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "wq": jnp.asarray(rng.normal(size=(D, D)) / np.sqrt(D), jnp.float32),
        "wkv": jnp.asarray(rng.normal(size=(D, 2 * D)) / np.sqrt(D), jnp.float32),
        "experts": jnp.asarray(
            rng.normal(size=(E, D, D)) / np.sqrt(D), jnp.float32
        ),
        "wo": jnp.asarray(rng.normal(size=(D, D)) / np.sqrt(D), jnp.float32),
    }


def _e2e_forward(p, x, experts_local, axis_name):
    """Ring attention over the sequence axis + a fixed-dispatch expert
    block whose all_to_alls ride the moe_a2a edge. x: (B, S_local, D)."""
    b, s_local, d = x.shape
    qkv_q = (x @ p["wq"]).reshape(b, s_local, H, d // H)
    kv = (x @ p["wkv"]).reshape(b, s_local, 2, H, d // H)
    q = jnp.moveaxis(qkv_q, 2, 1)  # (B, H, S_local, Dh)
    k = jnp.moveaxis(kv[:, :, 0], 2, 1)
    v = jnp.moveaxis(kv[:, :, 1], 2, 1)
    attn = ring_attention(q, k, v, axis_name=axis_name, causal=True)
    y = jnp.moveaxis(attn, 1, 2).reshape(b, s_local, d)
    # MoE block: contiguous token groups -> experts (fixed routing keeps
    # the test deterministic; the wire is what's under test).
    t = b * s_local
    exp_in = y.reshape(E, t // E, d)  # (E, C, D)
    slots = ep_dispatch(exp_in, axis_name)  # (E/ws, ws*C, D)
    h = jnp.tanh(jnp.einsum("ecd,edf->ecf", slots, experts_local))
    exp_out = ep_combine(h, axis_name)  # (E, C, D)
    out = exp_out.reshape(b, s_local, d) @ p["wo"]
    return out


def _e2e_train(n_steps=8, lr=0.05, seed=0):
    ws = 2
    mesh = _mesh(ws)
    rng = np.random.default_rng(100 + seed)
    x = rng.normal(size=(B, S, D)).astype(np.float32)
    tgt = rng.normal(size=(B, S, D)).astype(np.float32) * 0.1
    params = _e2e_init(seed)

    def loss_fn(p, xb, tb):
        out = _e2e_forward(
            {k: v for k, v in p.items() if k != "experts"},
            xb, p["experts"], "d",
        )
        return jnp.mean((out - tb) ** 2)

    def step(p, xb, tb):
        loss, g = jax.value_and_grad(loss_fn)(p, xb, tb)
        g = jax.tree.map(lambda a: lax.pmean(a, "d"), g)
        return lax.pmean(loss, "d"), g

    sharded = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(
            {"wq": P(), "wkv": P(), "wo": P(), "experts": P("d")},
            P(None, "d"), P(None, "d"),
        ),
        out_specs=(P(), {"wq": P(), "wkv": P(), "wo": P(), "experts": P("d")}),
        check_vma=False,
    ))
    losses = []
    for _ in range(n_steps):
        loss, g = sharded(params, x, tgt)
        losses.append(float(loss))
        params = jax.tree.map(lambda a, b: a - lr * b, params, g)
    jaxpr = str(jax.make_jaxpr(
        shard_map(
            step, mesh=mesh,
            in_specs=(
                {"wq": P(), "wkv": P(), "wo": P(), "experts": P("d")},
                P(None, "d"), P(None, "d"),
            ),
            out_specs=(
                P(), {"wq": P(), "wkv": P(), "wo": P(), "experts": P("d")}
            ),
            check_vma=False,
        )
    )(params, x, tgt))
    return losses, jaxpr


def test_e2e_moe_ring_wire_converges(monkeypatch):
    raw_losses, raw_jaxpr = _e2e_train()
    monkeypatch.setenv("CGX_WIRE", "on")
    wedges.set_edge_config(
        "ring_kv", ".*", EdgeConfig(cc=CompressionConfig(bits=4))
    )
    wedges.set_edge_config(
        "moe_a2a", ".*", EdgeConfig(cc=CompressionConfig(bits=4))
    )
    wire_losses, wire_jaxpr = _e2e_train()
    # converges, tracks the raw run at 4 bits
    assert wire_losses[-1] < wire_losses[0] * 0.9
    np.testing.assert_allclose(
        wire_losses, raw_losses, rtol=0.1, atol=5e-4
    )
    # compression runs INSIDE the staged program, with zero host callbacks
    assert wire_jaxpr != raw_jaxpr
    assert "callback" not in wire_jaxpr
    assert "callback" not in raw_jaxpr
    # per-edge counters observed for both edge kinds
    snap = metrics.snapshot("cgx.wire.")
    for kind in ("ring_kv", "moe_a2a"):
        assert snap.get(f"cgx.wire.bytes_wire.{kind}", 0) > 0, snap
    info = wdisp.edge_info()
    assert "wire:moe_a2a:moe.dispatch" in info
    assert info["wire:ring_kv:ring_attention.k"]["bits"] == 4


def test_e2e_qerr_stream_drives_controller(monkeypatch):
    """CGX_QERR_STATS=1 + a wire-on step: the edges stream live relative-L2
    into cgx.qerr.wire:*, and the controller's re-solve from THAT stream
    re-allocates the registered edge widths (observability -> control)."""
    monkeypatch.setenv("CGX_WIRE", "on")
    monkeypatch.setenv("CGX_QERR_STATS", "1")
    wedges.set_edge_config(
        "ring_kv", ".*", EdgeConfig(cc=CompressionConfig(bits=4))
    )
    wedges.set_edge_config(
        "moe_a2a", ".*", EdgeConfig(cc=CompressionConfig(bits=4))
    )
    _e2e_train(n_steps=2)
    qerr = {
        k: v for k, v in metrics.snapshot("cgx.qerr.wire:").items()
        if k.endswith(".count")
    }
    assert qerr, "wire edges did not stream qerr"
    ctl = WireController(avg_bits=5, every=0)
    alloc = ctl.update()
    assert alloc, "controller found no edges in the live stream"
    assert all(label.startswith("wire:") for label in alloc)
    # the write-back landed in the registry at the solved widths
    for label, bits in alloc.items():
        _, kind, name = label.split(":", 2)
        assert wedges.resolve_edge(kind, name).cc.bits == bits
    assert metrics.get("cgx.wire.controller_updates") == 1


def test_e2e_pipelined_step_wire(monkeypatch):
    """Pipelined train step (GPipe-through-AD) with the pp_act edge at
    8 bits: loss gradient allclose to the raw pipeline."""
    ws, n_micro = 4, 4
    mesh = _mesh(ws, "pp")
    rng = np.random.default_rng(9)
    d = 16
    stages = [
        {
            "w": jnp.asarray(rng.normal(size=(d, d)) / np.sqrt(d), jnp.float32),
            "b": jnp.zeros((d,), jnp.float32),
        }
        for _ in range(ws)
    ]
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.normal(size=(16, d)), jnp.float32)

    def stage_fn(p, t):
        return jnp.tanh(t @ p["w"] + p["b"])

    def pipe_loss(stacked_p):
        def run(stacked_local, xfull):
            micro = split_microbatches(xfull, n_micro)
            out = spmd_pipeline(
                stage_fn, stacked_local, micro, axis_name="pp",
                n_stages=ws,
            )
            return jnp.mean(merge_microbatches(out) ** 2)

        return shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
            check_vma=False,
        )(stacked_p, x)

    raw_loss, raw_g = jax.jit(jax.value_and_grad(pipe_loss))(stacked)
    monkeypatch.setenv("CGX_WIRE", "on")
    wedges.set_edge_config(
        "pp_act", ".*", EdgeConfig(cc=CompressionConfig(bits=8))
    )
    wire_loss, wire_g = jax.jit(jax.value_and_grad(pipe_loss))(stacked)
    np.testing.assert_allclose(
        float(wire_loss), float(raw_loss), rtol=0.05
    )
    for a, b in zip(jax.tree.leaves(wire_g), jax.tree.leaves(raw_g)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=0.05
        )
    assert metrics.snapshot("cgx.wire.").get(
        "cgx.wire.bytes_wire.pp_act", 0
    ) > 0


# ---------------------------------------------------------------------------
# Tooling: cgx_report's == wire == section and cgx_top's edges column.
# ---------------------------------------------------------------------------


def _tool(name):
    import importlib.util
    import pathlib

    p = pathlib.Path(__file__).resolve().parent.parent / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_report_and_top_render_wire(tmp_path):
    import json

    counters = {
        "cgx.wire.bytes_raw.moe_a2a": 8e6,
        "cgx.wire.bytes_wire.moe_a2a": 1e6,
        "cgx.wire.edges_compressed": 4,
        "cgx.wire.controller_updates": 2,
    }
    gauges = {"cgx.wire.bits.wire:moe_a2a:moe.dispatch": 6.0}
    (tmp_path / "metrics-rank0.jsonl").write_text(
        json.dumps({"ts": 1.0, "counters": counters, "gauges": gauges,
                    "histograms": {}}) + "\n"
    )
    (tmp_path / "flightrec-rank0.jsonl").write_text(
        json.dumps({"kind": "dump", "metrics": {**counters, **gauges}}) + "\n"
    )
    report = _tool("cgx_report")
    summary = report.summarize(report.load_dir(str(tmp_path)))
    assert summary["wire"]["edges"]["moe_a2a"]["ratio"] == 8.0
    assert summary["wire"]["controller_bits"][
        "wire:moe_a2a:moe.dispatch"
    ] == 6.0
    text = report.render(summary)
    assert "== wire" in text and "8.0x" in text and "controller bits" in text
    top = _tool("cgx_top")
    frame = top.render(str(tmp_path), {})
    assert "edges" in frame and "moe:8.0x" in frame
