"""Pipeline-parallelism tests: the SPMD GPipe schedule must match plain
sequential stage application, forward and backward, on a virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torch_cgx_tpu.parallel.pipeline import (
    merge_microbatches,
    spmd_pipeline,
    split_microbatches,
    stack_stage_params,
    unstack_stage_params,
)
from torch_cgx_tpu.utils.compat import shard_map

D = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stages(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(rng.normal(size=(D, D)) / np.sqrt(D), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(D,)) * 0.1, jnp.float32),
        }
        for _ in range(n)
    ]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def _pipelined(mesh, n_stages, n_micro, stacked, x):
    def run(stacked_local, xfull):
        micro = split_microbatches(xfull, n_micro)
        out = spmd_pipeline(
            _stage_fn, stacked_local, micro, axis_name="pp",
            n_stages=n_stages,
        )
        return merge_microbatches(out)

    return jax.jit(
        shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
            check_vma=False,
        )
    )(stacked, x)


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_sequential(n_micro):
    n_stages = 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    stages = _stages(n_stages)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(16, D)), jnp.float32)
    got = _pipelined(mesh, n_stages, n_micro, stack_stage_params(stages), x)
    want = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_grads_match_sequential():
    n_stages, n_micro = 4, 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    stages = _stages(n_stages, seed=2)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(8, D)), jnp.float32)

    def pipe_loss(stacked_p):
        def run(stacked_local, xfull):
            micro = split_microbatches(xfull, n_micro)
            out = spmd_pipeline(
                _stage_fn, stacked_local, micro, axis_name="pp",
                n_stages=n_stages,
            )
            return jnp.sum(merge_microbatches(out) ** 2)

        return shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P()),
            out_specs=P(), check_vma=False,
        )(stacked_p, x)

    def seq_loss(stacked_p):
        return jnp.sum(_sequential(unstack_stage_params(stacked_p, n_stages), x) ** 2)

    gp = jax.jit(jax.grad(pipe_loss))(stacked)
    gs = jax.grad(seq_loss)(stacked)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_stack_unstack_roundtrip():
    stages = _stages(3)
    back = unstack_stage_params(stack_stage_params(stages), 3)
    for a, b in zip(stages, back):
        np.testing.assert_array_equal(a["w"], b["w"])
        np.testing.assert_array_equal(a["b"], b["b"])


# ---------------------------------------------------------------------------
# 1F1B schedule (explicit fwd/bwd interleave, O(S) activation memory).
# ---------------------------------------------------------------------------


def _loss_fn(y, tgt):
    return jnp.mean((y - tgt) ** 2)


def _run_1f1b(mesh, n_stages, n_micro, stacked, micro, targets):
    from torch_cgx_tpu.parallel.pipeline import pipeline_1f1b

    def run(stacked_local, micro_local, tgts):
        return pipeline_1f1b(
            _stage_fn, _loss_fn, stacked_local, micro_local, tgts,
            axis_name="pp", n_stages=n_stages,
        )

    return jax.jit(
        shard_map(
            run, mesh=mesh,
            in_specs=(P("pp"), P("pp"), P()),
            out_specs=(P(), P("pp")),
            check_vma=False,
        )
    )(stacked, micro, targets)


@pytest.mark.parametrize("n_micro", [4, 8])
def test_1f1b_matches_sequential_grads(n_micro):
    """1F1B loss and per-stage parameter grads must equal plain sequential
    stage application differentiated by AD."""
    n_stages = 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    stages = _stages(n_stages, seed=5)
    stacked = stack_stage_params(stages)
    rng = np.random.default_rng(7)
    mb = 4  # microbatch size
    x = jnp.asarray(rng.normal(size=(n_micro, mb, D)), jnp.float32)
    targets = jnp.asarray(rng.normal(size=(n_micro, mb, D)), jnp.float32)

    loss, grads = _run_1f1b(mesh, n_stages, n_micro, stacked, x, targets)

    def seq_loss(stacked_p):
        per = []
        for k in range(n_micro):
            y = x[k]
            for p in unstack_stage_params(stacked_p, n_stages):
                y = _stage_fn(p, y)
            per.append(_loss_fn(y, targets[k]))
        return jnp.mean(jnp.stack(per))

    want_loss = seq_loss(stacked)
    want_grads = jax.grad(seq_loss)(stacked)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_grads)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_1f1b_loss_replicated_and_feed_sharded():
    """The microbatch stream is sharded over pp (no device holds the full
    stream) and the returned loss is replicated bit-identically. With
    check_vma=False the out_specs do NOT verify replication, so return the
    per-device loss explicitly (out_specs=P('pp')) and compare."""
    from torch_cgx_tpu.parallel.pipeline import pipeline_1f1b

    n_stages, n_micro = 4, 8
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    stages = _stages(n_stages, seed=9)
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.normal(size=(n_micro, 2, D)), jnp.float32)
    targets = jnp.asarray(rng.normal(size=(n_micro, 2, D)), jnp.float32)

    def run(sp, mi, tg):
        loss, _ = pipeline_1f1b(
            _stage_fn, _loss_fn, sp, mi, tg, axis_name="pp",
            n_stages=n_stages,
        )
        return loss[None]

    per_device = jax.jit(
        shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P("pp"), P()),
            out_specs=P("pp"), check_vma=False,
        )
    )(stack_stage_params(stages), x, targets)
    vals = np.asarray(per_device)
    assert vals.shape == (n_stages,)
    assert np.isfinite(vals).all() and (vals > 0).all()
    np.testing.assert_array_equal(vals, np.full_like(vals, vals[0]))


def test_1f1b_stash_bound():
    """The activation stash is O(S), independent of M (the schedule's
    memory claim: live_stash_microbatches)."""
    from torch_cgx_tpu.parallel.pipeline import live_stash_microbatches

    assert live_stash_microbatches(1) == 1
    assert live_stash_microbatches(4) == 7
    assert live_stash_microbatches(8) == 15
    # Bound must not depend on microbatch count: trace the jaxpr for two
    # different M and assert the stash buffer (K, mb, D) is the same size.
    import re

    n_stages = 4

    def trace(n_micro):
        mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
        from torch_cgx_tpu.parallel.pipeline import pipeline_1f1b

        def run(sp, mi, tg):
            return pipeline_1f1b(
                _stage_fn, _loss_fn, sp, mi, tg, axis_name="pp",
                n_stages=n_stages,
            )

        stages = _stages(n_stages)
        x = jnp.zeros((n_micro, 2, D), jnp.float32)
        t = jnp.zeros((n_micro, 2, D), jnp.float32)
        return str(
            jax.make_jaxpr(
                shard_map(
                    run, mesh=mesh, in_specs=(P("pp"), P("pp"), P()),
                    out_specs=(P(), P("pp")), check_vma=False,
                )
            )(stack_stage_params(stages), x, t)
        )

    k = live_stash_microbatches(n_stages)
    for n_micro in (8, 16):
        jaxpr = trace(n_micro)
        assert re.search(rf"\b{k}x2x{D}\b|\({k}, 2, {D}\)", jaxpr) or (
            f"{k},2,{D}" in jaxpr.replace(" ", "")
        )


def test_1f1b_composes_with_quantized_dp(monkeypatch):
    """PP x DP composition: 1F1B inside each dp replica, then the 4-bit
    quantized gradient allreduce over the dp axis — the full-matrix story
    on one mesh. Grads must equal the sequential reference averaged over
    replicas (within the quantization envelope), bit-identical across
    replicas (error symmetry)."""
    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.parallel import gradient_sync
    from torch_cgx_tpu.parallel.pipeline import pipeline_1f1b

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, "64")
    n_stages, n_micro, dp = 4, 4, 2
    mesh = Mesh(
        np.asarray(jax.devices()[: n_stages * dp]).reshape(dp, n_stages),
        ("dp", "pp"),
    )
    stages = _stages(n_stages, seed=21)
    stacked = stack_stage_params(stages)
    rng = np.random.default_rng(23)
    # Per-replica batches differ; the dp-allreduce averages them.
    x = jnp.asarray(rng.normal(size=(dp, n_micro, 2, D)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(dp, n_micro, 2, D)), jnp.float32)

    def run(sp, mi, tg):
        # shard_map gives (1, micro/pp, ...) per device on the dp-sharded
        # stream; drop the dp-local leading axis.
        loss, grads = pipeline_1f1b(
            _stage_fn, _loss_fn, sp,
            jnp.squeeze(mi, 0), jnp.squeeze(tg, 0),
            axis_name="pp", n_stages=n_stages,
        )
        grads = gradient_sync(grads, mesh=mesh, axes=("dp",), average=True)
        return loss, grads

    loss, grads = jax.jit(
        shard_map(
            run, mesh=mesh,
            in_specs=(P("pp"), P("dp", "pp"), P("dp")),
            out_specs=(P(), P("pp")),
            check_vma=False,
        )
    )(stacked, x, tgt)

    def seq_loss(sp, r):
        per = []
        for k in range(n_micro):
            y = x[r, k]
            for p in unstack_stage_params(sp, n_stages):
                y = _stage_fn(p, y)
            per.append(_loss_fn(y, tgt[r, k]))
        return jnp.mean(jnp.stack(per))

    want = jax.tree.map(
        lambda *gs: sum(gs) / dp,
        *[jax.grad(seq_loss)(stacked, r) for r in range(dp)],
    )
    for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want)):
        a, b = np.asarray(a), np.asarray(b)
        # 4-bit quantization envelope: a couple of quantization steps of
        # the leaf's value range (bucket range <= leaf range).
        unit = (b.max() - b.min() + 1e-6) / 15
        assert np.abs(a - b).max() < 4 * unit, (np.abs(a - b).max(), unit)


# ---------------------------------------------------------------------------
# Interleaved virtual-stage schedule (bubble / V).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_virtual,n_micro", [(2, 4), (2, 8), (3, 4)])
def test_interleaved_matches_sequential(n_virtual, n_micro):
    from torch_cgx_tpu.parallel.pipeline import (
        spmd_pipeline_interleaved,
        stack_interleaved_params,
    )

    n_stages = 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    chunks = _stages(n_stages * n_virtual, seed=5)
    stacked = stack_interleaved_params(chunks, n_stages, n_virtual)
    x = jnp.asarray(np.random.default_rng(6).normal(size=(16, D)), jnp.float32)

    def run(stacked_local, xfull):
        micro = split_microbatches(xfull, n_micro)
        out = spmd_pipeline_interleaved(
            _stage_fn, stacked_local, micro, axis_name="pp",
            n_stages=n_stages, n_virtual=n_virtual,
        )
        return merge_microbatches(out)

    got = jax.jit(
        shard_map(run, mesh=mesh, in_specs=(P("pp"), P()),
                      out_specs=P(), check_vma=False)
    )(stacked, x)
    want = _sequential(chunks, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_interleaved_grads_match_sequential():
    from torch_cgx_tpu.parallel.pipeline import (
        spmd_pipeline_interleaved,
        stack_interleaved_params,
    )

    n_stages, n_virtual, n_micro = 2, 2, 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    chunks = _stages(n_stages * n_virtual, seed=7)
    stacked = stack_interleaved_params(chunks, n_stages, n_virtual)
    x = jnp.asarray(np.random.default_rng(8).normal(size=(8, D)), jnp.float32)

    def pipe_loss(stacked_p):
        def run(stacked_local, xfull):
            micro = split_microbatches(xfull, n_micro)
            out = spmd_pipeline_interleaved(
                _stage_fn, stacked_local, micro, axis_name="pp",
                n_stages=n_stages, n_virtual=n_virtual,
            )
            return jnp.sum(merge_microbatches(out) ** 2)

        return shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P()),
            out_specs=P(), check_vma=False,
        )(stacked_p, x)

    def seq_loss(stacked_p):
        # invert the interleaved permutation: stacked row s*V + v is chunk
        # v*S + s
        rows = {}
        for s in range(n_stages):
            for v in range(n_virtual):
                rows[v * n_stages + s] = s * n_virtual + v
        ordered = [
            jax.tree.map(lambda x_, r=rows[j]: x_[r], stacked_p)
            for j in range(n_stages * n_virtual)
        ]
        return jnp.sum(_sequential(ordered, x) ** 2)

    gp = jax.jit(jax.grad(pipe_loss))(stacked)
    gs = jax.grad(seq_loss)(stacked)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_interleaved_rejects_ragged_microbatches():
    from torch_cgx_tpu.parallel.pipeline import spmd_pipeline_interleaved

    n_stages = 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    chunks = _stages(n_stages * 2)
    from torch_cgx_tpu.parallel.pipeline import stack_interleaved_params

    stacked = stack_interleaved_params(chunks, n_stages, 2)
    x = jnp.ones((6, 2, D), jnp.float32)  # 6 % 4 != 0

    def run(stacked_local, micro):
        return spmd_pipeline_interleaved(
            _stage_fn, stacked_local, micro, axis_name="pp",
            n_stages=n_stages, n_virtual=2,
        )

    with pytest.raises(AssertionError, match="microbatches % n_stages"):
        jax.jit(
            shard_map(run, mesh=mesh, in_specs=(P("pp"), P()),
                          out_specs=P(), check_vma=False)
        )(stacked, x)


def test_pipeline_compressed_hops():
    """8-bit quantized activation hops: outputs track the uncompressed
    pipeline closely and gradients still flow (STE backward)."""
    from torch_cgx_tpu.config import CompressionConfig

    n_stages, n_micro = 4, 8
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    stages = _stages(n_stages, seed=9)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(10).normal(size=(16, D)), jnp.float32)
    cc = CompressionConfig(bits=8, bucket_size=64)

    def run(hop_cc):
        def body(stacked_local, xfull):
            micro = split_microbatches(xfull, n_micro)
            out = spmd_pipeline(
                _stage_fn, stacked_local, micro, axis_name="pp",
                n_stages=n_stages, hop_cc=hop_cc,
            )
            return merge_microbatches(out)

        return jax.jit(
            shard_map(body, mesh=mesh, in_specs=(P("pp"), P()),
                          out_specs=P(), check_vma=False)
        )(stacked, x)

    plain = np.asarray(run(None))
    comp = np.asarray(run(cc))
    # 3 quantized hops with per-hop bucket error ~range/255; tanh keeps
    # activations in [-1, 1] so the compounded error stays small.
    assert np.abs(comp - plain).max() < 0.1, np.abs(comp - plain).max()
    assert not np.array_equal(comp, plain)  # compression actually engaged

    def loss(stacked_p):
        def body(stacked_local, xfull):
            micro = split_microbatches(xfull, n_micro)
            out = spmd_pipeline(
                _stage_fn, stacked_local, micro, axis_name="pp",
                n_stages=n_stages, hop_cc=cc,
            )
            return jnp.sum(merge_microbatches(out) ** 2)

        return shard_map(body, mesh=mesh, in_specs=(P("pp"), P()),
                             out_specs=P(), check_vma=False)(stacked_p, x)

    g = jax.jit(jax.grad(loss))(stacked)
    for leaf in jax.tree.leaves(g):
        arr = np.asarray(leaf)
        assert np.isfinite(arr).all()
        assert np.abs(arr).max() > 0  # cotangents crossed the quantized hops


def test_interleaved_compressed_hops():
    """hop_cc on the interleaved schedule: compressed output tracks the
    plain run within quantization error."""
    from torch_cgx_tpu.config import CompressionConfig
    from torch_cgx_tpu.parallel.pipeline import (
        spmd_pipeline_interleaved,
        stack_interleaved_params,
    )

    n_stages, n_virtual, n_micro = 4, 2, 8
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    chunks = _stages(n_stages * n_virtual, seed=11)
    stacked = stack_interleaved_params(chunks, n_stages, n_virtual)
    x = jnp.asarray(np.random.default_rng(12).normal(size=(16, D)), jnp.float32)

    def run(hop_cc):
        def body(stacked_local, xfull):
            micro = split_microbatches(xfull, n_micro)
            out = spmd_pipeline_interleaved(
                _stage_fn, stacked_local, micro, axis_name="pp",
                n_stages=n_stages, n_virtual=n_virtual, hop_cc=hop_cc,
            )
            return merge_microbatches(out)

        return np.asarray(jax.jit(
            shard_map(body, mesh=mesh, in_specs=(P("pp"), P()),
                          out_specs=P(), check_vma=False)
        )(stacked, x))

    plain = run(None)
    comp = run(CompressionConfig(bits=8, bucket_size=64))
    assert np.abs(comp - plain).max() < 0.15, np.abs(comp - plain).max()
    assert not np.array_equal(comp, plain)


def test_1f1b_compressed_hops():
    """hop_cc on 1F1B: both the activation (right) and cotangent (left)
    hops compress; loss/grads track the plain schedule."""
    from torch_cgx_tpu.config import CompressionConfig
    from torch_cgx_tpu.parallel.pipeline import pipeline_1f1b

    n_stages = 4
    m = 2 * n_stages
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    stages = _stages(n_stages, seed=13)
    stacked = stack_stage_params(stages)
    rng = np.random.default_rng(14)
    micro = jnp.asarray(rng.normal(size=(m, 2, D)), jnp.float32)
    tgts = jnp.asarray(rng.normal(size=(m, 2, D)) * 0.1, jnp.float32)

    def run(hop_cc):
        def body(stacked_local, micro_local, t):
            return pipeline_1f1b(
                _stage_fn, _loss_fn, stacked_local, micro_local, t,
                axis_name="pp", n_stages=n_stages, hop_cc=hop_cc,
            )

        loss, grads = jax.jit(
            shard_map(body, mesh=mesh, in_specs=(P("pp"), P("pp"), P()),
                          out_specs=(P(), P("pp")), check_vma=False)
        )(stacked, micro, tgts)
        return float(loss), jax.tree.map(np.asarray, grads)

    l_plain, g_plain = run(None)
    l_comp, g_comp = run(CompressionConfig(bits=8, bucket_size=64))
    assert abs(l_comp - l_plain) < 0.05 * abs(l_plain) + 1e-3, (l_comp, l_plain)
    for a, b in zip(jax.tree.leaves(g_comp), jax.tree.leaves(g_plain)):
        assert np.isfinite(a).all()
        # same order of magnitude, not identical (compression engaged)
        assert np.abs(a - b).max() < 0.2 * (np.abs(b).max() + 1e-6)
    assert any(
        not np.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(g_comp), jax.tree.leaves(g_plain))
    )
