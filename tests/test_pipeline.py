"""Pipeline-parallelism tests: the SPMD GPipe schedule must match plain
sequential stage application, forward and backward, on a virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torch_cgx_tpu.parallel.pipeline import (
    merge_microbatches,
    spmd_pipeline,
    split_microbatches,
    stack_stage_params,
    unstack_stage_params,
)

D = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stages(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(rng.normal(size=(D, D)) / np.sqrt(D), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(D,)) * 0.1, jnp.float32),
        }
        for _ in range(n)
    ]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def _pipelined(mesh, n_stages, n_micro, stacked, x):
    def run(stacked_local, xfull):
        micro = split_microbatches(xfull, n_micro)
        out = spmd_pipeline(
            _stage_fn, stacked_local, micro, axis_name="pp",
            n_stages=n_stages,
        )
        return merge_microbatches(out)

    return jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
            check_vma=False,
        )
    )(stacked, x)


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_sequential(n_micro):
    n_stages = 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    stages = _stages(n_stages)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(16, D)), jnp.float32)
    got = _pipelined(mesh, n_stages, n_micro, stack_stage_params(stages), x)
    want = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_grads_match_sequential():
    n_stages, n_micro = 4, 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pp",))
    stages = _stages(n_stages, seed=2)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(8, D)), jnp.float32)

    def pipe_loss(stacked_p):
        def run(stacked_local, xfull):
            micro = split_microbatches(xfull, n_micro)
            out = spmd_pipeline(
                _stage_fn, stacked_local, micro, axis_name="pp",
                n_stages=n_stages,
            )
            return jnp.sum(merge_microbatches(out) ** 2)

        return jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P()),
            out_specs=P(), check_vma=False,
        )(stacked_p, x)

    def seq_loss(stacked_p):
        return jnp.sum(_sequential(unstack_stage_params(stacked_p, n_stages), x) ** 2)

    gp = jax.jit(jax.grad(pipe_loss))(stacked)
    gs = jax.grad(seq_loss)(stacked)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_stack_unstack_roundtrip():
    stages = _stages(3)
    back = unstack_stage_params(stack_stage_params(stages), 3)
    for a, b in zip(stages, back):
        np.testing.assert_array_equal(a["w"], b["w"])
        np.testing.assert_array_equal(a["b"], b["b"])
