"""Pallas codec kernels vs the XLA oracle (interpret mode on CPU).

The wire format must be bit-identical between implementations — payloads are
exchanged between devices that may decode with either path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.config import CompressionConfig
from torch_cgx_tpu.ops import codec, codec_pallas, dispatch


@pytest.mark.parametrize("bits", [1, 2, 4, 7, 8])
@pytest.mark.parametrize("bucket_size", [64, 512])
def test_pallas_wire_matches_xla(bits, bucket_size):
    rows, m = 2, 4096
    xs = jnp.asarray(
        np.random.default_rng(bits).normal(size=(rows, m)), jnp.float32
    )
    q_p = codec_pallas.quantize_batch(xs, bits, bucket_size, interpret=True)
    q_x = jax.vmap(lambda r: codec.quantize(r, bits, bucket_size))(xs)
    # Encoders may differ by 1 ulp on unit (division rounding) and hence by
    # at most 1 level on boundary values; layout must be identical.
    assert q_p.packed.shape == q_x.packed.shape
    np.testing.assert_allclose(
        np.asarray(q_p.meta), np.asarray(q_x.meta), rtol=2e-6, atol=0
    )
    lvl_p = np.asarray(
        jax.vmap(lambda w: codec.unpack_levels(w, bits, 4096))(q_p.packed)
    ).astype(np.int64)
    lvl_x = np.asarray(
        jax.vmap(lambda w: codec.unpack_levels(w, bits, 4096))(q_x.packed)
    ).astype(np.int64)
    assert np.abs(lvl_p - lvl_x).max() <= 1
    # Cross-impl decode of the same payload: equal up to FMA-vs-mul+add
    # codegen (1 ulp). Bit-identity across *devices* is guaranteed by SPMD
    # (same executable everywhere) and is asserted by the reducer tests.
    for q in (q_p, q_x):
        y_xla = jax.vmap(lambda qq: codec.dequantize(qq))(q)
        y_pls = codec_pallas.dequantize_batch(q, interpret=True, out_dtype=q.dtype)
        np.testing.assert_allclose(
            np.asarray(y_xla), np.asarray(y_pls), rtol=2e-6, atol=5e-7
        )


def test_pallas_unaligned_numel():
    # m not a multiple of bucket_size: edge-padding must match XLA.
    rows, m, bits, bucket = 3, 1000, 4, 64
    xs = jnp.asarray(np.random.default_rng(0).normal(size=(rows, m)), jnp.float32)
    q_p = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    q_x = jax.vmap(lambda r: codec.quantize(r, bits, bucket))(xs)
    assert q_p.packed.shape == q_x.packed.shape
    # same payload decodes equal up to FMA codegen differences
    y = codec_pallas.dequantize_batch(q_p, interpret=True, out_dtype=jnp.float32)
    y_ref = jax.vmap(lambda qq: codec.dequantize(qq, out_dtype=jnp.float32))(q_p)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=2e-6, atol=5e-7)


def test_pallas_constant_exact():
    xs = jnp.full((2, 2048), 5.0, jnp.float32)
    q = codec_pallas.quantize_batch(xs, 4, 512, interpret=True)
    y = codec_pallas.dequantize_batch(q, interpret=True)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(xs))


def test_pallas_bf16():
    xs = jnp.asarray(np.linspace(-1, 1, 2 * 4096).reshape(2, 4096), jnp.bfloat16)
    q_p = codec_pallas.quantize_batch(xs, 8, 512, interpret=True)
    q_x = jax.vmap(lambda r: codec.quantize(r, 8, 512))(xs)
    assert q_p.packed.shape == q_x.packed.shape
    assert q_p.meta.dtype == jnp.bfloat16
    y = codec_pallas.dequantize_batch(q_p, interpret=True)
    err = np.abs(np.asarray(y, np.float32) - np.asarray(xs, np.float32))
    assert err.max() < 0.02


def test_stochastic_falls_back_off_tpu(monkeypatch):
    # pltpu.prng_* has no CPU lowering; dispatch must route stochastic
    # quantization to the XLA path off-TPU (pallas stochastic is exercised on
    # real TPU by bench.py / the verify drive).
    monkeypatch.setenv(cgx_config.CODEC_IMPL, "pallas")
    rows, m, bits, bucket = 2, 8192, 4, 512
    cc = CompressionConfig(bits=bits, bucket_size=bucket, stochastic=True)
    xs = jnp.asarray(np.random.default_rng(1).normal(size=(rows, m)), jnp.float32)
    q = dispatch.quantize_batch(xs, cc, key=jax.random.PRNGKey(3))
    y = np.asarray(dispatch.dequantize_batch(q, out_dtype=jnp.float32))
    xb = np.asarray(xs).reshape(rows, -1, bucket)
    unit = (xb.max(-1) - xb.min(-1)) / ((1 << bits) - 1)
    err = np.abs(y - np.asarray(xs)).reshape(rows, -1, bucket).max(-1)
    assert (err <= unit * 1.001 + 1e-7).all()


def test_pallas_add_fusion():
    xs = jnp.asarray(np.random.default_rng(2).normal(size=(2, 1024)), jnp.float32)
    acc = jnp.full((2, 1024), 3.0, jnp.float32)
    q = codec_pallas.quantize_batch(xs, 8, 256, interpret=True)
    y = codec_pallas.dequantize_batch(q, interpret=True)
    y_add = codec_pallas.dequantize_batch(q, add_to=acc, interpret=True)
    np.testing.assert_allclose(np.asarray(y_add), np.asarray(y) + 3.0, rtol=1e-6)


def test_supports_gating():
    assert codec_pallas.supports(4096, 4, 512, False)
    assert not codec_pallas.supports(4096, 4, 100, False)  # bucket % 32 != 0
    assert not codec_pallas.supports(4096, 4, 512, True)  # residual mode
    assert not codec_pallas.supports(100, 4, 512, False)  # tiny tensor


def test_dispatch_forced_pallas_on_cpu(monkeypatch):
    # CGX_CODEC_IMPL=pallas on CPU -> interpret-mode pallas, same wire bytes.
    monkeypatch.setenv(cgx_config.CODEC_IMPL, "pallas")
    cc = CompressionConfig(bits=4, bucket_size=512)
    xs = jnp.asarray(np.random.default_rng(5).normal(size=(2, 2048)), jnp.float32)
    q = dispatch.quantize_batch(xs, cc)
    q_ref = jax.vmap(lambda r: codec.quantize(r, 4, 512))(xs)
    np.testing.assert_array_equal(np.asarray(q.packed), np.asarray(q_ref.packed))
    monkeypatch.setenv(cgx_config.CODEC_IMPL, "xla")
    q2 = dispatch.quantize_batch(xs, cc)
    np.testing.assert_array_equal(np.asarray(q2.packed), np.asarray(q_ref.packed))


# ---------------------------------------------------------------------------
# v2 "sublane" kernel layout (CGX_PALLAS_KERNEL=sublane).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("bits", [1, 3, 4, 8])
@pytest.mark.parametrize("bucket_size", [64, 96, 512])
def test_sublane_layout_wire_matches_xla(monkeypatch, bits, bucket_size):
    """The v2 layout must produce byte-identical wire to the XLA codec in
    deterministic mode (stricter than v1's 1-level tolerance: v2 computes
    meta in XLA itself)."""
    monkeypatch.setenv("CGX_PALLAS_KERNEL", "sublane")
    rows, m = 2, 4032
    xs = jnp.asarray(
        np.random.default_rng(bits).normal(size=(rows, m)), jnp.float32
    )
    q_p = codec_pallas.quantize_batch(xs, bits, bucket_size, interpret=True)
    q_x = jax.vmap(lambda r: codec.quantize(r, bits, bucket_size))(xs)
    np.testing.assert_array_equal(np.asarray(q_p.packed), np.asarray(q_x.packed))
    np.testing.assert_allclose(
        np.asarray(q_p.meta), np.asarray(q_x.meta), rtol=2e-6, atol=0
    )
    y_p = codec_pallas.dequantize_batch(q_p, interpret=True, out_dtype=jnp.float32)
    y_x = jax.vmap(lambda qq: codec.dequantize(qq, out_dtype=jnp.float32))(q_x)
    np.testing.assert_allclose(
        np.asarray(y_p), np.asarray(y_x), rtol=2e-6, atol=5e-7
    )


def test_sublane_layout_constant_exact(monkeypatch):
    monkeypatch.setenv("CGX_PALLAS_KERNEL", "sublane")
    xs = jnp.full((1, 2048), 3.25, jnp.float32)
    q = codec_pallas.quantize_batch(xs, 4, 512, interpret=True)
    out = codec_pallas.dequantize_batch(q, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(xs))


@pytest.mark.tpu  # pltpu.prng_seed has no CPU-interpret lowering
def test_sublane_layout_stochastic_envelope(monkeypatch):
    monkeypatch.setenv("CGX_PALLAS_KERNEL", "sublane")
    xs = jnp.asarray(
        np.random.default_rng(0).normal(size=(1, 4096)), jnp.float32
    )
    q = codec_pallas.quantize_batch(
        xs, 4, 512, stochastic=True, key=jax.random.PRNGKey(7)
    )
    out = codec_pallas.dequantize_batch(q)
    unit = np.asarray(q.meta)[0, 0].max()
    assert np.abs(np.asarray(out) - np.asarray(xs)).max() <= unit * 1.01


def test_kernel_layout_env_validation(monkeypatch):
    monkeypatch.setenv("CGX_PALLAS_KERNEL", "v2")
    with pytest.raises(ValueError, match="CGX_PALLAS_KERNEL"):
        codec_pallas.quantize_batch(
            jnp.zeros((1, 512), jnp.float32), 4, 512, interpret=True
        )


def test_tile_rows_env_validation(monkeypatch):
    monkeypatch.setenv("CGX_PALLAS_TILE_ROWS", "0")
    with pytest.raises(ValueError, match="CGX_PALLAS_TILE_ROWS"):
        codec_pallas.quantize_batch(
            jnp.zeros((1, 512), jnp.float32), 4, 512, interpret=True
        )
