"""Pallas codec kernels vs the XLA oracle (interpret mode on CPU).

The wire format must be bit-identical between implementations — payloads are
exchanged between devices that may decode with either path. The chunked-
sublane format was designed so the Pallas kernels use identical float ops to
the XLA codec (same divide, same floor/clip), so deterministic payloads are
asserted byte-equal, not merely close.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.config import CompressionConfig
from torch_cgx_tpu.ops import codec, codec_pallas, dispatch


@pytest.mark.parametrize("bits", [1, 2, 4, 7, 8])
@pytest.mark.parametrize("bucket_size", [64, 512])
def test_pallas_wire_matches_xla(bits, bucket_size):
    # 4096 values at bucket 64 = 64 buckets (2 full chunks); at bucket 512 =
    # 8 buckets (tail-only region). Both regions must match the XLA bytes.
    rows, m = 2, 4096
    xs = jnp.asarray(
        np.random.default_rng(bits).normal(size=(rows, m)), jnp.float32
    )
    q_p = codec_pallas.quantize_batch(xs, bits, bucket_size, interpret=True)
    q_x = jax.vmap(lambda r: codec.quantize(r, bits, bucket_size))(xs)
    assert q_p.packed.shape == q_x.packed.shape
    np.testing.assert_array_equal(
        np.asarray(q_p.packed), np.asarray(q_x.packed)
    )
    np.testing.assert_array_equal(np.asarray(q_p.meta), np.asarray(q_x.meta))
    # Cross-impl decode of the same payload: equal up to FMA-vs-mul+add
    # codegen (1 ulp).
    for q in (q_p, q_x):
        y_xla = jax.vmap(lambda qq: codec.dequantize(qq))(q)
        y_pls = codec_pallas.dequantize_batch(q, interpret=True, out_dtype=q.dtype)
        np.testing.assert_allclose(
            np.asarray(y_xla), np.asarray(y_pls), rtol=2e-6, atol=5e-7
        )


@pytest.mark.parametrize("m", [1000, 33 * 64, 40 * 64 + 17])
def test_pallas_unaligned_numel(m):
    # m not a multiple of bucket_size: edge-padding must match XLA; sizes
    # straddling the chunk boundary exercise head+tail stitching.
    rows, bits, bucket = 3, 4, 64
    xs = jnp.asarray(np.random.default_rng(0).normal(size=(rows, m)), jnp.float32)
    q_p = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    q_x = jax.vmap(lambda r: codec.quantize(r, bits, bucket))(xs)
    assert q_p.packed.shape == q_x.packed.shape
    np.testing.assert_array_equal(np.asarray(q_p.packed), np.asarray(q_x.packed))
    y = codec_pallas.dequantize_batch(q_p, interpret=True, out_dtype=jnp.float32)
    y_ref = jax.vmap(lambda qq: codec.dequantize(qq, out_dtype=jnp.float32))(q_p)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=2e-6, atol=5e-7)


def test_pallas_constant_exact():
    xs = jnp.full((2, 40 * 512), 5.0, jnp.float32)
    q = codec_pallas.quantize_batch(xs, 4, 512, interpret=True)
    y = codec_pallas.dequantize_batch(q, interpret=True)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(xs))


def test_pallas_bf16():
    xs = jnp.asarray(
        np.linspace(-1, 1, 2 * 64 * 512).reshape(2, -1), jnp.bfloat16
    )
    q_p = codec_pallas.quantize_batch(xs, 8, 512, interpret=True)
    q_x = jax.vmap(lambda r: codec.quantize(r, 8, 512))(xs)
    assert q_p.packed.shape == q_x.packed.shape
    assert q_p.meta.dtype == jnp.bfloat16
    y = codec_pallas.dequantize_batch(q_p, interpret=True)
    err = np.abs(np.asarray(y, np.float32) - np.asarray(xs, np.float32))
    assert err.max() < 0.02


def test_stochastic_falls_back_off_tpu(monkeypatch):
    # pltpu.prng_* has no CPU lowering; dispatch must route stochastic
    # quantization to the XLA path off-TPU (pallas stochastic is exercised on
    # real TPU by bench.py / the verify drive).
    monkeypatch.setenv(cgx_config.CODEC_IMPL, "pallas")
    rows, m, bits, bucket = 2, 8192, 4, 512
    cc = CompressionConfig(bits=bits, bucket_size=bucket, stochastic=True)
    xs = jnp.asarray(np.random.default_rng(1).normal(size=(rows, m)), jnp.float32)
    q = dispatch.quantize_batch(xs, cc, key=jax.random.PRNGKey(3))
    y = np.asarray(dispatch.dequantize_batch(q, out_dtype=jnp.float32))
    xb = np.asarray(xs).reshape(rows, -1, bucket)
    unit = (xb.max(-1) - xb.min(-1)) / ((1 << bits) - 1)
    err = np.abs(y - np.asarray(xs)).reshape(rows, -1, bucket).max(-1)
    assert (err <= unit * 1.001 + 1e-7).all()


@pytest.mark.parametrize("bits,bucket", [(2, 128), (4, 512), (8, 256), (3, 384)])
def test_flat_path_wire_matches_xla(bits, bucket, monkeypatch):
    # The zero-relayout flat kernels (taken whenever nb_r % 32 == 0 and
    # bucket % 128 == 0 — the cleanly-sized buffers real training produces,
    # at the default 512/1024 bucket sizes) must emit the
    # same bytes as the XLA codec. Run under CPU interpret mode so the normal
    # suite covers the path BENCH_r02 shipped broken (VERDICT r2 Weak #1/#4).
    # Poison the block-path impls: if the gate ever stops routing these
    # shapes to the flat path, the test fails loudly instead of silently
    # testing the wrong kernels.
    def _boom(*a, **k):
        raise AssertionError("expected the flat fast path, got the block path")

    monkeypatch.setattr(codec_pallas, "_quantize_chunks_impl", _boom)
    monkeypatch.setattr(codec_pallas, "_dequantize_chunks_impl", _boom)
    m = 64 * bucket
    xs = jnp.asarray(
        np.random.default_rng(bits).normal(size=(2, m)), jnp.float32
    )
    q_p = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    q_x = jax.vmap(lambda r: codec.quantize(r, bits, bucket))(xs)
    np.testing.assert_array_equal(
        np.asarray(q_p.packed), np.asarray(q_x.packed)
    )
    np.testing.assert_array_equal(np.asarray(q_p.meta), np.asarray(q_x.meta))
    y_p = codec_pallas.dequantize_batch(q_p, interpret=True, out_dtype=jnp.float32)
    y_x = jax.vmap(
        lambda qq: codec.dequantize(qq, out_dtype=jnp.float32)
    )(q_x)
    np.testing.assert_allclose(
        np.asarray(y_p), np.asarray(y_x), rtol=2e-6, atol=5e-7
    )


def test_flat_path_unpadded_rows(monkeypatch):
    # Flat path with m not a bucket multiple but nb_r % 32 == 0 after
    # edge-padding: pad + slice-back must round-trip through the flat kernels.
    bits, bucket = 4, 128
    nb_r = 32
    m = nb_r * bucket - 7
    xs = jnp.asarray(np.random.default_rng(11).normal(size=(3, m)), jnp.float32)
    q_p = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    q_x = jax.vmap(lambda r: codec.quantize(r, bits, bucket))(xs)
    np.testing.assert_array_equal(np.asarray(q_p.packed), np.asarray(q_x.packed))
    y = codec_pallas.dequantize_batch(q_p, interpret=True, out_dtype=jnp.float32)
    assert y.shape == (3, m)
    y_ref = jax.vmap(lambda qq: codec.dequantize(qq, out_dtype=jnp.float32))(q_x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=2e-6, atol=5e-7)


@pytest.mark.tpu  # compiled (non-interpret) flat kernels on real hardware
def test_flat_path_wire_matches_xla_tpu():
    for bits, bucket in ((2, 128), (4, 512), (8, 256)):
        m = 64 * bucket
        xs = jnp.asarray(
            np.random.default_rng(bits).normal(size=(2, m)), jnp.float32
        )
        q_p = codec_pallas.quantize_batch(xs, bits, bucket)
        q_x = jax.vmap(lambda r: codec.quantize(r, bits, bucket))(xs)
        np.testing.assert_array_equal(
            np.asarray(q_p.packed), np.asarray(q_x.packed)
        )
        np.testing.assert_array_equal(
            np.asarray(q_p.meta), np.asarray(q_x.meta)
        )
        y_p = codec_pallas.dequantize_batch(q_p, out_dtype=jnp.float32)
        y_x = jax.vmap(
            lambda qq: codec.dequantize(qq, out_dtype=jnp.float32)
        )(q_x)
        np.testing.assert_allclose(
            np.asarray(y_p), np.asarray(y_x), rtol=2e-6, atol=5e-7
        )


@pytest.mark.tpu  # compiled-kernel check of the with_add Mosaic lowering
def test_fused_add_tpu():
    rows, bits, bucket = 2, 4, 512
    m = 64 * bucket
    xs = jnp.asarray(
        np.random.default_rng(21).normal(size=(rows, m)), jnp.float32
    )
    acc = jnp.asarray(
        np.random.default_rng(22).normal(size=(rows, m)), jnp.float32
    )
    q = codec_pallas.quantize_batch(xs, bits, bucket)
    fused = codec_pallas.dequantize_batch(
        q, add_to=acc, out_dtype=jnp.float32
    )
    plain = codec_pallas.dequantize_batch(q, out_dtype=jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(fused), np.asarray(acc) + np.asarray(plain)
    )


@pytest.mark.tpu  # pltpu.prng_seed has no CPU-interpret lowering
def test_pallas_stochastic_envelope():
    """Stochastic rounding moves each value to one of its bucket's two
    adjacent levels, so the error bound is PER BUCKET: |err| < that
    bucket's unit (floor(t + r), r in [0,1)). The bound must not be
    collapsed to bucket 0's unit — buckets with a wider min/max range
    have a larger unit, and the 2026-07-31 live-chip session caught
    exactly that (max err 1.036x bucket-0's unit, within its own
    bucket's)."""
    nb, bucket = 64, 512
    xs = jnp.asarray(
        np.random.default_rng(0).normal(size=(1, nb * bucket)), jnp.float32
    )
    q = codec_pallas.quantize_batch(
        xs, 4, bucket, stochastic=True, key=jax.random.PRNGKey(7)
    )
    out = codec_pallas.dequantize_batch(q)
    units = np.asarray(q.meta, np.float32)[0, :, 0]  # (nb,) per-bucket units
    err = np.abs(np.asarray(out) - np.asarray(xs)).reshape(nb, bucket)
    assert (err.max(axis=1) <= units * 1.01).all()
    # And the rounding is genuinely stochastic: strictly inside-the-grid
    # values must land on BOTH adjacent levels somewhere in 32k draws
    # (deterministic rounding would give err <= unit/2 everywhere). The
    # bound is PER BUCKET here too (advisor r5 low #3): the global max
    # error may come from a small-unit bucket, so comparing it against the
    # global max unit can fail spuriously when the widest bucket happens
    # to round near its levels — assert some bucket exceeds its OWN
    # deterministic bound instead.
    assert (err.max(axis=1) > units * 0.5).any()


def test_pallas_add_fusion():
    xs = jnp.asarray(np.random.default_rng(2).normal(size=(2, 64 * 256)), jnp.float32)
    acc = jnp.full_like(xs, 3.0)
    q = codec_pallas.quantize_batch(xs, 8, 256, interpret=True)
    y = codec_pallas.dequantize_batch(q, interpret=True)
    y_add = codec_pallas.dequantize_batch(q, add_to=acc, interpret=True)
    np.testing.assert_allclose(np.asarray(y_add), np.asarray(y) + 3.0, rtol=1e-6)


def test_supports_gating():
    assert codec_pallas.supports(4096, 4, 512, False)
    assert not codec_pallas.supports(4096, 4, 100, False)  # bucket % 32 != 0
    assert codec_pallas.supports(4096, 4, 512, True)  # residual mode rides
    assert codec_pallas.supports(4096 + 17, 4, 512, True)
    # residual mode with < 1 whole bucket left after the slice: XLA path
    assert not codec_pallas.supports(100, 4, 512, True)
    assert not codec_pallas.supports(100, 4, 512, False)  # tiny tensor


@pytest.mark.parametrize("m", [4096 + 17, 33 * 64 + 63])
def test_pallas_skip_incomplete_matches_xla(m):
    # Residual mode (compressor.cc:315-339): incomplete final bucket rides
    # raw; packed/meta/residual must all match the XLA oracle byte-for-byte
    # and the roundtrip must reproduce the tail exactly.
    rows, bits, bucket = 2, 4, 64
    xs = jnp.asarray(
        np.random.default_rng(m).normal(size=(rows, m)), jnp.float32
    )
    q_p = codec_pallas.quantize_batch(
        xs, bits, bucket, interpret=True, skip_incomplete_buckets=True
    )
    q_x = jax.vmap(
        lambda r: codec.quantize(r, bits, bucket, skip_incomplete_buckets=True)
    )(xs)
    assert q_p.packed.shape == q_x.packed.shape
    np.testing.assert_array_equal(np.asarray(q_p.packed), np.asarray(q_x.packed))
    np.testing.assert_array_equal(np.asarray(q_p.meta), np.asarray(q_x.meta))
    np.testing.assert_array_equal(
        np.asarray(q_p.residual), np.asarray(q_x.residual)
    )
    assert q_p.residual.shape == (rows, m % bucket)
    y = codec_pallas.dequantize_batch(q_p, interpret=True, out_dtype=jnp.float32)
    y_ref = jax.vmap(lambda qq: codec.dequantize(qq, out_dtype=jnp.float32))(q_x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(y_ref), rtol=2e-6, atol=5e-7
    )
    # the raw tail is exact
    np.testing.assert_array_equal(
        np.asarray(y)[:, m - m % bucket:], np.asarray(xs)[:, m - m % bucket:]
    )
    # add_to fusion with a residual present
    acc = jnp.ones_like(xs)
    y_acc = codec_pallas.dequantize_batch(q_p, add_to=acc, interpret=True)
    np.testing.assert_allclose(
        np.asarray(y_acc), np.asarray(y) + 1.0, rtol=2e-6, atol=5e-7
    )


def test_fused_add_matches_unfused():
    """The fused decompress-accumulate (UnpackArray<ADD> parity,
    cuda_compression_operations.cu:474-544) must be BIT-identical to
    decode-then-add: same op order (acc + (bmin + unit*lvl)), just one
    fewer HBM round trip. Engages only on the flat fast path with an
    exactly-tiling accumulator; a mismatched accumulator width falls back
    to the unfused add with the same values."""
    rows, bits, bucket = 2, 4, 128
    m = 64 * bucket  # nb_r = 64 full chunks per row -> flat path, no pad
    xs = jnp.asarray(np.random.default_rng(11).normal(size=(rows, m)), jnp.float32)
    acc = jnp.asarray(np.random.default_rng(12).normal(size=(rows, m)), jnp.float32)
    q = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    fused = codec_pallas.dequantize_batch(
        q, add_to=acc, interpret=True, out_dtype=jnp.float32
    )
    plain = codec_pallas.dequantize_batch(
        q, interpret=True, out_dtype=jnp.float32
    )
    np.testing.assert_array_equal(
        np.asarray(fused), np.asarray(acc) + np.asarray(plain)
    )
    # XLA-oracle agreement: equal up to the documented FMA-vs-mul+add
    # codegen delta between decode implementations (1 ulp).
    y_ref = jax.vmap(
        lambda qq, a: codec.dequantize(qq, add_to=a, out_dtype=jnp.float32)
    )(q, acc)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(y_ref), rtol=2e-6, atol=5e-7
    )
    # Unaligned numel (edge-padded flat path): falls back, same values.
    m2 = 64 * bucket - 57
    xs2, acc2 = xs[:, :m2], acc[:, :m2]
    q2 = codec_pallas.quantize_batch(xs2, bits, bucket, interpret=True)
    out2 = codec_pallas.dequantize_batch(
        q2, add_to=acc2, interpret=True, out_dtype=jnp.float32
    )
    want2 = acc2 + codec_pallas.dequantize_batch(
        q2, interpret=True, out_dtype=jnp.float32
    )
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(want2))


def test_dispatch_skip_incomplete_pallas(monkeypatch):
    # Forced-pallas dispatch honors the residual config end-to-end and the
    # flat fast path (bucket % 128 == 0) emits XLA-identical bytes.
    monkeypatch.setenv(cgx_config.CODEC_IMPL, "pallas")
    cc = CompressionConfig(bits=4, bucket_size=128, skip_incomplete_buckets=True)
    m = 32 * 128 + 50
    xs = jnp.asarray(np.random.default_rng(3).normal(size=(2, m)), jnp.float32)
    q = dispatch.quantize_batch(xs, cc)
    assert q.residual.shape == (2, 50)
    q_ref = jax.vmap(
        lambda r: codec.quantize(r, 4, 128, skip_incomplete_buckets=True)
    )(xs)
    np.testing.assert_array_equal(np.asarray(q.packed), np.asarray(q_ref.packed))
    y = dispatch.dequantize_batch(q)
    y_ref = jax.vmap(lambda qq: codec.dequantize(qq))(q_ref)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(y_ref), rtol=2e-6, atol=5e-7
    )


def test_dispatch_forced_pallas_on_cpu(monkeypatch):
    # CGX_CODEC_IMPL=pallas on CPU -> interpret-mode pallas, same wire bytes.
    monkeypatch.setenv(cgx_config.CODEC_IMPL, "pallas")
    cc = CompressionConfig(bits=4, bucket_size=64)
    xs = jnp.asarray(np.random.default_rng(5).normal(size=(2, 4096)), jnp.float32)
    q = dispatch.quantize_batch(xs, cc)
    q_ref = jax.vmap(lambda r: codec.quantize(r, 4, 64))(xs)
    np.testing.assert_array_equal(np.asarray(q.packed), np.asarray(q_ref.packed))
    monkeypatch.setenv(cgx_config.CODEC_IMPL, "xla")
    q2 = dispatch.quantize_batch(xs, cc)
    np.testing.assert_array_equal(np.asarray(q2.packed), np.asarray(q_ref.packed))


def test_host_wire_matches_pallas():
    # numpy/C++ host codec and pallas kernel bytes must agree (the torch
    # bridge encodes on host; JAX-side reducers may decode the same frames).
    from torch_cgx_tpu.ops import codec_host

    rows, m, bits, bucket = 1, 50_000, 3, 128
    x = np.random.default_rng(9).normal(size=m).astype(np.float32)
    q_h = codec_host.quantize(x, bits, bucket)
    q_p = codec_pallas.quantize_batch(
        jnp.asarray(x)[None, :], bits, bucket, interpret=True
    )
    np.testing.assert_array_equal(q_h.packed, np.asarray(q_p.packed)[0])
    np.testing.assert_array_equal(q_h.meta, np.asarray(q_p.meta)[0])


def test_tile_chunks_env_validation(monkeypatch):
    monkeypatch.setenv("CGX_PALLAS_TILE_CHUNKS", "0")
    with pytest.raises(ValueError, match="CGX_PALLAS_TILE_CHUNKS"):
        codec_pallas.quantize_batch(
            jnp.zeros((1, 64 * 512), jnp.float32), 4, 512, interpret=True
        )


def test_tile_chunks_env_override(monkeypatch):
    monkeypatch.setenv("CGX_PALLAS_TILE_CHUNKS", "2")
    xs = jnp.asarray(np.random.default_rng(3).normal(size=(1, 70 * 64)), jnp.float32)
    q = codec_pallas.quantize_batch(xs, 4, 64, interpret=True)
    q_ref = jax.vmap(lambda r: codec.quantize(r, 4, 64))(xs)
    np.testing.assert_array_equal(np.asarray(q.packed), np.asarray(q_ref.packed))


@pytest.mark.parametrize("shape_case", ["flat", "chunks"])
def test_butterfly_pack_byte_identity(monkeypatch, shape_case):
    """CGX_PALLAS_PACK=butterfly must emit exactly the same wire bytes as
    the default sum pack (both quantize kernel families)."""
    from torch_cgx_tpu.ops import codec_pallas

    bits = 4
    if shape_case == "flat":
        b, n = 128, 128 * 32 * 4  # whole chunks, bucket % 128 == 0
    else:
        b, n = 96, 96 * 32 * 2  # 32-aligned but not 128: chunk kernels
    rng = np.random.default_rng(5)
    xs = jnp.asarray(rng.normal(size=(1, n)), jnp.float32)

    monkeypatch.delenv("CGX_PALLAS_PACK", raising=False)
    q_sum = codec_pallas.quantize_batch(xs, bits, b, interpret=True)
    monkeypatch.setenv("CGX_PALLAS_PACK", "butterfly")
    q_bf = codec_pallas.quantize_batch(xs, bits, b, interpret=True)
    np.testing.assert_array_equal(np.asarray(q_sum.packed), np.asarray(q_bf.packed))
    np.testing.assert_array_equal(np.asarray(q_sum.meta), np.asarray(q_bf.meta))

    monkeypatch.setenv("CGX_PALLAS_PACK", "bogus")
    with pytest.raises(ValueError, match="CGX_PALLAS_PACK"):
        codec_pallas.quantize_batch(xs, bits, b, interpret=True)


def test_mul_encode_envelope_and_constant_exact(monkeypatch):
    """CGX_CODEC_ENCODE=mul (reciprocal-multiply level encode): trades
    strict cross-impl byte-identity (last-ulp ties may pick the adjacent
    level) for encode throughput. The error envelope, constant-bucket
    exactness, and decode round trip must all still hold."""
    monkeypatch.setenv("CGX_CODEC_ENCODE", "mul")
    bits, bucket = 4, 512
    rows, m = 2, 64 * bucket
    rng = np.random.default_rng(3)
    xs = jnp.asarray(rng.normal(size=(rows, m)), jnp.float32)
    q = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    y = codec_pallas.dequantize_batch(q, interpret=True, out_dtype=jnp.float32)
    unit = np.asarray(q.meta, np.float32)[..., 0].max()
    assert np.abs(np.asarray(y) - np.asarray(xs)).max() <= unit / 2 + 1e-6
    # differs from the div encode in at most a tiny fraction of levels, and
    # any differing value is off by exactly one level
    q_div = jax.vmap(lambda r: codec.quantize(r, bits, bucket))(xs)
    y_div = jax.vmap(lambda qq: codec.dequantize(qq, out_dtype=jnp.float32))(q_div)
    diff = np.abs(np.asarray(y) - np.asarray(y_div))
    assert (diff <= unit * 1.01).all()
    # diffs below unit/10 are last-ulp decode arithmetic, not level moves
    moved = np.mean(diff > unit * 0.1)
    assert moved < 1e-3, f"{moved:%} of levels moved"
    # constants stay bit-exact
    const = jnp.full((1, m), 2.75, jnp.float32)
    qc = codec_pallas.quantize_batch(const, bits, bucket, interpret=True)
    yc = codec_pallas.dequantize_batch(qc, interpret=True, out_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(yc), np.asarray(const))


@pytest.mark.tpu  # compiled Mosaic lowering of the butterfly pack
def test_flat_pack_butterfly_tpu(monkeypatch):
    monkeypatch.setenv("CGX_PALLAS_PACK", "butterfly")
    bits, bucket = 4, 512
    xs = jnp.asarray(
        np.random.default_rng(5).normal(size=(2, 64 * bucket)), jnp.float32
    )
    q_p = codec_pallas.quantize_batch(xs, bits, bucket)
    monkeypatch.delenv("CGX_PALLAS_PACK")
    q_s = codec_pallas.quantize_batch(xs, bits, bucket)
    np.testing.assert_array_equal(np.asarray(q_p.packed), np.asarray(q_s.packed))
    np.testing.assert_array_equal(np.asarray(q_p.meta), np.asarray(q_s.meta))


@pytest.mark.tpu  # compiled Mosaic lowering of the mul encode
def test_mul_encode_tpu(monkeypatch):
    monkeypatch.setenv("CGX_CODEC_ENCODE", "mul")
    bits, bucket = 4, 512
    xs = jnp.asarray(
        np.random.default_rng(6).normal(size=(1, 64 * bucket)), jnp.float32
    )
    q = codec_pallas.quantize_batch(xs, bits, bucket)
    y = codec_pallas.dequantize_batch(q, out_dtype=jnp.float32)
    unit = np.asarray(q.meta, np.float32)[..., 0].max()
    assert np.abs(np.asarray(y) - np.asarray(xs)).max() <= unit / 2 + 1e-6


# Slow tier: the interpret-mode sweep runs ~40 s serially; the
# targeted parity tests above keep both kernel families in tier-1.
@pytest.mark.slow
def test_fuzz_pallas_wire_matches_xla():
    """Seeded fuzz over supported (n, bits, bucket) combos — both kernel
    families (flat whole-chunk rows and chunk-block tails) must stay
    byte-identical to the XLA oracle across odd sizes and value extremes
    (the class of tail bug test_codec_host's fuzz caught in the C++ core).
    Interpret mode; small operands keep it fast."""
    rng = np.random.default_rng(0xCA5)
    # Pinned flat-path combos: nb % 32 == 0 and bucket % 128 == 0 routes
    # the whole-chunk-row kernels; random draws below essentially always
    # carry a chunk tail, which would leave that family unfuzzed.
    combos = [(4096, 4, 128, False), (8192, 2, 128, False)]
    for bits in (1, 2, 3, 4, 5, 6, 7, 8):
        n = int(rng.integers(256, 9000))
        bucket = int(rng.choice([32, 64, 96, 128, 160, 512]))
        skip = bool(rng.integers(0, 2)) and (n % bucket != 0)
        if codec_pallas.supports(n, bits, bucket, skip):
            combos.append((n, bits, bucket, skip))
    assert len(combos) >= 8  # the seed must keep real coverage
    for n, bits, bucket, skip in combos:
        from conftest import fuzz_operand

        kind = rng.integers(0, 3)
        x = fuzz_operand(rng, n, int(kind))
        xs = jnp.asarray(x)[None, :]
        ctx = (n, bits, bucket, skip, int(kind))
        qp = codec_pallas.quantize_batch(
            xs, bits, bucket, interpret=True, skip_incomplete_buckets=skip
        )
        qx = codec.quantize(
            jnp.asarray(x), bits, bucket, skip_incomplete_buckets=skip
        )
        np.testing.assert_array_equal(
            np.asarray(qp.packed[0]), np.asarray(qx.packed), err_msg=str(ctx))
        np.testing.assert_array_equal(
            np.asarray(qp.meta[0], np.float32),
            np.asarray(qx.meta, np.float32), err_msg=str(ctx))
        dp = np.asarray(codec_pallas.dequantize_batch(
            qp, out_dtype=jnp.float32, interpret=True
        )[0])
        dx = np.asarray(codec.dequantize(qx, out_dtype=jnp.float32))
        # Decode parity is NOT bit-exact: min + lvl*unit rounds once per
        # op, and orderings differ between kernels, so the two decodes can
        # differ by a couple of roundings AT THE OPERAND MAGNITUDE — which
        # is many ulps of the RESULT when min and lvl*unit cancel (decoded
        # value near zero inside a wide bucket). Bound per element by the
        # bucket's own magnitude; each implementation stays deterministic
        # (the byte-equal wire above), which is all error symmetry needs,
        # and the quantization envelope (unit/2) dwarfs this bound.
        pad = (-n) % bucket
        xb = np.concatenate([x, np.repeat(x[-1:], pad)]).reshape(-1, bucket)
        bound = np.abs(xb).max(axis=1).repeat(bucket)[:n]
        tol = 4 * np.spacing(np.float32(bound))
        diff = np.abs(dp - dx)
        worst = int(np.argmax(diff - tol))
        assert (diff <= tol).all(), (
            ctx, worst, dp[worst], dx[worst], float(tol[worst]))


# ---------------------------------------------------------------------------
# Fused SRA epilogue (ISSUE 4): K-operand dequantize-accumulate(-requantize)
# vs the staged oracle, in interpret mode on CPU.
# ---------------------------------------------------------------------------


def _staged_epilogue(q, xs, own_idx, bits, bucket, out_dtype=jnp.float32):
    """The staged reference ops, spelled out: decode rows, swap the raw own
    chunk, ordered accumulate, stage-2 quantize — the byte oracle for the
    fused kernel."""
    ws = xs.shape[0]
    vals = codec_pallas.dequantize_batch(q, out_dtype=jnp.float32, interpret=True)
    own = (jnp.arange(ws) == own_idx)[:, None]
    red = dispatch.ordered_rowsum(
        jnp.where(own, xs.astype(jnp.float32), vals)
    )
    return red, codec_pallas.quantize_batch(
        red.astype(out_dtype)[None], bits, bucket, interpret=True
    )


@pytest.mark.parametrize("ws,bits,bucket", [
    (2, 4, 128), (4, 2, 128), (4, 8, 256), (8, 4, 128), (3, 1, 128),
])
def test_fused_epilogue_matches_staged_oracle(ws, bits, bucket):
    """The acceptance oracle: the fused dequant-accumulate-requantize
    kernel must reproduce the staged path's stage-2 wire BYTES (payload
    and per-bucket meta) and reduced values exactly, per bucket, on the
    default deterministic div encode."""
    chunk = 2 * codec.CHUNK_BUCKETS * bucket
    rng = np.random.default_rng(ws * 10 + bits)
    xs = jnp.asarray(rng.normal(size=(ws, chunk)), jnp.float32)
    q = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    assert codec_pallas.supports_reduce(q)
    own_idx = jnp.int32(ws - 1)
    red_ref, q_ref = _staged_epilogue(q, xs, own_idx, bits, bucket)
    red = codec_pallas.reduce_rows_batch(
        q, raw_row=xs[ws - 1], own_idx=own_idx, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(red_ref), np.asarray(red))
    q_f = codec_pallas.sra_epilogue_batch(
        q, raw_row=xs[ws - 1], own_idx=own_idx, interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(q_ref.packed), np.asarray(q_f.packed)
    )
    # per-bucket meta: (1, nb, 2) (unit, min) pairs must agree bucket by
    # bucket, not just in aggregate
    np.testing.assert_array_equal(
        np.asarray(q_ref.meta, np.float32), np.asarray(q_f.meta, np.float32)
    )
    # both decode to the same allgather-phase values
    y_ref = codec_pallas.dequantize_batch(q_ref, interpret=True)
    y_f = codec_pallas.dequantize_batch(q_f, interpret=True)
    np.testing.assert_array_equal(np.asarray(y_ref), np.asarray(y_f))


def test_fused_reduce_no_own_swap_matches_staged():
    """The all-to-all form: no raw-row substitution — plain K-operand
    decompress-accumulate."""
    ws, bits, bucket = 4, 4, 128
    chunk = codec.CHUNK_BUCKETS * bucket
    xs = jnp.asarray(
        np.random.default_rng(7).normal(size=(ws, chunk)), jnp.float32
    )
    q = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    vals = codec_pallas.dequantize_batch(q, out_dtype=jnp.float32, interpret=True)
    ref = dispatch.ordered_rowsum(vals)
    got = codec_pallas.reduce_rows_batch(q, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_fused_epilogue_bf16_wire_dtype():
    """bf16 wire: the staged path quantizes reduced.astype(bf16); the
    fused kernel's cast_dtype must round identically."""
    ws, bits, bucket = 4, 4, 128
    chunk = codec.CHUNK_BUCKETS * bucket
    xs = jnp.asarray(
        np.random.default_rng(8).normal(size=(ws, chunk)), jnp.float32
    ).astype(jnp.bfloat16)
    q = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    own_idx = jnp.int32(1)
    _, q_ref = _staged_epilogue(q, xs, own_idx, bits, bucket, jnp.bfloat16)
    q_f = codec_pallas.sra_epilogue_batch(
        q, raw_row=xs[1], own_idx=own_idx, out_dtype=jnp.bfloat16,
        interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(q_ref.packed), np.asarray(q_f.packed)
    )
    np.testing.assert_array_equal(
        np.asarray(q_ref.meta, np.float32), np.asarray(q_f.meta, np.float32)
    )


def test_fused_epilogue_mul_encode_envelope_and_ties(monkeypatch):
    """ISSUE 4 satellite: CGX_CODEC_ENCODE=mul must apply INSIDE the fused
    epilogue's requantize — same one-knob flip criterion as the plain
    quantize kernel (PERF_NOTES.md): error envelope holds, only a tiny
    tie fraction of levels moves vs the div encode, constants stay
    bit-exact."""
    ws, bits, bucket = 4, 4, 512
    chunk = 2 * codec.CHUNK_BUCKETS * bucket
    rng = np.random.default_rng(9)
    xs = jnp.asarray(rng.normal(size=(ws, chunk)), jnp.float32)
    q = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    own_idx = jnp.int32(0)
    red_ref, q_div = _staged_epilogue(q, xs, own_idx, bits, bucket)
    monkeypatch.setenv("CGX_CODEC_ENCODE", "mul")
    q_mul = codec_pallas.sra_epilogue_batch(
        q, raw_row=xs[0], own_idx=own_idx, interpret=True
    )
    monkeypatch.delenv("CGX_CODEC_ENCODE")
    # meta (pure max/min arithmetic) is encode-independent
    np.testing.assert_array_equal(
        np.asarray(q_div.meta, np.float32), np.asarray(q_mul.meta, np.float32)
    )
    y_div = codec_pallas.dequantize_batch(q_div, interpret=True)[0]
    y_mul = codec_pallas.dequantize_batch(q_mul, interpret=True)[0]
    unit = np.asarray(q_mul.meta, np.float32)[..., 0].max()
    # envelope: the mul decode still round-trips the reduced chunk within
    # half a level
    assert np.abs(np.asarray(y_mul) - np.asarray(red_ref)).max() <= (
        unit / 2 + 1e-5
    )
    # tie fraction: differing values are off by at most one level and rare
    diff = np.abs(np.asarray(y_mul) - np.asarray(y_div))
    assert (diff <= unit * 1.01).all()
    assert np.mean(diff > unit * 0.1) < 1e-3
    # constant buckets encode exactly under mul too
    const = jnp.full((ws, chunk), 1.5, jnp.float32)
    qc = codec_pallas.quantize_batch(const, bits, bucket, interpret=True)
    monkeypatch.setenv("CGX_CODEC_ENCODE", "mul")
    qc_f = codec_pallas.sra_epilogue_batch(
        qc, raw_row=const[0], own_idx=jnp.int32(0), interpret=True
    )
    yc = codec_pallas.dequantize_batch(qc_f, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(yc), np.full((1, chunk), ws * 1.5, np.float32)
    )


def test_fused_reduce_unsupported_shapes_fall_back(monkeypatch):
    """Dispatch keeps the staged reference path for shapes outside the
    flat-kernel geometry (tail buckets, non-128-aligned buckets) and on
    CPU auto mode — supports_reduce gates the kernel, values are
    unchanged either way."""
    ws, bits = 4, 4
    # bucket not 128-aligned -> unsupported
    xs = jnp.asarray(
        np.random.default_rng(11).normal(size=(ws, 32 * 64)), jnp.float32
    )
    q = codec_pallas.quantize_batch(xs, bits, 64, interpret=True)
    assert not codec_pallas.supports_reduce(q)
    # chunk tail (nb_r % 32 != 0) -> unsupported
    q2 = codec_pallas.quantize_batch(
        jnp.asarray(np.random.default_rng(12).normal(size=(ws, 8 * 128)),
                    jnp.float32),
        bits, 128, interpret=True,
    )
    assert not codec_pallas.supports_reduce(q2)
    # forced-fused dispatch on a supported shape equals forced-staged
    chunk = codec.CHUNK_BUCKETS * 128
    xs3 = jnp.asarray(
        np.random.default_rng(13).normal(size=(ws, chunk)), jnp.float32
    )
    q3 = codec_pallas.quantize_batch(xs3, bits, 128, interpret=True)
    own_idx = jnp.int32(2)
    monkeypatch.setenv("CGX_CODEC_IMPL", "pallas")
    monkeypatch.setenv("CGX_SRA_EPILOGUE", "staged")
    staged = dispatch.reduce_rows(q3, raw_rows=xs3, own_idx=own_idx)
    monkeypatch.setenv("CGX_SRA_EPILOGUE", "fused")
    fused = dispatch.reduce_rows(q3, raw_rows=xs3, own_idx=own_idx)
    np.testing.assert_array_equal(np.asarray(staged), np.asarray(fused))


@pytest.mark.tpu  # compiled Mosaic lowering of the fused epilogue
def test_fused_epilogue_tpu():
    ws, bits, bucket = 8, 4, 512
    chunk = 2 * codec.CHUNK_BUCKETS * bucket
    xs = jnp.asarray(
        np.random.default_rng(14).normal(size=(ws, chunk)), jnp.float32
    )
    q = codec_pallas.quantize_batch(xs, bits, bucket)
    own_idx = jnp.int32(3)
    vals = codec_pallas.dequantize_batch(q, out_dtype=jnp.float32)
    own = (jnp.arange(ws) == own_idx)[:, None]
    red = dispatch.ordered_rowsum(
        jnp.where(own, xs.astype(jnp.float32), vals)
    )
    q_ref = codec_pallas.quantize_batch(red[None], bits, bucket)
    q_f = codec_pallas.sra_epilogue_batch(
        q, raw_row=xs[3], own_idx=own_idx
    )
    np.testing.assert_array_equal(
        np.asarray(q_ref.packed), np.asarray(q_f.packed)
    )
    np.testing.assert_array_equal(
        np.asarray(q_ref.meta, np.float32), np.asarray(q_f.meta, np.float32)
    )


# ---------------------------------------------------------------------------
# Double-buffered manual-DMA lowerings (CGX_PALLAS_DB) + int8 epilogue
# accumulation (CGX_SRA_ACCUM) — codec roofline round 2.
# ---------------------------------------------------------------------------


def _db_case(rng, rows=2, chunks=4, bucket=512):
    return jnp.asarray(
        rng.standard_normal((rows, chunks * 32 * bucket)), jnp.float32
    )


@pytest.mark.parametrize("bits", [1, 4, 8])
def test_db_quantize_bytes_match_grid(bits, monkeypatch):
    """CGX_PALLAS_DB=on: the manual-DMA quantize emits byte-identical
    words/meta to the grid kernel (per-block math is shared)."""
    xs = _db_case(np.random.default_rng(21))
    q_grid = codec_pallas.quantize_batch(xs, bits, 512, interpret=True)
    monkeypatch.setenv("CGX_PALLAS_DB", "on")
    q_db = codec_pallas.quantize_batch(xs, bits, 512, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(q_grid.packed), np.asarray(q_db.packed)
    )
    np.testing.assert_array_equal(
        np.asarray(q_grid.meta), np.asarray(q_db.meta)
    )


def test_db_dequantize_and_fused_add_match_grid(monkeypatch):
    rng = np.random.default_rng(22)
    xs = _db_case(rng)
    q = codec_pallas.quantize_batch(xs, 4, 512, interpret=True)
    acc = jnp.asarray(rng.standard_normal(xs.shape), jnp.float32)
    d_grid = codec_pallas.dequantize_batch(q, interpret=True)
    a_grid = codec_pallas.dequantize_batch(q, add_to=acc, interpret=True)
    monkeypatch.setenv("CGX_PALLAS_DB", "on")
    d_db = codec_pallas.dequantize_batch(q, interpret=True)
    a_db = codec_pallas.dequantize_batch(q, add_to=acc, interpret=True)
    np.testing.assert_array_equal(np.asarray(d_grid), np.asarray(d_db))
    np.testing.assert_array_equal(np.asarray(a_grid), np.asarray(a_db))


def test_db_epilogue_bytes_match_grid(monkeypatch):
    ws, bits, bucket = 4, 4, 512
    rng = np.random.default_rng(23)
    xs = jnp.asarray(
        rng.standard_normal((ws, 2 * 32 * bucket)), jnp.float32
    )
    q = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    own = jnp.int32(1)
    e_grid = codec_pallas.sra_epilogue_batch(
        q, raw_row=xs[1], own_idx=own, interpret=True
    )
    monkeypatch.setenv("CGX_PALLAS_DB", "on")
    e_db = codec_pallas.sra_epilogue_batch(
        q, raw_row=xs[1], own_idx=own, interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(e_grid.packed), np.asarray(e_db.packed)
    )
    np.testing.assert_array_equal(
        np.asarray(e_grid.meta, np.float32),
        np.asarray(e_db.meta, np.float32),
    )


def test_db_auto_is_inert_without_tuned_entry():
    """auto (default) never engages the DB lowering unless a persisted
    autotune entry measured it faster on this chip."""
    assert not codec_pallas._use_db(None)


def test_int8_accum_envelope(monkeypatch):
    """CGX_SRA_ACCUM=int8: the fixed-point peer-row fold stays within the
    documented envelope of the exact f32 fold — per-row unit snap error
    <= U/2^13 * maxlvl, summed over ws rows."""
    ws, bits, bucket = 4, 4, 512
    rng = np.random.default_rng(24)
    xs = jnp.asarray(
        rng.standard_normal((ws, 2 * 32 * bucket)), jnp.float32
    )
    q = codec_pallas.quantize_batch(xs, bits, bucket, interpret=True)
    own = jnp.int32(2)
    exact = codec_pallas.reduce_rows_batch(
        q, raw_row=xs[2], own_idx=own, interpret=True
    )
    monkeypatch.setenv("CGX_SRA_ACCUM", "int8")
    fixed = codec_pallas.reduce_rows_batch(
        q, raw_row=xs[2], own_idx=own, interpret=True
    )
    units = np.asarray(q.meta, np.float32)[..., 0]
    bound = ws * units.max() * ((1 << bits) - 1) / (1 << 13) + 1e-6
    err = np.max(np.abs(np.asarray(exact) - np.asarray(fixed)))
    assert err <= bound, (err, bound)
    # and the requantizing epilogue still produces a decodable payload
    q2 = codec_pallas.sra_epilogue_batch(
        q, raw_row=xs[2], own_idx=own, interpret=True
    )
    dec = codec_pallas.dequantize_batch(q2, interpret=True)
    unit2 = np.abs(np.asarray(exact)).max() / ((1 << bits) - 1)
    assert np.max(
        np.abs(np.asarray(dec)[0] - np.asarray(exact))
    ) <= 2 * unit2 + bound


def test_int8_accum_constant_buckets_exact(monkeypatch):
    """Constant buckets (unit 0) decode exactly under the int8 fold too —
    the zero-unit guard must not poison the fixed-point scales."""
    ws, bucket = 4, 512
    xs = jnp.tile(
        jnp.asarray([[1.5]], jnp.float32), (ws, 32 * bucket)
    )
    q = codec_pallas.quantize_batch(xs, 4, bucket, interpret=True)
    monkeypatch.setenv("CGX_SRA_ACCUM", "int8")
    red = codec_pallas.reduce_rows_batch(q, interpret=True)
    np.testing.assert_allclose(np.asarray(red), ws * 1.5, rtol=1e-6)
