"""Host codec (numpy + native C++) parity with the JAX codec oracle.

The torch bridge stages DDP buckets through this codec, so its wire bytes
must be byte-identical to what the JAX/Pallas path produces (same format as
the reference's compressor wire, compressor.cc:401-419)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from torch_cgx_tpu.ops import codec, codec_host
from torch_cgx_tpu.runtime import native

CASES = [
    (16, 2, 64),
    (77, 8, 512),
    (130, 2, 64),
    (1000, 3, 64),
    (4096, 1, 128),
    (10_000, 4, 512),
    (65_536, 6, 2048),
]


def _datasets(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        np.linspace(-3.0, 5.0, n).astype(np.float32),
        rng.standard_normal(n).astype(np.float32),
        np.full(n, 2.5, np.float32),  # constant buckets — exactness oracle
    ]


def _numpy_quantize(x, bits, bucket, **kw):
    """Force the pure-numpy path regardless of the native build."""
    orig = codec_host._native
    codec_host._native = lambda: None
    try:
        return codec_host.quantize(x, bits, bucket, **kw)
    finally:
        codec_host._native = orig


@pytest.mark.parametrize("n,bits,bucket", CASES)
def test_wire_bytes_match_jax(n, bits, bucket):
    for x in _datasets(n):
        q_np = _numpy_quantize(x, bits, bucket)
        q_jax = codec.quantize(jnp.asarray(x), bits, bucket)
        np.testing.assert_array_equal(q_np.packed, np.asarray(q_jax.packed))
        np.testing.assert_array_equal(q_np.meta, np.asarray(q_jax.meta))


@pytest.mark.parametrize("n,bits,bucket", CASES)
def test_native_matches_numpy(n, bits, bucket):
    if not native.available():
        pytest.skip("native core not built (no g++)")
    for x in _datasets(n, seed=1):
        q_np = _numpy_quantize(x, bits, bucket)
        packed, meta = native.quantize_f32(x, bits, bucket)
        np.testing.assert_array_equal(q_np.packed, packed)
        np.testing.assert_array_equal(q_np.meta, meta)
        d_np = codec_host.dequantize(q_np, out_dtype=np.float32)
        d_nat = native.dequantize_f32(packed, meta, bits, bucket, n)
        np.testing.assert_array_equal(d_np, d_nat)


def test_decode_within_one_ulp_of_xla():
    n, bits, bucket = 10_000, 4, 512
    x = np.linspace(-3, 5, n).astype(np.float32)
    q = _numpy_quantize(x, bits, bucket)
    d_host = codec_host.dequantize(q, out_dtype=np.float32)
    d_jax = np.asarray(
        codec.dequantize(codec.quantize(jnp.asarray(x), bits, bucket),
                         out_dtype=jnp.float32)
    )
    ulp = np.spacing(np.abs(d_jax).astype(np.float32))
    assert np.all(np.abs(d_host - d_jax) <= ulp)


def test_roundtrip_error_bound():
    n, bits, bucket = 50_000, 4, 512
    x = np.linspace(0.0, 1.0, n).astype(np.float32)
    q = _numpy_quantize(x, bits, bucket)
    out = codec_host.dequantize(q, out_dtype=np.float32)
    # per-bucket range / (2^bits - 1) is the max quantization error
    step = (x[bucket] - x[0]) / ((1 << bits) - 1)
    assert np.abs(out - x).max() <= step


def test_constant_buckets_exact():
    x = np.full(2048, -1.25, np.float32)
    for bits in (1, 2, 4, 8):
        q = _numpy_quantize(x, bits, 512)
        np.testing.assert_array_equal(
            codec_host.dequantize(q, out_dtype=np.float32), x
        )


def test_serialization_roundtrip():
    n, bits, bucket = 1000, 3, 64
    x = np.random.default_rng(2).standard_normal(n).astype(np.float32)
    q = _numpy_quantize(x, bits, bucket)
    buf = q.to_bytes()
    _, _, _, total = codec_host.wire_layout(n, bits, bucket, np.float32)
    assert buf.nbytes == total == q.wire_bytes()
    q2 = codec_host.from_bytes(buf, n, bits, bucket, np.float32)
    np.testing.assert_array_equal(q2.packed, q.packed)
    np.testing.assert_array_equal(q2.meta, q.meta)
    np.testing.assert_array_equal(
        codec_host.dequantize(q2, out_dtype=np.float32),
        codec_host.dequantize(q, out_dtype=np.float32),
    )


def test_serialization_padding_crosses_group_boundary():
    """Regression: bucket padding that crosses a 32-lane group boundary must
    be framed identically by wire_layout (receiver) and quantize (sender)."""
    n, bits, bucket = 10_000, 4, 512  # padded 10240 vs main 10000
    x = np.linspace(-3, 5, n).astype(np.float32)
    q = _numpy_quantize(x, bits, bucket)
    buf = q.to_bytes()
    assert buf.nbytes == codec_host.wire_layout(n, bits, bucket, np.float32)[3]
    q2 = codec_host.from_bytes(buf, n, bits, bucket, np.float32)
    np.testing.assert_array_equal(
        codec_host.dequantize(q2, out_dtype=np.float32),
        codec_host.dequantize(q, out_dtype=np.float32),
    )


def test_skip_incomplete_buckets_residual():
    n, bits, bucket = 1000, 4, 512  # 488-value tail -> residual
    x = np.random.default_rng(3).standard_normal(n).astype(np.float32)
    q = _numpy_quantize(x, bits, bucket, skip_incomplete_buckets=True)
    assert q.residual.shape[0] == n % bucket
    out = codec_host.dequantize(q, out_dtype=np.float32)
    np.testing.assert_array_equal(out[-(n % bucket):], x[-(n % bucket):])
    buf = q.to_bytes()
    q2 = codec_host.from_bytes(
        buf, n, bits, bucket, np.float32, skip_incomplete=True
    )
    np.testing.assert_array_equal(
        codec_host.dequantize(q2, out_dtype=np.float32), out
    )


def test_add_accumulate():
    n = 5000
    rng = np.random.default_rng(4)
    x = rng.standard_normal(n).astype(np.float32)
    acc = rng.standard_normal(n).astype(np.float32)
    q = _numpy_quantize(x, 4, 512)
    fused = codec_host.dequantize(q, add_to=acc.copy(), out_dtype=np.float32)
    plain = acc + codec_host.dequantize(q, out_dtype=np.float32)
    np.testing.assert_allclose(fused, plain, rtol=0, atol=0)


def test_native_executor_async():
    if not native.available():
        pytest.skip("native core not built (no g++)")
    rng = np.random.default_rng(5)
    ex = native.NativeExecutor(2)
    try:
        xs = [rng.standard_normal(20_000).astype(np.float32) for _ in range(4)]
        jobs = []
        for x in xs:
            packed, meta = native.quantize_f32(x[:1], 4, 512)  # shape probe
            packed = np.empty(codec.packed_words(-(-20_000 // 512) * 512, 4),
                              np.uint32)
            meta = np.empty((-(-20_000 // 512), 2), np.float32)
            jobs.append((ex.submit_quantize(x, 4, 512, packed, meta),
                         x, packed, meta))
        for jid, x, packed, meta in jobs:
            ex.wait(jid)
            ref_p, ref_m = native.quantize_f32(x, 4, 512)
            np.testing.assert_array_equal(packed, ref_p)
            np.testing.assert_array_equal(meta, ref_m)
    finally:
        ex.close()


def test_stochastic_rounding_unbiased():
    n, bits, bucket = 100_000, 2, 512
    x = np.random.default_rng(6).uniform(-1, 1, n).astype(np.float32)
    rng = np.random.default_rng(7)
    acc = np.zeros(n, np.float64)
    reps = 30
    for _ in range(reps):
        q = _numpy_quantize(x, bits, bucket, stochastic=True, rng=rng)
        acc += codec_host.dequantize(q, out_dtype=np.float32)
    mean = (acc / reps).astype(np.float32)
    # unbiased: mean of stochastic decodes approaches x much closer than the
    # deterministic quantization step
    step = 2.0 / ((1 << bits) - 1)
    assert np.abs(mean - x).mean() < step / 4


# Slow tier: exhaustive three-way fuzz (~20 s); the pinned-combo
# byte-identity tests above stay in tier-1.
@pytest.mark.slow
def test_fuzz_three_way_byte_identity():
    """Seeded fuzz over the config space: every (n, bits, bucket) combo
    must produce BYTE-IDENTICAL wire from all three implementations
    (numpy host, native C++, XLA codec) and decode consistently — the
    fixed CASES list can't cover the odd-size / extreme-value corners
    the bridge actually sees (reference sweep: test_cgx.py:69-93)."""
    rng = np.random.default_rng(0xC6)
    combos = []
    for bits in range(1, 9):
        for _ in range(2):
            n = int(rng.integers(1, 50_000))
            bucket = int(rng.choice([1, 32, 100, 512, 1024, 100_000]))
            combos.append((n, bits, bucket))
    for n, bits, bucket in combos:
        from conftest import fuzz_operand

        kind = int(rng.integers(0, 3))
        x = fuzz_operand(rng, n, kind)
        q_np = _numpy_quantize(x, bits, bucket)  # pure-numpy path, forced
        q_jax = codec.quantize(jnp.asarray(x), bits, bucket)
        ctx = (n, bits, bucket, int(kind))
        np.testing.assert_array_equal(
            q_np.packed, np.asarray(q_jax.packed), err_msg=str(ctx))
        np.testing.assert_array_equal(
            np.asarray(q_np.meta, np.float32).reshape(-1),
            np.asarray(q_jax.meta, np.float32).reshape(-1),
            err_msg=str(ctx))
        if native.available():
            p_nat, m_nat = native.quantize_f32(x, bits, bucket)
            np.testing.assert_array_equal(q_np.packed, p_nat, err_msg=str(ctx))
            np.testing.assert_array_equal(
                np.asarray(q_np.meta, np.float32).reshape(-1),
                m_nat.reshape(-1), err_msg=str(ctx))
        # Decode consistency across all three paths (the numpy dequantize
        # is forced off the native core the same way _numpy_quantize is).
        orig = codec_host._native
        codec_host._native = lambda: None
        try:
            d_np = codec_host.dequantize(q_np, out_dtype=np.float32)
        finally:
            codec_host._native = orig
        d_jax = np.asarray(codec.dequantize(q_jax, out_dtype=jnp.float32))
        # Same cross-impl decode contract as test_decode_within_one_ulp_of
        # _xla: an FMA-contracting XLA build may differ by an ulp.
        ulp = np.abs(d_np.view(np.int32) - d_jax.view(np.int32))
        assert ulp.max() <= 1, (ctx, int(ulp.max()))
        if native.available():
            d_nat = native.dequantize_f32(p_nat, m_nat, bits, bucket, n)
            np.testing.assert_array_equal(d_np, d_nat, err_msg=str(ctx))
