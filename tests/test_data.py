"""Input-pipeline tests (subsystem absent from the reference — see data.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_cgx_tpu import data as cgx_data
from torch_cgx_tpu.parallel import flat_mesh


def _arrays(n=32):
    return {
        "x": np.arange(n * 4, dtype=np.float32).reshape(n, 4),
        "y": np.arange(n, dtype=np.int32),
    }


def test_iterate_batches_epochs_and_shapes():
    batches = list(cgx_data.iterate_batches(_arrays(32), 8, epochs=2))
    assert len(batches) == 8  # 4 per epoch x 2
    assert batches[0]["x"].shape == (8, 4)
    # without rng, order is deterministic
    np.testing.assert_array_equal(batches[0]["y"], np.arange(8))


def test_iterate_batches_shuffles_and_covers():
    rng = np.random.default_rng(0)
    batches = list(cgx_data.iterate_batches(_arrays(32), 8, rng=rng))
    seen = np.sort(np.concatenate([b["y"] for b in batches]))
    np.testing.assert_array_equal(seen, np.arange(32))  # a permutation
    assert any(
        not np.array_equal(b["y"], np.sort(b["y"])) for b in batches
    ) or not np.array_equal(batches[0]["y"], np.arange(8))


def test_iterate_batches_drop_remainder():
    batches = list(cgx_data.iterate_batches(_arrays(30), 8))
    assert len(batches) == 3
    batches = list(
        cgx_data.iterate_batches(_arrays(30), 8, drop_remainder=False)
    )
    assert len(batches) == 4 and batches[-1]["x"].shape[0] == 6


def test_iterate_batches_validation():
    with pytest.raises(ValueError, match="leading"):
        next(cgx_data.iterate_batches(
            {"x": np.zeros((4, 2)), "y": np.zeros(5)}, 2))
    with pytest.raises(ValueError, match="batch_size"):
        next(cgx_data.iterate_batches(_arrays(4), 8))


def test_shard_batches_places_on_mesh():
    mesh = flat_mesh()
    it = cgx_data.shard_batches(
        cgx_data.iterate_batches(_arrays(32), 16), mesh
    )
    b = next(it)
    assert isinstance(b["x"], jax.Array)
    # Sharding EQUIVALENCE, not PartitionSpec == — shard_batch builds its
    # spec as P(axes) with axes a tuple, and jax 0.4.x PartitionSpec.__eq__
    # does not normalize the single-axis tuple entry P(('dp',),) against
    # the scalar spelling P('dp'), though both name the same placement
    # (newer jax normalizes at construction).
    want = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("dp"))
    assert b["x"].sharding.is_equivalent_to(want, b["x"].ndim)
    assert len(b["x"].addressable_shards) == len(jax.devices())


def test_prefetch_order_and_exhaustion():
    out = list(cgx_data.prefetch(iter(range(10)), size=3))
    assert out == list(range(10))


def test_prefetch_propagates_errors():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = cgx_data.prefetch(gen(), size=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_end_to_end_training_with_pipeline(monkeypatch):
    """The docstring's typical loop, on the 8-device mesh with 4-bit grads."""
    import optax

    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.parallel import make_train_step, replicate

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    mesh = flat_mesh()
    w_true = np.asarray([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    xs = np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32)
    ys = (xs @ w_true).astype(np.float32)

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    params = replicate({"w": jnp.zeros((4, 1))}, mesh)
    opt = optax.adam(0.1)
    opt_state = replicate(opt.init(params), mesh)
    step = make_train_step(loss_fn, opt, mesh, donate=False)

    it = cgx_data.prefetch(
        cgx_data.shard_batches(
            cgx_data.iterate_batches(
                {"x": xs, "y": ys}, 32,
                rng=np.random.default_rng(1), epochs=20,
            ),
            mesh,
        )
    )
    first = last = None
    for i, batch in enumerate(it):
        params, opt_state, loss = step(params, opt_state, batch, jnp.int32(i))
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < 0.05 * first, (first, last)


def test_prefetch_abandoned_consumer_stops_producer():
    """Breaking out of the loop must unblock and stop the producer thread."""
    import threading
    import time

    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield i

    before = threading.active_count()
    it = cgx_data.prefetch(gen(), size=2)
    assert next(it) == 0
    it.close()  # GeneratorExit -> finally -> stop producer
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before, "prefetch thread leaked"
    assert len(produced) < 1000, "producer ran unbounded after abandon"


def test_shard_batches_remainder_raises_clearly():
    mesh = flat_mesh()  # 8 devices
    it = cgx_data.shard_batches(
        cgx_data.iterate_batches(_arrays(30), 8, drop_remainder=False), mesh
    )
    next(it), next(it), next(it)  # 8, 8, 8
    with pytest.raises(ValueError, match="not divisible"):
        next(it)  # remainder of 6
