"""Codec unit tests — the layer the reference never unit-tested (SURVEY.md §4).

Oracles transplanted from the reference integration suite
(/root/reference/test/test_cgx.py):
* constant buckets quantize bit-exactly (test_cgx.py:69-78),
* varying data obeys the per-bucket quantization-error envelope
  unit/2 = (max-min)/(2^bits-1)/2 per value (test_cgx.py:91-93 analogue),
plus packing roundtrip/density checks the reference lacks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_cgx_tpu.ops import codec


@pytest.mark.parametrize("bits", [1, 2, 3, 4, 5, 6, 7, 8])
def test_pack_unpack_roundtrip(bits):
    rng = np.random.default_rng(bits)
    m = 1000  # deliberately not a multiple of 32
    lvl = rng.integers(0, 1 << bits, size=m).astype(np.uint32)
    packed = codec.pack_levels(jnp.asarray(lvl), bits)
    assert packed.dtype == jnp.uint32
    assert packed.shape[0] == codec.packed_words(m, bits)
    out = codec.unpack_levels(packed, bits, m)
    np.testing.assert_array_equal(np.asarray(out), lvl)


def test_packing_density_matches_reference():
    # For 32-aligned n, bit-plane words = exactly n*bits/8 bytes — the same
    # payload density as the reference byte packing (compressor.cc:401-419).
    for bits in range(1, 9):
        n = 4096
        assert codec.packed_words(n, bits) * 4 == n * bits // 8
        ours = codec.wire_bytes(n, bits, 512, 4)
        ref = codec.reference_wire_bytes(n, bits, 512, 4)
        assert ours <= ref + 8


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("bits", [2, 4, 8])
@pytest.mark.parametrize("size", [1, 100, 512, 10000])
def test_constant_tensor_exact(dtype, bits, size):
    # Constant buckets: max == min -> unit = 0 -> level 0 -> decode == min.
    x = jnp.full((size,), 3.0, dtype=dtype)
    q = codec.quantize(x, bits, 512)
    y = codec.dequantize(q)
    assert y.dtype == dtype
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


@pytest.mark.parametrize("bits", [1, 2, 3, 4, 5, 6, 7, 8])
@pytest.mark.parametrize("bucket_size", [64, 512, 2048])
@pytest.mark.parametrize("size", [128, 50_000])
def test_error_envelope(bits, bucket_size, size):
    # Deterministic rounding error is at most unit/2 per value (+ float eps).
    x = jnp.linspace(-1.0, 1.0, size, dtype=jnp.float32)
    q = codec.quantize(x, bits, bucket_size)
    y = codec.dequantize(q)
    eff_bucket = min(bucket_size, size)
    step = 2.0 / (size - 1)
    unit = (eff_bucket - 1) * step / ((1 << bits) - 1)
    err = np.max(np.abs(np.asarray(y) - np.asarray(x)))
    assert err <= unit / 2 + 1e-5, (err, unit)


def test_nonaligned_sizes_roundtrip_bounds():
    # Sizes that are not multiples of bucket_size or 32.
    for size in [1, 2, 31, 33, 63, 513, 517, 1025]:
        x = jnp.asarray(np.random.default_rng(size).normal(size=size), jnp.float32)
        q = codec.quantize(x, 4, 64)
        y = np.asarray(codec.dequantize(q))
        xb = np.asarray(x)
        # every decoded value within the bucket range
        assert y.min() >= xb.min() - 1e-6
        assert y.max() <= xb.max() + 1e-6


def test_stochastic_rounding_unbiased():
    key = jax.random.PRNGKey(0)
    x = jnp.full((512,), 0.3, dtype=jnp.float32)
    # Put one 0 and one 1 in the bucket so unit > 0 and 0.3 is between levels.
    x = x.at[0].set(0.0).at[1].set(1.0)
    reps = 200
    keys = jax.random.split(key, reps)

    def roundtrip(k):
        q = codec.quantize(x, 1, 512, stochastic=True, key=k)
        return codec.dequantize(q)

    ys = jax.vmap(roundtrip)(keys)
    mean = np.asarray(ys).mean(axis=0)
    # E[decode] == x for stochastic rounding; tolerance ~ 3*sigma/sqrt(reps)
    np.testing.assert_allclose(mean[2:], 0.3, atol=0.12)


def test_stochastic_requires_key():
    x = jnp.ones((32,), jnp.float32)
    with pytest.raises(ValueError):
        codec.quantize(x, 4, 32, stochastic=True)


def test_dequantize_add_fuses_accumulation():
    x = jnp.linspace(0, 1, 256, dtype=jnp.float32)
    acc = jnp.full((256,), 10.0, jnp.float32)
    q = codec.quantize(x, 8, 64)
    y = codec.dequantize(q)
    y_add = codec.dequantize(q, add_to=acc)
    np.testing.assert_allclose(np.asarray(y_add), np.asarray(y) + 10.0, rtol=1e-6)


def test_skip_incomplete_buckets_residual_exact():
    size = 512 + 37  # 37-element partial bucket carried raw
    x = jnp.asarray(np.random.default_rng(0).normal(size=size), jnp.float32)
    q = codec.quantize(x, 2, 512, skip_incomplete_buckets=True)
    assert q.residual.shape[0] == 37
    y = np.asarray(codec.dequantize(q))
    np.testing.assert_array_equal(y[512:], np.asarray(x)[512:])  # tail exact


def test_dummy_codec_identity():
    x = jnp.asarray(np.random.default_rng(1).normal(size=333), jnp.float32)
    q = codec.quantize_dummy(x)
    y = codec.dequantize_dummy(q)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_quantize_jit_compatible():
    x = jnp.linspace(-1, 1, 2048, dtype=jnp.float32)

    @jax.jit
    def roundtrip(x):
        q = codec.quantize(x, 4, 512)
        return codec.dequantize(q)

    y = roundtrip(x)
    assert y.shape == x.shape


def test_bf16_error_envelope():
    size, bits, bucket = 4096, 4, 512
    x = jnp.linspace(-1.0, 1.0, size, dtype=jnp.bfloat16)
    q = codec.quantize(x, bits, bucket)
    y = codec.dequantize(q)
    assert y.dtype == jnp.bfloat16
    xf = np.asarray(x, np.float32)
    step = 2.0 / (size - 1)
    unit = (bucket - 1) * step / ((1 << bits) - 1)
    # bf16 meta adds ~2^-8 relative slop on unit*level (level <= 15 here).
    err = np.max(np.abs(np.asarray(y, np.float32) - xf))
    assert err <= unit / 2 + 0.02, (err, unit)
