"""Selection semantics of the step-rate projection's measurement intake.

`tools/project_steprate.py` turns BASELINE.json's north star into numbers
by combining measured codec throughputs with the allreduce cost model; the
record-selection rules decide WHICH measurement becomes the headline, so
they are locked here:

* bench.py records win by recency, and reset the qbench best-of race;
* among qbench `current` records, the best throughput at the projection's
  bits/bucket AND the production encode/pack defaults wins — experimental
  knob records (mul encode, butterfly pack) and other codec configs never
  leak into the projection;
* records marked `unresolved` (noise-clamped scan slopes, null metrics)
  are skipped.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import project_steprate as ps  # noqa: E402


@pytest.fixture(autouse=True)
def _production_env(monkeypatch):
    """The selection filter tracks the session env's encode/pack defaults;
    pin them so env mutations from other suite tests can't leak in."""
    monkeypatch.delenv("CGX_CODEC_ENCODE", raising=False)
    monkeypatch.delenv("CGX_PALLAS_PACK", raising=False)


def _write_log(tmp_path, records):
    p = tmp_path / "BENCH_LOG.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in records))
    return str(p)


def _qrec(gbps, *, variant="current", bits=4, bucket=512, encode="div",
          pack="sum", ts="t", **extra):
    rec = {
        "tool": "qbench", "variant": variant, "tc": 16, "mb": 128,
        "bits": bits, "bucket": bucket, "pack": pack, "encode": encode,
        "t_ms": 1.0, "gbps_in": gbps, "ts": ts,
    }
    rec.update(extra)
    return rec


def test_missing_log_falls_back_to_round3_table(tmp_path):
    m = ps.newest_codec_numbers(str(tmp_path / "absent.jsonl"))
    assert m == ps.R3


def test_best_config_matched_record_wins(tmp_path):
    log = _write_log(tmp_path, [
        _qrec(130.5, ts="a"),
        _qrec(93.7, ts="b"),   # worse tile — must not displace the best
    ])
    m = ps.newest_codec_numbers(log)
    assert m["quantize_GBps_in"] == 130.5
    assert "qbench a" in m["provenance"]


def test_experimental_and_mismatched_configs_never_leak(tmp_path):
    log = _write_log(tmp_path, [
        _qrec(120.0, ts="prod"),
        _qrec(999.0, encode="mul", ts="knob"),     # pending-adoption knob
        _qrec(999.0, pack="butterfly", ts="knob2"),
        _qrec(999.0, bits=2, ts="otherbits"),      # different codec config
        _qrec(999.0, bucket=128, ts="otherbucket"),
        _qrec(999.0, variant="nometa", ts="bound"),  # upper-bound variant
    ])
    m = ps.newest_codec_numbers(log, bits=4, bucket=512)
    assert m["quantize_GBps_in"] == 120.0


def test_unresolved_records_skipped(tmp_path):
    log = _write_log(tmp_path, [
        _qrec(None, t_ms=None, ts="noise",
              unresolved="slope <= noise; re-run with a larger --k"),
    ])
    m = ps.newest_codec_numbers(log)
    assert m["quantize_GBps_in"] == ps.R3["quantize_GBps_in"]


def test_bench_record_wins_by_recency_and_resets_race(tmp_path):
    bench = {
        "tool": "bench",
        "detail": {"quantize_GBps": 110.0, "dequantize_GBps": 600.0},
        "ts": "bench-session",
    }
    log = _write_log(tmp_path, [_qrec(130.5, ts="old"), bench,
                                _qrec(125.0, ts="new")])
    m = ps.newest_codec_numbers(log)
    # The later bench session superseded the 130.5 race; the freshest
    # qbench record after it wins again.
    assert m["quantize_GBps_in"] == 125.0
    assert m["dequantize_GBps_out"] == 600.0
    log2 = _write_log(tmp_path, [_qrec(130.5, ts="old"), bench])
    m2 = ps.newest_codec_numbers(log2)
    assert m2["quantize_GBps_in"] == 110.0


@pytest.mark.parametrize("ws", [2, 8, 32])
def test_projection_rows_shape_and_monotonicity(ws):
    rows = ps.project(473 * 2**20, ws, 4, 512, dict(ps.R3))
    assert len(rows) == len(ps.REGIMES)
    # fp32 step time strictly decreases as the interconnect gets faster
    # (pairwise-strict: a constant list must fail — it would mean the
    # bandwidth term dropped out of the cost model).
    fp32 = [r["fp32_step_ms"] for r in rows]
    assert all(a > b for a, b in zip(fp32, fp32[1:]))
    for r in rows:
        assert r["speedup"] == pytest.approx(
            r["fp32_step_ms"] / r["q_step_ms"], abs=0.01
        )
