"""Cross-rank trace timeline tests (ISSUE 3 tentpole + satellites).

Covers the span layer (inert without ``CGX_METRICS_DIR``, span/instant
records with monotonic clocks and thread track metadata, flush-on-raise),
its hot-path emitters (``trace_span``, the shm channel's put/take with
message keys), the ``tools/cgx_trace.py`` merger (torn-file tolerance,
clock-offset estimation on synthetic skewed ranks, Chrome trace-event
schema validity, cross-rank flow links) and the acceptance 2-rank bridge
run: per-rank span JSONL -> one ``trace.json`` with >= 1 cross-rank flow
per collective plus a step-time attribution table.
"""

from __future__ import annotations

import importlib.util
import json
import multiprocessing as mp
import os
import subprocess
import sys
import tempfile
import time
import traceback

import numpy as np
import pytest

from torch_cgx_tpu.observability import flightrec, timeline
from torch_cgx_tpu.robustness import faults
from torch_cgx_tpu.utils.logging import metrics

from test_faults import FakeStore, _channel_pair

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CGX_TRACE = os.path.join(_REPO, "tools", "cgx_trace.py")

pytestmark = pytest.mark.faults


def _load_cgx_trace():
    spec = importlib.util.spec_from_file_location("cgx_trace", _CGX_TRACE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh():
    faults.reset_injectors()
    metrics.reset()
    flightrec.reset()
    timeline.reset()
    yield
    faults.reset_injectors()
    metrics.reset()
    flightrec.reset()
    timeline.reset()


# ---------------------------------------------------------------------------
# Span layer core.
# ---------------------------------------------------------------------------


def test_timeline_inert_without_dir(tmp_path):
    assert not timeline.enabled()
    with timeline.span("op", timeline.CAT_COLLECTIVE, seq=1):
        pass
    timeline.instant("ev")
    timeline.record("x", timeline.CAT_WIRE, 0.0, 1.0)
    tl = timeline.get_timeline()
    assert tl._buf == []  # nothing buffered: the clean path records nothing
    timeline.flush()
    assert list(tmp_path.iterdir()) == []


def test_timeline_span_flush_and_meta(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    timeline.set_rank(3)
    with timeline.span("allreduce", timeline.CAT_COLLECTIVE, seq=7):
        time.sleep(0.005)
    timeline.instant("allreduce_group", bits=4)
    timeline.flush()
    path = tmp_path / "spans-rank3.jsonl"
    assert path.exists()
    lines = [json.loads(l) for l in open(path)]
    meta, events = lines[0], lines[1:]
    assert meta["kind"] == "meta" and meta["rank"] == 3
    assert "mono_wall_delta" in meta and "pid" in meta
    spans = [e for e in events if e["kind"] == "span"]
    assert spans and spans[0]["name"] == "allreduce"
    assert spans[0]["cat"] == "collective" and spans[0]["seq"] == 7
    assert spans[0]["dur_s"] >= 0.005
    assert isinstance(spans[0]["t_mono"], float)
    assert spans[0]["tid"] and spans[0]["tname"]
    instants = [e for e in events if e["kind"] == "instant"]
    assert instants and instants[0]["name"] == "allreduce_group"
    assert instants[0]["bits"] == 4
    # a second flush appends without duplicating the meta header
    with timeline.span("broadcast", timeline.CAT_COLLECTIVE, seq=8):
        pass
    timeline.flush()
    lines2 = [json.loads(l) for l in open(path)]
    assert sum(1 for l in lines2 if l["kind"] == "meta") == 1


def test_timeline_span_records_on_raise(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    timeline.set_rank(0)
    with pytest.raises(RuntimeError):
        with timeline.span("failing", timeline.CAT_COLLECTIVE, seq=1):
            raise RuntimeError("boom")
    timeline.flush()
    lines = [json.loads(l) for l in open(tmp_path / "spans-rank0.jsonl")]
    spans = [e for e in lines if e.get("kind") == "span"]
    assert spans and spans[0]["name"] == "failing"
    assert spans[0]["ok"] is False


def test_trace_span_emits_timeline(tmp_path, monkeypatch):
    from torch_cgx_tpu.utils.tracing import trace_span

    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    timeline.set_rank(0)
    with trace_span("grad_sync"):
        pass
    timeline.flush()
    lines = [json.loads(l) for l in open(tmp_path / "spans-rank0.jsonl")]
    spans = [e for e in lines if e.get("kind") == "span"]
    assert any(
        s["name"] == "grad_sync" and s["cat"] == "span" and s["ok"]
        for s in spans
    )


def test_shm_channel_emits_keyed_spans(tmp_path, monkeypatch):
    mdir = tmp_path / "m"
    monkeypatch.setenv("CGX_METRICS_DIR", str(mdir))
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("cgx1q/s0>1", np.ones(4096, np.uint8).tobytes())
        reader.take("cgx1q/s0>1")
    finally:
        writer.close()
        reader.close()
    timeline.flush()
    # both channels share the process singleton: rank 0 (first bind) wins
    lines = [json.loads(l) for l in open(mdir / "spans-rank0.jsonl")]
    by_name = {}
    for e in lines:
        if e.get("kind") == "span":
            by_name.setdefault(e["name"], e)
    assert by_name["shm.put"]["key"] == "cgx1q/s0>1"
    assert by_name["shm.put"]["cat"] == "wire"
    assert by_name["shm.put"]["bytes"] >= 4096
    assert by_name["shm.take.wait"]["cat"] == "wait"
    assert by_name["shm.take.copy"]["key"] == "cgx1q/s0>1"


def test_failed_take_wait_still_leaves_span(tmp_path, monkeypatch):
    # The interval that ends in BridgeTimeoutError is exactly what the
    # trace exists to show: the victim's wait must appear, ok=False.
    from torch_cgx_tpu.robustness import BridgeTimeoutError

    mdir = tmp_path / "m"
    monkeypatch.setenv("CGX_METRICS_DIR", str(mdir))
    monkeypatch.setenv("CGX_BRIDGE_TIMEOUT_MS", "200")
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        with pytest.raises(BridgeTimeoutError):
            reader.take("never-posted")
    finally:
        writer.close()
        reader.close()
    timeline.flush()
    lines = [json.loads(l) for l in open(mdir / "spans-rank0.jsonl")]
    waits = [
        e for e in lines
        if e.get("kind") == "span" and e["name"] == "shm.take.wait"
    ]
    assert waits and waits[-1]["ok"] is False
    assert waits[-1]["key"] == "never-posted"
    assert waits[-1]["dur_s"] >= 0.2  # the full timed-out wait interval


# ---------------------------------------------------------------------------
# Merger: offsets, schema, flows, torn files.
# ---------------------------------------------------------------------------


def _synthetic_rank_file(path, rank, events, delta=1000.0):
    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": "meta", "rank": rank, "pid": 100 + rank,
            "t_mono": 0.0, "t_wall": delta, "mono_wall_delta": delta,
        }) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _span(name, cat, t, dur, **kw):
    return {"kind": "span", "name": name, "cat": cat, "t_mono": t,
            "dur_s": dur, "tid": 1, "tname": "cgx-worker", **kw}


def test_clock_offset_estimator_synthetic_skew():
    cgx_trace = _load_cgx_trace()
    skew = 5.0  # rank 1's perf_counter runs 5 s ahead of rank 0's
    lat = 0.001  # symmetric one-way latency
    per_rank = {0: {"meta": None, "events": []},
                1: {"meta": None, "events": []}}
    for i in range(4):
        t = 10.0 + i
        # rank 0 -> rank 1: published at t (rank0 clock), header arrives
        # lat later (true time), i.e. t + lat + skew on rank 1's clock.
        per_rank[0]["events"].append(
            _span("shm.put", "wire", t, 0.0, key=f"a{i}"))
        per_rank[1]["events"].append(
            _span("shm.take.wait", "wait", t + lat + skew, 0.0, key=f"a{i}"))
        # rank 1 -> rank 0
        per_rank[1]["events"].append(
            _span("shm.put", "wire", t + 0.5 + skew, 0.0, key=f"b{i}"))
        per_rank[0]["events"].append(
            _span("shm.take.wait", "wait", t + 0.5 + lat, 0.0, key=f"b{i}"))
    offsets = cgx_trace.estimate_offsets(per_rank)
    assert offsets[0] == 0.0
    # recovered correction maps rank 1's clock back onto rank 0's:
    # off_1 ~= -skew, within the one-way latency
    assert abs(offsets[1] + skew) <= lat + 1e-9


def test_clock_offset_fallback_uses_meta_delta(tmp_path):
    cgx_trace = _load_cgx_trace()
    # no message pairs at all: fall back to wall-clock deltas
    _synthetic_rank_file(
        tmp_path / "spans-rank0.jsonl", 0,
        [_span("allreduce", "collective", 1.0, 0.1, seq=1)], delta=1000.0)
    _synthetic_rank_file(
        tmp_path / "spans-rank1.jsonl", 1,
        [_span("allreduce", "collective", 2.0, 0.1, seq=1)], delta=997.5)
    per_rank = cgx_trace.load_spans(str(tmp_path))
    offsets = cgx_trace.estimate_offsets(per_rank)
    assert offsets[0] == 0.0
    assert offsets[1] == pytest.approx(-2.5)


def _validate_chrome_trace(trace):
    """Minimal Chrome trace-event schema check (the contract
    ui.perfetto.dev / chrome://tracing load by)."""
    assert isinstance(trace, dict) and isinstance(
        trace["traceEvents"], list
    )
    flow_open = {}
    for ev in trace["traceEvents"]:
        assert isinstance(ev.get("name"), str) and ev["name"]
        ph = ev.get("ph")
        assert ph in ("X", "i", "M", "s", "f"), ph
        assert isinstance(ev.get("pid"), int)
        if ph == "M":
            assert ev["name"] in (
                "process_name", "process_sort_index", "thread_name"
            )
            assert "args" in ev
            continue
        assert isinstance(ev.get("ts"), (int, float)) and ev["ts"] >= 0
        assert isinstance(ev.get("tid"), int)
        if ph == "X":
            assert isinstance(ev.get("dur"), (int, float)) and ev["dur"] > 0
        if ph == "i":
            assert ev.get("s") in ("g", "p", "t")
        if ph == "s":
            flow_open.setdefault(ev["id"], []).append(ev)
        if ph == "f":
            assert ev.get("bp") == "e"
            assert ev["id"] in flow_open, "flow finish without start"
            src = flow_open[ev["id"]][0]
            assert ev["ts"] >= src["ts"], "flow arrow goes back in time"
    return flow_open


def test_cgx_trace_merges_flows_and_attribution(tmp_path):
    # Two synthetic ranks exchanging one SRA round (seq 1) and its
    # shm messages, plus codec/wait spans for the attribution buckets.
    ev0 = [
        _span("allreduce", "collective", 1.0, 0.5, seq=1, ok=True),
        _span("codec.compress", "quantize", 1.05, 0.08, elems=1024),
        _span("shm.put", "wire", 1.15, 0.02, key="cgx1q/s0>1", bytes=512),
        _span("shm.take.wait", "wait", 1.2, 0.1, key="cgx1q/s1>0"),
        _span("shm.take.copy", "wire", 1.3, 0.01, key="cgx1q/s1>0",
              bytes=512),
        {"kind": "instant", "name": "allreduce_group", "cat": "trace",
         "t_mono": 0.9, "tid": 1, "tname": "MainThread", "bits": 4},
    ]
    ev1 = [
        _span("allreduce", "collective", 1.02, 0.5, seq=1, ok=True),
        _span("shm.put", "wire", 1.1, 0.02, key="cgx1q/s1>0", bytes=512),
        _span("shm.take.wait", "wait", 1.18, 0.1, key="cgx1q/s0>1"),
        _span("shm.take.copy", "wire", 1.28, 0.01, key="cgx1q/s0>1",
              bytes=512),
    ]
    _synthetic_rank_file(tmp_path / "spans-rank0.jsonl", 0, ev0)
    _synthetic_rank_file(tmp_path / "spans-rank1.jsonl", 1, ev1)
    proc = subprocess.run(
        [sys.executable, _CGX_TRACE, str(tmp_path), "--json"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["ranks"] == [0, 1]
    assert report["cross_rank_flows"] >= 3  # 1 collective + 2 msg flows
    assert report["per_op"]["allreduce"]["count"] == 2
    att0 = report["per_rank"]["0"]
    assert att0["quantize"] == pytest.approx(0.08)
    assert att0["wire"] == pytest.approx(0.03)
    assert att0["wait"] == pytest.approx(0.1)
    assert att0["other"] == pytest.approx(0.5 - 0.08 - 0.03 - 0.1)
    trace = json.load(open(tmp_path / "trace.json"))
    flow_open = _validate_chrome_trace(trace)
    assert flow_open  # at least one flow pair survived validation
    # the human report renders the attribution table
    proc = subprocess.run(
        [sys.executable, _CGX_TRACE, str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0
    assert "step-time attribution" in proc.stdout
    assert "queue-wait" in proc.stdout


def test_cgx_trace_tolerates_torn_span_file(tmp_path):
    _synthetic_rank_file(
        tmp_path / "spans-rank0.jsonl", 0,
        [_span("allreduce", "collective", 1.0, 0.1, seq=1)])
    with open(tmp_path / "spans-rank1.jsonl", "w") as f:
        f.write(json.dumps({"kind": "meta", "rank": 1, "pid": 2,
                            "t_mono": 0.0, "t_wall": 0.0,
                            "mono_wall_delta": 0.0}) + "\n")
        f.write(json.dumps(_span("allreduce", "collective", 1.0, 0.1,
                                 seq=1)) + "\n")
        f.write('{"kind": "span", "name": "allr')  # killed mid-write
    proc = subprocess.run(
        [sys.executable, _CGX_TRACE, str(tmp_path), "--json"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["per_op"]["allreduce"]["count"] == 2  # torn line dropped
    _validate_chrome_trace(json.load(open(tmp_path / "trace.json")))


def test_cgx_trace_empty_dir(tmp_path):
    proc = subprocess.run(
        [sys.executable, _CGX_TRACE, str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 1
    assert "no spans" in proc.stderr


# ---------------------------------------------------------------------------
# Acceptance: 2-rank bridge run -> merged trace with cross-rank flow links
# per collective + attribution table (reuses the faults-harness pattern).
# ---------------------------------------------------------------------------


def _trace_rank_main(rank: int, ws: int, initfile: str, mdir: str, q) -> None:
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, _REPO)
        os.environ["CGX_METRICS_DIR"] = mdir
        os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
        os.environ["CGX_BRIDGE_TIMEOUT_MS"] = "60000"
        # Chaos seasoning (the faults-marker harness): injected take
        # latency must show up as longer wait spans, not break the
        # timeline or the merge.
        os.environ["CGX_FAULTS"] = "delay_take:10ms"
        import torch
        import torch.distributed as dist
        import torch_cgx_tpu.torch_backend  # noqa: F401 — registers "cgx"

        dist.init_process_group(
            "cgx", init_method=f"file://{initfile}", rank=rank,
            world_size=ws,
        )
        t = torch.full((8192,), float(rank + 1))
        for _ in range(2):
            dist.all_reduce(t)
        dist.broadcast(t, src=0)
        dist.barrier()
        dist.destroy_process_group()
        q.put((rank, None))
    except Exception:
        q.put((rank, traceback.format_exc()))


@pytest.mark.torch_bridge
def test_two_rank_chaos_run_merges_into_chrome_trace(tmp_path):
    mdir = str(tmp_path / "metrics")
    initfile = tempfile.mktemp(prefix="cgx_trace_store_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_trace_rank_main, args=(r, 2, initfile, mdir, q))
        for r in range(2)
    ]
    for p in procs:
        p.start()
    errs = [q.get(timeout=180) for _ in procs]
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    if os.path.exists(initfile):
        os.unlink(initfile)
    for rank, err in errs:
        assert err is None, f"rank {rank}: {err}"
    # per-rank span JSONL exists for both ranks
    for r in range(2):
        assert os.path.exists(os.path.join(mdir, f"spans-rank{r}.jsonl")), (
            os.listdir(mdir)
        )
    proc = subprocess.run(
        [sys.executable, _CGX_TRACE, mdir, "--json"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["ranks"] == [0, 1]
    # every collective both ranks ran is cross-rank linked: 2 allreduces
    # + broadcast + barrier => at least 4 collective flow links
    trace = json.load(open(os.path.join(mdir, "trace.json")))
    _validate_chrome_trace(trace)
    coll_flow_starts = [
        ev for ev in trace["traceEvents"]
        if ev.get("ph") == "s" and ev.get("cat") == "flow.collective"
    ]
    linked_ops = {ev["name"].split("#")[0] for ev in coll_flow_starts}
    assert {"allreduce", "broadcast", "barrier"} <= linked_ops, linked_ops
    assert len(coll_flow_starts) >= 4
    assert report["cross_rank_flows"] >= 4
    # the attribution decomposition saw quantized work and waits
    for r in ("0", "1"):
        att = report["per_rank"][r]
        assert att["collective"] > 0
        assert att["quantize"] > 0
        assert att["wire"] > 0
    assert report["per_op"]["allreduce"]["count"] == 4  # 2 ops x 2 ranks
    # human-readable attribution table renders
    proc = subprocess.run(
        [sys.executable, _CGX_TRACE, mdir],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0
    assert "step-time attribution" in proc.stdout


def test_attribution_overlap_fraction_from_synthetic_spans(tmp_path):
    # ISSUE 6 satellite: overlap fraction = share of collective wall time
    # during which recorded trace_span compute was simultaneously live —
    # computed on interval unions so nested spans don't double-count.
    cgx_trace = _load_cgx_trace()
    ev0 = [
        _span("allreduce", "collective", 1.0, 0.5, seq=1),
        _span("allreduce", "collective", 2.0, 0.5, seq=2),
        # compute overlapping [1.25, 1.5) -> 0.25 s
        _span("fwd", "span", 1.25, 0.5),
        # compute overlapping [2.0, 2.1) -> 0.1 s ...
        _span("bwd", "span", 1.9, 0.2),
        # ... with a nested span inside the same window (union: no change)
        _span("bwd.inner", "span", 2.0, 0.05),
    ]
    ev1 = [_span("allreduce", "collective", 1.0, 1.0, seq=1)]
    _synthetic_rank_file(tmp_path / "spans-rank0.jsonl", 0, ev0)
    _synthetic_rank_file(tmp_path / "spans-rank1.jsonl", 1, ev1)
    proc = subprocess.run(
        [sys.executable, _CGX_TRACE, str(tmp_path), "--json"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    # (0.25 + 0.1) s hidden under compute of 1.0 s collective time
    assert report["per_rank"]["0"]["overlap_frac"] == pytest.approx(0.35)
    # no recorded compute at all -> fully serialized communication
    assert report["per_rank"]["1"]["overlap_frac"] == 0.0
    # the human table carries the new column
    proc = subprocess.run(
        [sys.executable, _CGX_TRACE, str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0
    assert "overlap" in proc.stdout
