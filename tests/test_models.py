"""Model zoo smoke tests: forward shapes, grad step, compressed-DP training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torch_cgx_tpu.models import (
    GPT2,
    Bert,
    BertConfig,
    GPT2Config,
    ResNet18,
    ResNet50,
    ViT,
    ViTConfig,
    lm_loss,
    mlm_loss,
)


def test_resnet18_forward_and_grad():
    model = ResNet18(num_classes=10, cifar_stem=True)
    x = jnp.zeros((4, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (4, 10)

    def loss_fn(params):
        out, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        return jnp.mean(out**2)

    g = jax.grad(loss_fn)(variables["params"])
    assert jax.tree_util.tree_structure(g) == jax.tree_util.tree_structure(
        variables["params"]
    )


# Slow tier: depth-scaling rerun of the resnet18 coverage above.
@pytest.mark.slow
def test_resnet50_forward():
    model = ResNet50(num_classes=100, cifar_stem=False)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert model.apply(variables, x, train=False).shape == (2, 100)


def test_gpt2_forward_loss_grad():
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32))
    )
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    logits = model.apply({"params": params}, toks)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert logits.dtype == jnp.float32

    def loss(p):
        return lm_loss(model.apply({"params": p}, toks), toks)

    l0 = float(loss(params))
    assert np.isfinite(l0) and l0 < 2 * np.log(cfg.vocab_size)
    g = jax.grad(loss)(params)
    assert jnp.isfinite(g["wte"]["embedding"]).all()


def test_bert_mlm():
    cfg = BertConfig.tiny()
    model = Bert(cfg)
    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 24))
    )
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    logits = model.apply({"params": params}, toks)
    assert logits.shape == (2, 24, cfg.vocab_size)
    mask = jnp.zeros((2, 24)).at[:, :4].set(1.0)
    l = mlm_loss(logits, toks, mask)
    assert np.isfinite(float(l))


def test_vit_forward():
    cfg = ViTConfig.tiny()
    model = ViT(cfg)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    assert model.apply({"params": params}, x).shape == (2, 10)


@pytest.mark.slow
def test_gpt2_compressed_dp_training(monkeypatch):
    """End-to-end: tiny GPT-2, 8 devices, 4-bit grads, loss decreases."""
    import os

    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.parallel import (
        flat_mesh,
        make_train_step,
        replicate,
        shard_batch,
    )

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, "512")
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    # learnable data: repeated pattern
    data = np.tile(np.arange(32) % 64, (64, 1)).astype(np.int32)
    mesh = flat_mesh()
    params = replicate(
        model.init(jax.random.PRNGKey(0), jnp.asarray(data[:2]))["params"], mesh
    )
    opt = optax.adam(1e-2)
    opt_state = replicate(opt.init(params), mesh)

    def loss_fn(p, batch):
        return lm_loss(model.apply({"params": p}, batch), batch)

    step = make_train_step(loss_fn, opt, mesh, donate=False)
    losses = []
    for i in range(12):
        batch = shard_batch(jnp.asarray(data), mesh)
        params, opt_state, loss = step(params, opt_state, batch, jnp.int32(i))
        losses.append(float(loss))
    assert losses[-1] < 0.6 * losses[0], losses


def test_bert_compressed_dp_training(monkeypatch):
    """BASELINE.md config row: BERT fine-tune DDP at 8-bit with the
    layer_min_size filter keeping LN/bias raw — loss must fall."""
    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.parallel import (
        flat_mesh,
        make_train_step,
        replicate,
        shard_batch,
    )

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "8")
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, "512")
    cfg = BertConfig.tiny()
    model = Bert(cfg)
    # learnable MLM data: predictable token pattern, mask every 4th position
    tokens = np.tile(np.arange(32) % 50, (16, 1)).astype(np.int32)
    mask = np.zeros_like(tokens)
    mask[:, ::4] = 1
    inputs = np.where(mask == 1, 3, tokens).astype(np.int32)  # 3 = [MASK]
    mesh = flat_mesh()
    params = replicate(
        model.init(jax.random.PRNGKey(0), jnp.asarray(inputs[:2]))["params"],
        mesh,
    )
    opt = optax.adam(2e-2)
    opt_state = replicate(opt.init(params), mesh)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        return mlm_loss(logits, batch["y"], batch["m"])

    step = make_train_step(loss_fn, opt, mesh, donate=False)
    batch = {
        "x": jnp.asarray(inputs),
        "y": jnp.asarray(tokens),
        "m": jnp.asarray(mask.astype(np.float32)),
    }
    losses = []
    for i in range(10):
        params, opt_state, loss = step(
            params, opt_state, shard_batch(batch, mesh), jnp.int32(i)
        )
        losses.append(float(loss))
    assert losses[-1] < 0.6 * losses[0], losses


def test_vit_hierarchical_compressed_training(monkeypatch):
    """BASELINE.md config row: ViT with the INTRA_BROADCAST hierarchical
    allreduce (2x4 cross x intra mesh), 4-bit — loss must fall and replicas
    stay in sync."""
    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.parallel import (
        CROSS_AXIS,
        INTRA_AXIS,
        hierarchical_mesh,
        make_train_step,
        replicate,
        shard_batch,
    )

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.INTRA_BROADCAST, "1")
    cfg = ViTConfig.tiny()
    model = ViT(cfg)
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, size=32).astype(np.int32)
    templates = rng.normal(size=(10, 32, 32, 3)).astype(np.float32)
    images = templates[labels] + 0.1 * rng.normal(
        size=(32, 32, 32, 3)
    ).astype(np.float32)
    mesh = hierarchical_mesh(intra_size=4)
    axes = (CROSS_AXIS, INTRA_AXIS)
    params = replicate(
        model.init(jax.random.PRNGKey(0), jnp.asarray(images[:2]))["params"],
        mesh,
    )
    opt = optax.adam(2e-3)
    opt_state = replicate(opt.init(params), mesh)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        onehot = jax.nn.one_hot(batch["y"], 10)
        return optax.softmax_cross_entropy(logits, onehot).mean()

    step = make_train_step(loss_fn, opt, mesh, axes=axes, donate=False)
    batch = {"x": jnp.asarray(images), "y": jnp.asarray(labels)}
    losses = []
    for i in range(10):
        params, opt_state, loss = step(
            params, opt_state, shard_batch(batch, mesh, axes), jnp.int32(i)
        )
        losses.append(float(loss))
    assert losses[-1] < 0.7 * losses[0], losses
    # Error symmetry: replicated params identical on every device.
    leaf = jax.tree.leaves(params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(s, shards[0])


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason=(
        "container limitation (jax pinned at 0.4.x): partial-auto "
        "shard_map — manual over dp, GSPMD over tp — cannot run the "
        "quantized reducers on this runtime. Root cause, reproduced "
        "minimally: (a) lax.axis_index of a manual axis lowers to a bare "
        "PartitionId instruction, which the SPMD partitioner rejects "
        "('PartitionId instruction is not supported for SPMD "
        "partitioning'); (b) even with axis_index routed around, the "
        "SRA/Ring collectives (all_to_all, ppermute) inside the "
        "partial-auto region hit a FATAL XLA check "
        "(hlo_sharding_util.cc IsManualSubgroup) and abort the process. "
        "Both are fixed in the modern jax.shard_map lowering this "
        "codebase targets (utils/compat.py); the test runs wherever "
        "jax.shard_map exists."
    ),
)
def test_tp_sharding_survives_train_step(monkeypatch):
    """make_train_step leaves non-sync mesh axes to GSPMD: tensor-parallel
    parameter shardings must SURVIVE the step (review r3: in_specs=P() on a
    fully-manual shard_map silently gathered tp-sharded params to
    replicated, so tp did duplicate work forever after)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.models import GPT2, GPT2Config, lm_loss
    from torch_cgx_tpu.models.gpt2 import tp_param_spec
    from torch_cgx_tpu.parallel import make_train_step, shard_batch
    from torch_cgx_tpu.utils.tree import path_str

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(8, 32)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), tokens[:1])["params"]

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = [tp_param_spec(path_str(p), l) for p, l in flat]
    params = jax.tree_util.tree_unflatten(
        treedef,
        [
            jax.device_put(l, NamedSharding(mesh, s))
            for (p, l), s in zip(flat, specs)
        ],
    )
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, batch):
        return lm_loss(model.apply({"params": p}, batch), batch)

    step = make_train_step(loss_fn, opt, mesh, axes=("dp",), donate=False)
    p2, opt_state, loss = step(
        params, opt_state, shard_batch(tokens, mesh, ("dp",)), jnp.int32(0)
    )
    assert np.isfinite(float(loss))

    # Every tp-sharded leaf must still be sharded over tp afterwards.
    flat2 = jax.tree_util.tree_flatten_with_path(p2)[0]
    checked = 0
    for ((path, leaf), spec) in zip(flat2, specs):
        if spec and any(ax == "tp" for ax in jax.tree.leaves(tuple(spec))):
            got = leaf.sharding.spec
            assert "tp" in str(got), (path_str(path), got)
            checked += 1
    assert checked >= 4, f"only {checked} tp-sharded leaves found"
