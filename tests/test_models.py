"""Model zoo smoke tests: forward shapes, grad step, compressed-DP training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torch_cgx_tpu.models import (
    GPT2,
    Bert,
    BertConfig,
    GPT2Config,
    ResNet18,
    ResNet50,
    ViT,
    ViTConfig,
    lm_loss,
    mlm_loss,
)


def test_resnet18_forward_and_grad():
    model = ResNet18(num_classes=10, cifar_stem=True)
    x = jnp.zeros((4, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (4, 10)

    def loss_fn(params):
        out, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        return jnp.mean(out**2)

    g = jax.grad(loss_fn)(variables["params"])
    assert jax.tree_util.tree_structure(g) == jax.tree_util.tree_structure(
        variables["params"]
    )


def test_resnet50_forward():
    model = ResNet50(num_classes=100, cifar_stem=False)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert model.apply(variables, x, train=False).shape == (2, 100)


def test_gpt2_forward_loss_grad():
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32))
    )
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    logits = model.apply({"params": params}, toks)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert logits.dtype == jnp.float32

    def loss(p):
        return lm_loss(model.apply({"params": p}, toks), toks)

    l0 = float(loss(params))
    assert np.isfinite(l0) and l0 < 2 * np.log(cfg.vocab_size)
    g = jax.grad(loss)(params)
    assert jnp.isfinite(g["wte"]["embedding"]).all()


def test_bert_mlm():
    cfg = BertConfig.tiny()
    model = Bert(cfg)
    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 24))
    )
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    logits = model.apply({"params": params}, toks)
    assert logits.shape == (2, 24, cfg.vocab_size)
    mask = jnp.zeros((2, 24)).at[:, :4].set(1.0)
    l = mlm_loss(logits, toks, mask)
    assert np.isfinite(float(l))


def test_vit_forward():
    cfg = ViTConfig.tiny()
    model = ViT(cfg)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    assert model.apply({"params": params}, x).shape == (2, 10)


@pytest.mark.slow
def test_gpt2_compressed_dp_training(monkeypatch):
    """End-to-end: tiny GPT-2, 8 devices, 4-bit grads, loss decreases."""
    import os

    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.parallel import (
        flat_mesh,
        make_train_step,
        replicate,
        shard_batch,
    )

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, "512")
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    # learnable data: repeated pattern
    data = np.tile(np.arange(32) % 64, (64, 1)).astype(np.int32)
    mesh = flat_mesh()
    params = replicate(
        model.init(jax.random.PRNGKey(0), jnp.asarray(data[:2]))["params"], mesh
    )
    opt = optax.adam(1e-2)
    opt_state = replicate(opt.init(params), mesh)

    def loss_fn(p, batch):
        return lm_loss(model.apply({"params": p}, batch), batch)

    step = make_train_step(loss_fn, opt, mesh, donate=False)
    losses = []
    for i in range(12):
        batch = shard_batch(jnp.asarray(data), mesh)
        params, opt_state, loss = step(params, opt_state, batch, jnp.int32(i))
        losses.append(float(loss))
    assert losses[-1] < 0.6 * losses[0], losses
