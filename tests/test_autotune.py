"""Codec autotune cache: hit/miss accounting, on-disk persistence,
invalidation (incl. supervisor.invalidate_trace_caches), mode gating,
and that tuned entries actually steer the kernels without changing
bytes."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from torch_cgx_tpu.ops import autotune, codec_pallas


@pytest.fixture(autouse=True)
def _fresh_memo():
    autotune.invalidate("test setup")
    yield
    autotune.invalidate("test teardown")


def _tuned_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_AUTOTUNE_DIR", str(tmp_path))
    return tmp_path


def test_lookup_miss_counts_and_returns_none(tmp_path, monkeypatch):
    _tuned_dir(tmp_path, monkeypatch)
    assert autotune.lookup(
        autotune.KIND_FLAT, n_chunks=64, bucket_size=512, bits=4
    ) is None
    s = autotune.stats()
    assert s["misses"] == 1 and s["hits"] == 0


def test_record_then_hit(tmp_path, monkeypatch):
    _tuned_dir(tmp_path, monkeypatch)
    autotune.record(
        autotune.KIND_FLAT, autotune.TunedConfig(tc=4, pack="butterfly"),
        n_chunks=64, bucket_size=512, bits=4,
    )
    hit = autotune.lookup(
        autotune.KIND_FLAT, n_chunks=64, bucket_size=512, bits=4
    )
    assert hit is not None and hit.tc == 4 and hit.pack == "butterfly"
    assert autotune.stats()["hits"] == 1
    # A different shape is a different key.
    assert autotune.lookup(
        autotune.KIND_FLAT, n_chunks=128, bucket_size=512, bits=4
    ) is None


def test_persistence_across_invalidation(tmp_path, monkeypatch):
    """record() persists to disk; invalidate() drops the memo; the next
    lookup reloads the persisted entry (a fresh process would too)."""
    _tuned_dir(tmp_path, monkeypatch)
    autotune.record(
        autotune.KIND_EPILOGUE, autotune.TunedConfig(tc=2, db=True),
        n_chunks=8, bucket_size=512, bits=4, ws=4,
    )
    path = autotune.cache_path()
    assert path.exists()
    autotune.invalidate("simulated restart")
    assert autotune.stats()["hits"] == 0
    hit = autotune.lookup(
        autotune.KIND_EPILOGUE, n_chunks=8, bucket_size=512, bits=4, ws=4
    )
    assert hit is not None and hit.tc == 2 and hit.db is True
    assert autotune.stats()["loads"] == 1


def test_supervisor_invalidate_trace_caches_drops_memo(tmp_path, monkeypatch):
    _tuned_dir(tmp_path, monkeypatch)
    autotune.record(
        autotune.KIND_FLAT, autotune.TunedConfig(tc=8),
        n_chunks=32, bucket_size=512, bits=4, persist=False,
    )
    assert autotune.lookup(
        autotune.KIND_FLAT, n_chunks=32, bucket_size=512, bits=4
    ) is not None
    from torch_cgx_tpu.robustness import supervisor

    supervisor.invalidate_trace_caches()
    # persist=False: the entry lived only in the memo — gone now.
    assert autotune.stats()["hits"] == 0
    assert autotune.lookup(
        autotune.KIND_FLAT, n_chunks=32, bucket_size=512, bits=4
    ) is None


def test_mode_off_never_consults(tmp_path, monkeypatch):
    _tuned_dir(tmp_path, monkeypatch)
    autotune.record(
        autotune.KIND_FLAT, autotune.TunedConfig(tc=4),
        n_chunks=64, bucket_size=512, bits=4,
    )
    monkeypatch.setenv("CGX_AUTOTUNE", "off")
    assert autotune.lookup(
        autotune.KIND_FLAT, n_chunks=64, bucket_size=512, bits=4
    ) is None


def test_corrupt_cache_file_tolerated(tmp_path, monkeypatch):
    _tuned_dir(tmp_path, monkeypatch)
    autotune.cache_path().parent.mkdir(parents=True, exist_ok=True)
    autotune.cache_path().write_text("{not json")
    assert autotune.lookup(
        autotune.KIND_FLAT, n_chunks=64, bucket_size=512, bits=4
    ) is None  # no raise
    # and a half-valid document keeps its parseable entries
    autotune.invalidate("reset")
    doc = {"entries": {
        "flat/c64/b512/q4/w0/ediv": {"tc": 4},
        "garbage": {"tc": "x"},
    }}
    autotune.cache_path().write_text(json.dumps(doc))
    hit = autotune.lookup(
        autotune.KIND_FLAT, n_chunks=64, bucket_size=512, bits=4
    )
    assert hit is not None and hit.tc == 4


def test_tune_skips_failing_candidates(tmp_path, monkeypatch):
    _tuned_dir(tmp_path, monkeypatch)

    def measure(cand):
        if cand.tc == 8:
            raise RuntimeError("mosaic wedge")  # the tc=32 lesson
        return 0.5 if cand.tc == 4 else 1.0

    win = autotune.tune(
        autotune.KIND_CHUNKS,
        [autotune.TunedConfig(tc=t) for t in (2, 4, 8)],
        measure,
        n_chunks=64, bucket_size=512, bits=4, input_bytes=10**9,
    )
    assert win is not None and win.tc == 4 and win.gbps == pytest.approx(2.0)
    assert autotune.lookup(
        autotune.KIND_CHUNKS, n_chunks=64, bucket_size=512, bits=4
    ).tc == 4


def test_env_fingerprint_separates_encode_eras(tmp_path, monkeypatch):
    _tuned_dir(tmp_path, monkeypatch)
    autotune.record(
        autotune.KIND_FLAT, autotune.TunedConfig(tc=4),
        n_chunks=64, bucket_size=512, bits=4,
    )
    monkeypatch.setenv("CGX_CODEC_ENCODE", "mul")
    assert autotune.lookup(
        autotune.KIND_FLAT, n_chunks=64, bucket_size=512, bits=4
    ) is None


def test_snap_to_divisor():
    assert autotune.snap_to_divisor(16, 48, 64) == 16
    assert autotune.snap_to_divisor(10, 48, 64) == 8
    assert autotune.snap_to_divisor(100, 48, 7) == 6
    assert autotune.snap_to_divisor(0, 48, 64) == 1


def test_tuned_tc_steers_kernel_without_changing_bytes(tmp_path, monkeypatch):
    """A tuned flat-kernel tile changes the grid, never the wire: the
    deterministic payload is tc-invariant (packing is per-chunk)."""
    _tuned_dir(tmp_path, monkeypatch)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 4 * 32 * 512)), jnp.float32)
    q_default = codec_pallas.quantize_batch(x, 4, 512, interpret=True)
    autotune.record(
        autotune.KIND_FLAT, autotune.TunedConfig(tc=2),
        n_chunks=8, bucket_size=512, bits=4,
    )
    assert codec_pallas._pipe_tc(
        8, 512,
        autotune.lookup(
            autotune.KIND_FLAT, n_chunks=8, bucket_size=512, bits=4
        ),
    ) == 2
    q_tuned = codec_pallas.quantize_batch(x, 4, 512, interpret=True)
    assert bool(jnp.array_equal(q_default.packed, q_tuned.packed))
    assert bool(jnp.array_equal(q_default.meta, q_tuned.meta))


def test_tuned_db_engages_double_buffer(tmp_path, monkeypatch):
    """CGX_PALLAS_DB=auto engages the DB lowering iff a tuned entry for
    the shape says it measured faster — bytes identical either way."""
    _tuned_dir(tmp_path, monkeypatch)
    assert not codec_pallas._use_db(None)
    assert codec_pallas._use_db(autotune.TunedConfig(tc=4, db=True))
    monkeypatch.setenv("CGX_PALLAS_DB", "off")
    assert not codec_pallas._use_db(autotune.TunedConfig(tc=4, db=True))
    monkeypatch.setenv("CGX_PALLAS_DB", "on")
    assert codec_pallas._use_db(None)
    monkeypatch.delenv("CGX_PALLAS_DB")
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((1, 4 * 32 * 512)), jnp.float32)
    q_grid = codec_pallas.quantize_batch(x, 4, 512, interpret=True)
    autotune.record(
        autotune.KIND_FLAT, autotune.TunedConfig(tc=2, db=True),
        n_chunks=4, bucket_size=512, bits=4,
    )
    q_db = codec_pallas.quantize_batch(x, 4, 512, interpret=True)
    assert bool(jnp.array_equal(q_grid.packed, q_db.packed))
    assert bool(jnp.array_equal(q_grid.meta, q_db.meta))
