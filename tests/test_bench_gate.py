"""Bench regression gate tests (ISSUE 3): the committed trajectory must
pass ``--smoke`` (this IS the tier-1 self-check the issue asks for), a
synthetic 2x regression must fail with the offending metric named, and
the record normalization must skip failure/unresolved rows.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_GATE = os.path.join(_REPO, "tools", "bench_gate.py")


def _load_gate():
    spec = importlib.util.spec_from_file_location("bench_gate", _GATE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_smoke_passes_on_committed_trajectory():
    # Acceptance: bench_gate exits zero on the committed BENCH_LOG.
    proc = subprocess.run(
        [sys.executable, _GATE, "--smoke"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "checked" in proc.stdout


def test_synthetic_2x_regression_fails_named(tmp_path):
    # Acceptance: a fresh run at half the historical throughput exits
    # nonzero and names the offending metric.
    gate = _load_gate()
    history = gate._read_jsonl(os.path.join(_REPO, "BENCH_LOG.jsonl"))
    baselines = gate.build_baselines(history)
    metric, base = next(iter(sorted(baselines.items())))
    cand = tmp_path / "cand.jsonl"
    cand.write_text(json.dumps({
        "tool": "shm_bench" if "bridge" in metric else "bench",
        "metric": metric, "value": base / 2, "unit": "GB/s",
    }) + "\n")
    proc = subprocess.run(
        [sys.executable, _GATE, "--candidate", str(cand)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 1
    assert metric in proc.stderr  # the offending metric is named
    assert "REGRESSION" in proc.stdout


def test_candidate_within_threshold_passes(tmp_path):
    gate = _load_gate()
    history = gate._read_jsonl(os.path.join(_REPO, "BENCH_LOG.jsonl"))
    baselines = gate.build_baselines(history)
    metric, base = next(iter(sorted(baselines.items())))
    cand = tmp_path / "cand.jsonl"
    cand.write_text(json.dumps({
        "tool": "shm_bench", "metric": metric, "value": base * 0.9,
        "unit": "GB/s",
    }) + "\n")
    proc = subprocess.run(
        [sys.executable, _GATE, "--candidate", str(cand), "--json"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout
    verdict = json.loads(proc.stdout)
    assert verdict["ok"] and verdict["checks"]


def test_normalize_skips_failures_and_unresolved():
    gate = _load_gate()
    assert gate.normalize({"tool": "bench", "metric": "device_init_failure",
                           "value": 0, "unit": "none"}) is None
    assert gate.normalize({"tool": "qbench", "variant": "x", "gbps_in": None,
                           "unresolved": "noise"}) is None
    assert gate.normalize({"tool": "qbench", "variant": "current", "tc": 16,
                           "mb": 128, "bits": 4, "pack": "sum",
                           "encode": "div", "gbps_in": 130.5}) == (
        "qbench_current_tc16_mb128_b4_sum_div", 130.5)
    key, v = gate.normalize({"tool": "shm_bench", "metric": "m",
                             "value": 0.5, "unit": "GB/s (shm)"})
    assert key == "m" and v == 0.5
    # non-throughput units carry no gate direction: skipped
    assert gate.normalize({"tool": "bench", "metric": "m", "value": 3.0,
                           "unit": "steps"}) is None


def test_gate_logic_threshold_and_first_sighting():
    gate = _load_gate()
    baselines = {"m": 1.0}
    reg, checks = gate.gate(
        [{"tool": "shm_bench", "metric": "m", "value": 0.65,
          "unit": "GB/s"},
         {"tool": "shm_bench", "metric": "new", "value": 0.1,
          "unit": "GB/s"}],
        baselines, threshold_pct=30.0,
    )
    assert len(checks) == 1  # first sighting of "new" is not gated
    assert reg and reg[0]["metric"] == "m"
    assert reg[0]["delta_pct"] == pytest.approx(-35.0)
    reg2, _ = gate.gate(
        [{"tool": "shm_bench", "metric": "m", "value": 0.75,
          "unit": "GB/s"}],
        baselines, threshold_pct=30.0,
    )
    assert not reg2  # -25% is inside the 30% band


def test_published_floor_wins_over_history():
    gate = _load_gate()
    history = [{"tool": "shm_bench", "metric": "m", "value": 0.4,
                "unit": "GB/s"}]
    b = gate.build_baselines(history, published={"m": 0.8})
    assert b["m"] == 0.8


# ---------------------------------------------------------------------------
# CPU-placeholder separation (ISSUE 6 satellite): rows that ran on the CPU
# stand-in during the flaky-transport rounds (BENCH_r05's
# device_init_failure incident) must form their own trajectory and never
# dilute — or be judged against — chip truth.
# ---------------------------------------------------------------------------


def _chip_row(value, **kw):
    return {"tool": "bench", "metric": "pallas_codec_roundtrip",
            "value": value, "unit": "GB/s", "chip": "TPU v5 lite",
            "backend": "tpu", **kw}


def _cpu_row(value, **kw):
    return {"tool": "bench", "metric": "pallas_codec_roundtrip",
            "value": value, "unit": "GB/s", "chip": "cpu",
            "backend": "cpu", **kw}


def test_placeholder_rows_key_into_their_own_trajectory():
    gate = _load_gate()
    assert gate.normalize(_chip_row(100.0)) == (
        "pallas_codec_roundtrip", 100.0)
    assert gate.normalize(_cpu_row(2.0)) == (
        "pallas_codec_roundtrip@cpu", 2.0)
    # detail.chip tagging (older rows carried the chip inside detail)
    rec = {"tool": "bench", "metric": "pallas_codec_roundtrip",
           "value": 3.0, "unit": "GB/s", "detail": {"chip": "cpu"}}
    assert gate.normalize(rec) == ("pallas_codec_roundtrip@cpu", 3.0)
    # host-side tools are genuinely host metrics, NOT placeholders
    host = {"tool": "shm_bench", "metric": "bridge_put_take",
            "value": 1.0, "unit": "GB/s", "backend": "host"}
    assert gate.normalize(host) == ("bridge_put_take", 1.0)


def test_placeholder_rows_never_dilute_chip_median():
    gate = _load_gate()
    # three cpu stand-ins around two real chip rows: the chip baseline
    # must stay the chip median, not collapse toward the placeholders
    hist = [_chip_row(100.0), _cpu_row(2.0), _chip_row(110.0),
            _cpu_row(2.1), _cpu_row(1.9)]
    b = gate.build_baselines(hist)
    assert b["pallas_codec_roundtrip"] == pytest.approx(105.0)
    assert b["pallas_codec_roundtrip@cpu"] == pytest.approx(2.0)


def test_published_floor_is_a_chip_promise_never_cpu():
    gate = _load_gate()
    b = gate.build_baselines(
        [_cpu_row(2.0)],
        published={"pallas_codec_roundtrip": 90.0,
                   "pallas_codec_roundtrip@cpu": 50.0},
    )
    # the floor lands on the chip key; a floor on a placeholder key is
    # refused outright (nothing could ever meet it honestly)
    assert b["pallas_codec_roundtrip"] == 90.0
    assert b["pallas_codec_roundtrip@cpu"] == pytest.approx(2.0)


def test_placeholder_candidate_never_meets_chip_floor():
    gate = _load_gate()
    regs, checks = gate.gate(
        [_cpu_row(2.0)], {"pallas_codec_roundtrip": 100.0}, 30.0)
    # different trajectory key: not compared at all, not a regression
    assert not regs and not checks


def test_smoke_skips_placeholder_only_trajectories():
    gate = _load_gate()
    # a placeholder trajectory with a sustained 10x cliff: smoke must not
    # gate it (it proves the code path runs, it defends no floor)...
    hist = [_cpu_row(2.0), _cpu_row(2.1), _cpu_row(0.2), _cpu_row(0.2),
            _cpu_row(0.2)]
    regs, checks = gate.smoke(hist, threshold_pct=30.0)
    assert regs == [] and checks == []
    # ...while the same cliff on chip truth still fails loudly
    hist = [_chip_row(100.0), _chip_row(101.0), _chip_row(10.0),
            _chip_row(10.0), _chip_row(10.0)]
    regs, _ = gate.smoke(hist, threshold_pct=30.0)
    assert regs and regs[0]["metric"] == "pallas_codec_roundtrip"


# ---------------------------------------------------------------------------
# Overlap-fraction floor (ISSUE 9 satellite): sched records gate a second
# trajectory, <metric>:overlap_frac, like throughput — @cpu separation
# preserved.
# ---------------------------------------------------------------------------


def _sched_rec(overlap, value=0.02, backend="host"):
    return {
        "tool": "bench",
        "metric": "sched_pipelined_vs_monolithic_4bit_32MB_x4",
        "value": value,
        "unit": "GB/s",
        "overlap_frac": overlap,
        "backend": backend,
        "chip": backend,
    }


def test_overlap_normalizer_yields_second_trajectory():
    gate = _load_gate()
    rec = _sched_rec(0.25)
    keys = dict(gate.normalize_all(rec))
    assert keys["sched_pipelined_vs_monolithic_4bit_32MB_x4"] == 0.02
    assert (
        keys["sched_pipelined_vs_monolithic_4bit_32MB_x4:overlap_frac"]
        == 0.25
    )
    # 0.0 is a VALID measurement (total collapse must face the floor,
    # not bypass it); absent/negative overlap contributes nothing
    assert gate.normalize_overlap(_sched_rec(0.0)) is not None
    assert gate.normalize_overlap(_sched_rec(-1.0)) is None
    assert gate.normalize_overlap({"metric": "x", "value": 1}) is None


def test_overlap_total_collapse_fails_the_gate():
    # The worst regression — the pipeline fully re-serialized
    # (overlap_frac 0.0, e.g. the schedule silently degraded to one
    # chunk) — must fail, not slip past normalization.
    gate = _load_gate()
    history = [_sched_rec(0.25), _sched_rec(0.22), _sched_rec(0.28)]
    baselines = gate.build_baselines(history)
    regressions, _ = gate.gate([_sched_rec(0.0)], baselines, 30.0)
    assert any(
        r["metric"].endswith(":overlap_frac") and r["value"] == 0.0
        for r in regressions
    )


def test_overlap_regression_fails_the_gate():
    gate = _load_gate()
    history = [_sched_rec(0.25), _sched_rec(0.22), _sched_rec(0.28)]
    baselines = gate.build_baselines(history)
    # a run whose pipeline quietly re-serialized: overlap collapses while
    # throughput barely moves — the overlap floor must catch it
    regressions, checks = gate.gate(
        [_sched_rec(0.01, value=0.019)], baselines, 30.0
    )
    names = {r["metric"] for r in regressions}
    assert "sched_pipelined_vs_monolithic_4bit_32MB_x4:overlap_frac" in names
    assert "sched_pipelined_vs_monolithic_4bit_32MB_x4" not in names


def test_overlap_placeholder_rows_key_cpu_trajectory():
    gate = _load_gate()
    rec = gate.normalize_overlap(_sched_rec(0.3, backend="cpu"))
    assert rec is not None
    assert rec[0].endswith(":overlap_frac@cpu")
    # and the cpu trajectory never meets the host baseline
    history = [_sched_rec(0.25)] * 3
    baselines = gate.build_baselines(history)
    regressions, checks = gate.gate(
        [_sched_rec(0.01, backend="cpu")], baselines, 30.0
    )
    assert not regressions and not checks


# ---------------------------------------------------------------------------
# Cost-model prediction floor (ISSUE 12): the <metric>:pred_ratio
# trajectory + the hard CGX_GATE_PRED_SLACK check.
# ---------------------------------------------------------------------------


def test_pred_normalizer_yields_third_trajectory():
    bg = _load_gate()
    rec = {
        "metric": "planner_vs_static_4bit_32MB_x4",
        "value": 1.2, "unit": "GB/s",
        "pred_ratio": 1.1,
        "predicted_step_ms": 110.0, "measured_step_ms": 100.0,
        "backend": "host", "chip": "host",
    }
    # the gated value is prediction ACCURACY min(r, 1/r): symmetric
    # around the 1.0 ideal, so drift in EITHER direction regresses
    keys = dict(bg.normalize_all(rec))
    assert keys["planner_vs_static_4bit_32MB_x4:pred_ratio"] == \
        pytest.approx(1 / 1.1)
    # derived from the ms pair when the ratio field is absent
    del rec["pred_ratio"]
    keys = dict(bg.normalize_all(rec))
    assert keys["planner_vs_static_4bit_32MB_x4:pred_ratio"] == \
        pytest.approx(1 / 1.1)
    # an underpredicting model maps to the same accuracy
    rec["pred_ratio"] = 1 / 1.1
    keys = dict(bg.normalize_all(rec))
    assert keys["planner_vs_static_4bit_32MB_x4:pred_ratio"] == \
        pytest.approx(1 / 1.1)


def test_pred_placeholder_rows_key_cpu_trajectory():
    bg = _load_gate()
    rec = {
        "metric": "planner_vs_static_4bit_32MB_x4",
        "pred_ratio": 0.9, "backend": "cpu", "chip": "cpu",
    }
    norm = bg.normalize_pred(rec)
    assert norm is not None
    assert norm[0].endswith(":pred_ratio@cpu")


def test_pred_slack_violation_fails_loudly(monkeypatch):
    # A record whose measured step exceeds predicted*slack fails the
    # candidate gate with NO history needed — the planner's own
    # prediction is the floor (planner regression / cost-model drift).
    bg = _load_gate()
    monkeypatch.delenv("CGX_GATE_PRED_SLACK", raising=False)
    bad = {
        "metric": "planner_vs_static_4bit_32MB_x4",
        "predicted_step_ms": 100.0, "measured_step_ms": 151.0,
    }
    ok = {
        "metric": "planner_vs_static_4bit_32MB_x4",
        "predicted_step_ms": 100.0, "measured_step_ms": 149.0,
    }
    fails = bg.check_pred_slack([bad, ok])
    assert len(fails) == 1
    assert fails[0]["metric"] == "planner_vs_static_4bit_32MB_x4:pred_slack"
    # env knob moves the floor
    monkeypatch.setenv("CGX_GATE_PRED_SLACK", "2.0")
    assert bg.check_pred_slack([bad]) == []
    # explicit argument wins over env
    assert len(bg.check_pred_slack([bad], 1.2)) == 1


def test_pred_ratio_regression_fails_the_gate():
    bg = _load_gate()
    history = [
        {"metric": "planner_vs_static_4bit_32MB_x4", "pred_ratio": r,
         "backend": "host", "chip": "host"}
        for r in (1.0, 1.05, 0.95)
    ]
    baselines = bg.build_baselines(history)
    # accuracies: (1.0, 1/1.05, 0.95) -> median 1/1.05
    assert baselines["planner_vs_static_4bit_32MB_x4:pred_ratio"] == \
        pytest.approx(1 / 1.05)
    # drift in EITHER direction fails: heavy underprediction...
    cand = [{"metric": "planner_vs_static_4bit_32MB_x4", "pred_ratio": 0.4,
             "backend": "host", "chip": "host"}]
    regressions, _checks = bg.gate(cand, baselines, 30.0)
    assert len(regressions) == 1
    assert regressions[0]["metric"].endswith(":pred_ratio")
    # ...and unbounded OVERprediction (ratio 5.0 -> accuracy 0.2)
    cand = [{"metric": "planner_vs_static_4bit_32MB_x4", "pred_ratio": 5.0,
             "backend": "host", "chip": "host"}]
    regressions, _checks = bg.gate(cand, baselines, 30.0)
    assert len(regressions) == 1


def test_peak_mb_normalizes_inverse_and_gates_lower_better():
    # ISSUE 18 satellite: records carrying the memory ledger's peak_mb
    # gate an INVERSE (1/MB) trajectory, so a footprint growth fails
    # exactly like a throughput cliff.
    bg = _load_gate()
    rec = {"metric": "bench_4bit_512MB", "value": 10.0, "peak_mb": 256.0,
           "backend": "tpu", "chip": "v5e"}
    key, v = bg.normalize_peak_mb(rec)
    assert key == "bench_4bit_512MB:peak_mb"
    assert v == pytest.approx(1.0 / 256.0)
    # present in the full normalization fan-out
    assert (key, v) in bg.normalize_all(rec)
    # ledger off (no key), bogus values, unresolved rows: no trajectory
    assert bg.normalize_peak_mb({"metric": "m", "value": 1.0}) is None
    assert bg.normalize_peak_mb({"metric": "m", "peak_mb": 0}) is None
    assert bg.normalize_peak_mb({"metric": "m", "peak_mb": True}) is None
    assert bg.normalize_peak_mb(
        {"metric": "m", "peak_mb": 9.0, "unresolved": True}) is None
    # placeholder rows stay in their own @cpu trajectory
    ph = {"metric": "bench_4bit_512MB", "peak_mb": 256.0,
          "backend": "tpu", "chip": "cpu"}
    key_ph, _ = bg.normalize_peak_mb(ph)
    assert key_ph.endswith("@cpu")


def test_peak_mb_growth_fails_the_gate():
    bg = _load_gate()
    history = [
        {"metric": "bench_4bit_512MB", "value": 10.0, "peak_mb": mb,
         "backend": "host", "chip": "host"}
        for mb in (250.0, 256.0, 260.0)
    ]
    baselines = bg.build_baselines(history)
    assert baselines["bench_4bit_512MB:peak_mb"] == \
        pytest.approx(1.0 / 256.0)
    # a 2x memory growth (inverse halves) fails, named
    cand = [{"metric": "bench_4bit_512MB", "value": 10.0, "peak_mb": 512.0,
             "backend": "host", "chip": "host"}]
    regressions, _checks = bg.gate(cand, baselines, 30.0)
    assert [r["metric"] for r in regressions] == \
        ["bench_4bit_512MB:peak_mb"]
    # a shrink (inverse grows) passes
    cand[0]["peak_mb"] = 128.0
    regressions, _checks = bg.gate(cand, baselines, 30.0)
    assert regressions == []
