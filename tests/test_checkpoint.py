"""Checkpoint/resume tests — including the registry-survival property the
reference lacks (SURVEY.md §5.4: its in-process layer registry vanishes on
restart; ours rides inside every checkpoint)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import torch_cgx_tpu
from torch_cgx_tpu import CompressionConfig, checkpoint as ckpt
from torch_cgx_tpu import config as cfg


def _tree():
    return {
        "params": {
            "dense": {"kernel": jnp.arange(12.0).reshape(3, 4),
                      "bias": jnp.ones((4,))},
        },
        "step": jnp.asarray(7),
    }


def test_save_restore_roundtrip(tmp_path):
    tree = _tree()
    path = ckpt.save(str(tmp_path), tree, step=7)
    assert path.endswith("step_7")
    out = ckpt.restore(str(tmp_path), target=jax.tree.map(jnp.zeros_like, tree))
    chex_equal = jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        tree, out,
    )
    del chex_equal


def test_latest_step_discovery(tmp_path):
    assert ckpt.latest_step(str(tmp_path)) is None
    for s in (3, 10, 5):
        ckpt.save(str(tmp_path), {"x": jnp.zeros(2)}, step=s)
    assert ckpt.all_steps(str(tmp_path)) == [3, 5, 10]
    assert ckpt.latest_step(str(tmp_path)) == 10
    out = ckpt.restore(str(tmp_path), target={"x": jnp.zeros(2)})
    assert out["x"].shape == (2,)


def test_registry_survives_restart(tmp_path):
    cfg.register_layer(0, 0, 3000, 4, 256)
    cfg.register_layer(0, 1, 96, 32, 0)
    cfg.register_layer(1, 0, 512, 2, 64)
    torch_cgx_tpu.set_layer_pattern_config(
        r"kernel$", CompressionConfig(bits=4, bucket_size=1024)
    )
    ckpt.save(str(tmp_path), _tree(), step=1)
    # Simulated process restart: statics wiped.
    torch_cgx_tpu.clear_registry()
    assert cfg.registered_layer_sizes(0) is None
    ckpt.restore(str(tmp_path), target=jax.tree.map(jnp.zeros_like, _tree()))
    assert cfg.registered_layer_sizes(0) == [3000, 96]
    assert cfg.registered_layer_sizes(1) == [512]
    assert cfg.get_layer_config((0, 0)).bits == 4
    assert cfg.get_layer_config((0, 0)).bucket_size == 256
    assert cfg.get_layer_config((0, 1)).bits == 32
    resolved = cfg.resolve_pattern_config("model/dense/kernel")
    assert resolved is not None and resolved.bits == 4


def test_restore_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.restore(str(tmp_path / "nope"))


def test_registry_restore_under_shrunk_world(tmp_path):
    """ISSUE 5 satellite: a registry snapshot saved by a ws=4 run
    re-installs cleanly in a ws=3 survivor world. The registry stores
    per-(bucket, layer) facts only — nothing world-size shaped — so the
    restore must succeed verbatim, and the bucket's chunk layout must
    RE-DERIVE for the shrunk world rather than replay any ws=4 plan
    (``_chunk_split``/``chunk_layout`` are pure functions of (n, ws))."""
    from torch_cgx_tpu.parallel.reducers import chunk_layout
    from torch_cgx_tpu.torch_backend.backend import _chunk_split

    # The registry as a ws=4 bridge run (DDP hook) would fill it.
    cfg.register_layer(0, 0, 4096, 4, 128)
    cfg.register_layer(0, 1, 2048, 2, 64)
    cfg.register_layer(1, 0, 300, 8, 0)
    ckpt.save(str(tmp_path), _tree(), step=3)
    total = 4096 + 2048
    sizes4, offs4 = _chunk_split(total, 4)
    # Simulated eviction-restart: statics wiped, restored at ws=3.
    torch_cgx_tpu.clear_registry()
    ckpt.restore(str(tmp_path), target=jax.tree.map(jnp.zeros_like, _tree()))
    assert cfg.registered_layer_sizes(0) == [4096, 2048]
    assert cfg.registered_layer_sizes(1) == [300]
    # No stale layer indices: every registered (bucket, layer) resolves.
    assert cfg.get_layer_config((0, 0)).bits == 4
    assert cfg.get_layer_config((0, 1)).bucket_size == 64
    assert cfg.get_layer_config((1, 0)).bits == 8
    # The bucket layout is derived fresh for the survivor world.
    sizes3, offs3 = _chunk_split(total, 3)
    assert len(sizes3) == 3 and sum(sizes3) == total
    assert sizes3 != sizes4
    assert offs3 == [0] + list(np.cumsum(sizes3)[:-1])
    assert chunk_layout(total, 3) != chunk_layout(total, 4)


def test_training_resume_equivalence(tmp_path):
    """Train 4 steps, checkpoint at 2, resume, and match the uninterrupted
    run bit-for-bit (the actual resume contract)."""
    def loss_fn(p, b):
        return jnp.mean((b @ p["w"] - 1.0) ** 2)

    opt = optax.adam(1e-2)
    p0 = {"w": jnp.ones((4, 2))}
    s0 = opt.init(p0)
    batch = jnp.arange(8.0).reshape(2, 4)

    @jax.jit
    def step(p, s, b):
        g = jax.grad(loss_fn)(p, b)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    # Uninterrupted.
    p, s = p0, s0
    for _ in range(4):
        p, s = step(p, s, batch)
    want = np.asarray(p["w"])

    # Interrupted + resumed.
    p, s = p0, s0
    for _ in range(2):
        p, s = step(p, s, batch)
    ckpt.save(str(tmp_path), {"params": p, "opt": s}, step=2)
    restored = ckpt.restore(
        str(tmp_path), target={"params": p0, "opt": s0}
    )
    p, s = restored["params"], restored["opt"]
    for _ in range(2):
        p, s = step(p, s, batch)
    np.testing.assert_array_equal(np.asarray(p["w"]), want)


def test_compression_state_roundtrip(tmp_path):
    """EF residuals and PowerSGD warm-start factors are ordinary pytrees —
    a resumed run must get back bit-identical compression state (the
    warm-started Q is load-bearing: losing it restarts the power
    iteration from random)."""

    from torch_cgx_tpu import checkpoint as ckpt
    from torch_cgx_tpu.parallel import init_powersgd
    from torch_cgx_tpu.parallel.grad_sync import ErrorFeedbackState

    params = {"w": jnp.ones((32, 8), jnp.float32), "b": jnp.ones((8,))}
    psgd = init_powersgd(params, rank=2)
    # make the state distinctive
    psgd = psgd._replace(
        es=tuple(
            None if e is None else e + 0.25 for e in psgd.es
        )
    )
    ef = ErrorFeedbackState(
        e={"w": jnp.full((32, 8), 0.5, jnp.float32)}
    )
    tree = {"params": params, "psgd": psgd, "ef": ef, "step": jnp.int32(7)}
    ckpt.save(str(tmp_path), tree, 7)
    back = ckpt.restore(str(tmp_path), 7, target=tree)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # pytree structure (incl. the None slots) survives
    assert jax.tree.structure(tree) == jax.tree.structure(back)
