"""Live health plane tests (ISSUE 6).

Unit suite: online estimators (EWMA, P² quantiles) against numpy oracles,
straggler scoring from synthetic collective-phase skew, sustained-gate +
cooldown event semantics, SLO breach events over the live qerr stream,
Prometheus text exposition (pure render + a real scrape over the stdlib
endpoint), the leader-side cluster health merge, `cgx_top` rendering, and
inertness with every `CGX_HEALTH_*` / `CGX_PROM_PORT` knob unset.

Chaos acceptance (`torch_bridge`): a 2-rank bridge run with a `slow_rank`
fault — the health plane flags the lagging rank strictly before the
bridge timeout could fire (the bounded wait never expires at all), the
recovery supervisor records the straggler as suspect evidence, and a live
scrape of the Prometheus port returns parseable exposition with ``cgx_``
samples. The inertness half of the acceptance (env unset ⇒ grad_sync
bit-identity unchanged) is carried by the existing test_grad_sync suite,
which runs with all CGX_* env cleared.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import re
import sys
import tempfile
import time
import traceback
import types
import urllib.request

import numpy as np
import pytest

from torch_cgx_tpu.observability import health, watch
from torch_cgx_tpu.utils.logging import metrics

from test_faults import FakeStore

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    metrics.reset()
    yield
    health.stop()
    watch.stop_prom()
    metrics.reset()


# ---------------------------------------------------------------------------
# Online estimators vs numpy oracles.
# ---------------------------------------------------------------------------


def test_ewma_matches_numpy_recurrence_oracle():
    rng = np.random.default_rng(0)
    xs = rng.exponential(scale=0.3, size=200)
    hl = 8.0
    e = health.Ewma(half_life=hl)
    alpha = 1.0 - 2.0 ** (-1.0 / hl)
    oracle = xs[0]
    for x in xs:
        e.update(x)
    for x in xs[1:]:
        oracle = oracle + alpha * (x - oracle)
    assert e.value == pytest.approx(float(oracle), rel=1e-12)
    assert e.n == len(xs)


def test_ewma_halflife_semantics():
    # after exactly half_life samples of 0 from a start of 1.0 the value
    # has halved — that IS the definition of the half-life
    e = health.Ewma(half_life=16.0)
    e.update(1.0)
    for _ in range(16):
        e.update(0.0)
    assert e.value == pytest.approx(0.5, rel=1e-9)


@pytest.mark.parametrize("q,tol", [(0.5, 0.02), (0.9, 0.02), (0.99, 0.02)])
def test_p2_quantile_vs_numpy_uniform(q, tol):
    rng = np.random.default_rng(7)
    xs = rng.uniform(size=5000)
    est = health.P2Quantile(q)
    for x in xs:
        est.update(x)
    assert abs(est.value() - np.percentile(xs, q * 100)) < tol


def test_p2_quantile_vs_numpy_exponential():
    # heavier tail than uniform: the estimator must still track p99
    rng = np.random.default_rng(11)
    xs = rng.exponential(scale=1.0, size=8000)
    est = health.P2Quantile(0.99)
    for x in xs:
        est.update(x)
    true = float(np.percentile(xs, 99))
    assert abs(est.value() - true) < 0.15 * true


def test_p2_quantile_exact_below_five_observations():
    est = health.P2Quantile(0.5)
    assert est.value() == 0.0
    for x in (5.0, 1.0, 3.0):
        est.update(x)
    assert est.value() == 3.0  # exact: sorted()[1] of three samples
    with pytest.raises(ValueError):
        health.P2Quantile(1.5)


# ---------------------------------------------------------------------------
# Straggler scoring from synthetic collective-phase skew.
# ---------------------------------------------------------------------------


def _skewed_engine(monkeypatch, **kw):
    """Engine with peers 1/2 answering in 10 ms and peer 3's wait
    in-flight and 1.2 s old, on a fully controlled clock."""
    eng = health.HealthEngine(0, straggler_factor=3.0, **kw)
    clock = {"t": 100.0}
    monkeypatch.setattr(health.time, "perf_counter", lambda: clock["t"])
    for peer in (1, 2):
        for _ in range(4):
            tok = eng.wait_begin(peer, "k")
            clock["t"] += 0.01
            eng.wait_end(tok)
    eng.wait_begin(3, "k")
    clock["t"] += 1.2
    return eng, clock


def test_straggler_scores_from_synthetic_skew(monkeypatch):
    eng, clock = _skewed_engine(monkeypatch)
    scores = eng.straggler_scores(clock["t"])
    # peer 3: 1.2 s in-flight over the floored 10 ms median = way past 3x
    assert scores[3] >= 3.0
    # the healthy peers are judged against the straggler's signal in
    # their median — nowhere near the gate
    assert scores[1] < 1.0 and scores[2] < 1.0


def test_straggler_event_sustained_gate_and_cooldown(monkeypatch):
    eng, _ = _skewed_engine(monkeypatch)
    got = []
    eng.add_consumer(got.append)  # plain function: held strongly
    assert eng.sample() == []  # tick 1: firing but not yet sustained
    out = eng.sample()  # tick 2: sustained -> emitted
    assert [e.kind for e in out] == ["straggler"]
    ev = out[0]
    assert ev.suspect == 3 and ev.rank == 0
    assert ev.value >= ev.threshold == 3.0
    assert dict(ev.detail)["wait_s"] >= 1.2
    assert got == [ev]  # consumer saw exactly the emitted event
    # cooldown: the sustained condition stays ONE event stream
    assert eng.sample() == []
    assert metrics.get("cgx.health.events") == 1
    assert metrics.get("cgx.health.events.straggler") == 1
    # per-peer gauges are exported every tick regardless
    assert metrics.get("cgx.health.straggler.r3") >= 3.0


def test_forget_peers_clears_straggler_state(monkeypatch):
    eng, _ = _skewed_engine(monkeypatch)
    eng.sample()
    assert eng.sample()  # sustained -> emitted
    eng.forget_peers()
    # per-peer signals, sustain bookkeeping and gauges are all gone: a
    # new generation starts clean instead of re-emitting the evicted
    # peer's frozen wait EWMA every cooldown window
    assert eng.straggler_scores() == {}
    assert eng.sample() == []
    assert metrics.get("cgx.health.straggler.r3") == 0.0


def test_invalidate_trace_caches_forgets_health_peers(monkeypatch):
    monkeypatch.setenv("CGX_HEALTH", "1")
    eng = health.maybe_start(0)
    tok = eng.wait_begin(3, "k")
    from torch_cgx_tpu.robustness import supervisor as sup_mod

    sup_mod.invalidate_trace_caches()
    with eng._lock:
        assert eng._peers == {} and eng._inflight == {}
    eng.wait_end(tok)  # dead-generation token: no-op, not a crash


def test_dead_weak_consumer_is_dropped(monkeypatch):
    eng, _ = _skewed_engine(monkeypatch)

    class Sink:
        def __init__(self):
            self.got = []

        def cb(self, ev):
            self.got.append(ev)

    sink = Sink()
    eng.add_consumer(sink.cb)  # bound method: held weakly
    del sink
    eng.sample()
    assert eng.sample()  # emits without raising into the dead ref
    with eng._lock:
        assert eng._consumers == []


def test_raising_consumer_does_not_kill_emission(monkeypatch):
    eng, _ = _skewed_engine(monkeypatch)
    got = []

    def bad(ev):
        raise RuntimeError("consumer bug")

    eng.add_consumer(bad)
    eng.add_consumer(got.append)
    eng.sample()
    assert eng.sample()
    assert len(got) == 1


# ---------------------------------------------------------------------------
# Step-time regression, qerr SLO, arena pressure.
# ---------------------------------------------------------------------------


def test_step_regression_event_fast_vs_slow_ewma():
    eng = health.HealthEngine(0, step_factor=2.0)
    for _ in range(20):
        eng.note_step(0.1)
    for _ in range(10):
        eng.note_step(1.0)
    assert eng.sample() == []  # sustain gate
    out = eng.sample()
    assert [e.kind for e in out] == ["step_regression"]
    d = dict(out[0].detail)
    assert d["fast_s"] > d["slow_s"] > 0
    st = eng.status()["step"]
    assert st["n"] == 30
    assert st["p50_s"] > 0 and st["p99_s"] >= st["p50_s"]


def test_no_step_regression_on_steady_cadence():
    eng = health.HealthEngine(0, step_factor=2.0)
    for _ in range(40):
        eng.note_step(0.1)
    assert eng.sample() == [] and eng.sample() == []


def test_qerr_slo_breach_event():
    eng = health.HealthEngine(0, qerr_slo=0.05)
    for _ in range(10):
        metrics.observe("cgx.qerr.dense/kernel", 0.2)
    eng.sample()
    out = eng.sample()
    assert [e.kind for e in out] == ["qerr_slo"]
    assert dict(out[0].detail)["layer"] == "dense/kernel"
    assert out[0].value == pytest.approx(0.2)


def test_qerr_slo_quiet_below_threshold():
    eng = health.HealthEngine(0, qerr_slo=0.5)
    for _ in range(10):
        metrics.observe("cgx.qerr.dense/kernel", 0.2)
    assert eng.sample() == [] and eng.sample() == []


def test_arena_pressure_trend_event():
    eng = health.HealthEngine(0)
    metrics.add("cgx.arena_pressure_waits")
    assert eng.sample() == []  # first tick establishes the window
    metrics.add("cgx.arena_pressure_waits", 2.0)
    out = eng.sample()
    assert [e.kind for e in out] == ["arena_pressure"]
    assert out[0].value == 2.0
    # no further movement -> no further events
    assert eng.sample() == []


# ---------------------------------------------------------------------------
# Event/status files (what cgx_top and the chaos suite read).
# ---------------------------------------------------------------------------


def test_event_and_status_files_written(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    eng, _ = _skewed_engine(monkeypatch)
    eng.sample()
    eng.sample()
    events = [
        json.loads(line)
        for line in open(tmp_path / "health-rank0.jsonl")
    ]
    assert [e["kind"] for e in events] == ["straggler"]
    assert events[0]["suspect"] == 3
    status = json.load(open(tmp_path / "health-status-rank0.json"))
    assert status["rank"] == 0
    assert float(status["straggler_scores"]["3"]) >= 3.0
    assert status["events_recent"][-1]["kind"] == "straggler"


def test_cgx_top_renders_synthetic_dir(tmp_path, monkeypatch):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "cgx_top", os.path.join(_REPO, "tools", "cgx_top.py")
    )
    cgx_top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cgx_top)
    # one metrics export line + a health status + a flightrec failure
    with open(tmp_path / "metrics-rank0.jsonl", "w") as f:
        f.write(json.dumps({
            "ts": 1000.0,
            "counters": {"cgx.step.count": 10.0,
                         "cgx.sra.bytes_in": 800.0,
                         "cgx.sra.wire_bytes_out": 100.0},
            "gauges": {"cgx.recovery.generation": 1.0},
            "histograms": {"cgx.collective.allreduce_s": {
                "count": 10, "p50": 0.002, "p99": 0.004}},
        }) + "\n")
    with open(tmp_path / "health-status-rank0.json", "w") as f:
        json.dump({"rank": 0, "straggler_scores": {"1": 5.2},
                   "step": {}, "events_recent": [
                       {"kind": "straggler", "value": 5.2,
                        "threshold": 3.0, "suspect": 1}]}, f)
    with open(tmp_path / "flightrec-rank0.jsonl", "w") as f:
        f.write(json.dumps({"kind": "failure",
                            "error": "BridgeTimeoutError",
                            "op": "allreduce"}) + "\n")
    state: dict = {}
    first = cgx_top.render(str(tmp_path), state)
    assert "5.2→r1" in first  # worst straggler score
    assert "8.0x" in first  # wire ratio 800/100
    assert "BridgeTimeoutError(allreduce)" in first
    assert "straggler" in first  # recent events block
    # second frame with a step-count delta computes a rate
    with open(tmp_path / "metrics-rank0.jsonl", "a") as f:
        f.write(json.dumps({
            "ts": 1002.0, "counters": {"cgx.step.count": 14.0},
            "gauges": {}, "histograms": {},
        }) + "\n")
    second = cgx_top.render(str(tmp_path), state)
    assert "2.00" in second  # (14-10)/(1002-1000) steps/s
    # empty dir renders the hint, not a crash
    empty = tmp_path / "empty"
    empty.mkdir()
    assert "no metrics-rank" in cgx_top.render(str(empty), {})


# ---------------------------------------------------------------------------
# Prometheus exposition: pure render + a real scrape.
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"(NaN|[+-]Inf|[+-]?[0-9.eE+-]+)$"
)


def _assert_parses(body: str) -> None:
    for line in body.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|gauge|summary)$", line), line
        else:
            assert _SAMPLE_RE.match(line), line


def test_render_prometheus_text_exposition():
    metrics.add("cgx.health.events", 3.0)
    metrics.set("cgx.recovery.generation", 2.0)
    metrics.observe("cgx.collective.allreduce_s", 0.002)
    metrics.observe("cgx.collective.allreduce_s", 0.004)
    status = {"straggler_scores": {"1": 4.5},
              "step": {"ewma_fast_s": 0.1, "p99_s": 0.2}}
    body = watch.render_prometheus(status=status, rank=3)
    _assert_parses(body)
    assert "cgx_health_events 3.0" in body
    assert "cgx_recovery_generation 2.0" in body
    assert '# TYPE cgx_collective_allreduce_s summary' in body
    assert 'cgx_collective_allreduce_s{quantile="0.50"}' in body
    assert "cgx_collective_allreduce_s_count 2.0" in body
    assert 'cgx_health_straggler_score{peer="1"} 4.5' in body
    assert 'cgx_up{rank="3"} 1.0' in body


def test_prom_name_mangling():
    assert watch._prom_name("cgx.sra.wire_bytes_out") == (
        "cgx_sra_wire_bytes_out")
    assert watch._prom_name("cgx.qerr.dense/kernel") == (
        "cgx_qerr_dense_kernel")
    assert watch._prom_name("0weird").startswith("_")


def test_prom_server_scrape_and_port_publish(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    metrics.add("cgx.health.events")
    srv = watch.PromServer(0, rank=0).start()
    try:
        assert srv.port and srv.port > 0
        published = json.load(open(tmp_path / "prom-rank0.json"))
        assert published["port"] == srv.port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10
        ).read().decode()
        _assert_parses(body)
        assert "cgx_health_events" in body
        assert metrics.get("cgx.health.prom_scrapes") == 1
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=10
        ).read().decode())
        assert hz == {"rank": 0, "health_engine": "off"}
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=10)
    finally:
        srv.stop()


def test_maybe_start_prom_requires_knob_and_survives_bind_conflict(
    monkeypatch,
):
    assert watch.maybe_start_prom() is None  # knob unset: no socket
    srv = watch.PromServer(0, rank=0).start()
    try:
        # an occupied port degrades to a warning, never an exception
        monkeypatch.setenv("CGX_PROM_PORT", str(srv.port))
        assert watch.maybe_start_prom() is None
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Leader-side cluster health merge over the store control plane.
# ---------------------------------------------------------------------------


def test_aggregate_health_over_store(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_HEALTH", "1")
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    eng = health.maybe_start(0)
    assert eng is not None
    eng.note_step(0.25)
    store = FakeStore()
    # non-leader publishes and returns None
    assert watch.aggregate_health_over_store(store, 1, 2) is None
    view = watch.aggregate_health_over_store(store, 0, 2, timeout_s=2.0)
    assert view is not None
    assert view["world_size"] == 2
    assert view["ranks_reporting"] == [0, 1]
    assert view["missing_ranks"] == []
    assert view["step_per_rank"][0]["n"] == 1
    logged = [json.loads(line)
              for line in open(tmp_path / "cluster-health.jsonl")]
    assert logged[-1]["ranks_reporting"] == [0, 1]
    # a silent rank is named within the bounded deadline, never waited on
    assert watch.aggregate_health_over_store(store, 1, 3, round_id=1) is None
    t0 = time.monotonic()
    view = watch.aggregate_health_over_store(
        store, 0, 3, round_id=1, timeout_s=0.3)
    assert time.monotonic() - t0 < 5.0
    assert view["missing_ranks"] == [2]


def test_aggregate_is_noop_without_engine():
    assert watch.aggregate_health_over_store(FakeStore(), 0, 2) is None


# ---------------------------------------------------------------------------
# Supervisor handoff (unit): a straggler event becomes suspect evidence.
# ---------------------------------------------------------------------------


def _stub_supervisor():
    from torch_cgx_tpu.robustness.supervisor import RecoverySupervisor

    group = types.SimpleNamespace(
        global_rank=0, global_ranks=[0, 1], generation=0)
    return RecoverySupervisor(FakeStore(), group)


def _ev(kind="straggler", suspect=1, value=9.5):
    return health.HealthEvent(
        kind=kind, rank=0, value=value, threshold=3.0, suspect=suspect)


def test_supervisor_records_straggler_hint():
    sup = _stub_supervisor()
    sup.note_health_event(_ev())
    assert sup.suspect_hints == {1: 9.5}
    assert metrics.get("cgx.recovery.health_hints") == 1
    # non-straggler kinds and self-references are not evidence
    sup.note_health_event(_ev(kind="step_regression", suspect=None))
    sup.note_health_event(_ev(suspect=0))
    assert sup.suspect_hints == {1: 9.5}


def test_supervisor_hint_expires_after_ttl(monkeypatch):
    sup = _stub_supervisor()
    sup.note_health_event(_ev())
    assert 1 in sup.suspect_hints
    real = time.monotonic
    monkeypatch.setattr(
        "torch_cgx_tpu.robustness.supervisor.time.monotonic",
        lambda: real() + sup.HINT_TTL_S + 1.0,
    )
    assert sup.suspect_hints == {}


def test_supervisor_consumer_registered_with_live_engine(monkeypatch):
    monkeypatch.setenv("CGX_HEALTH", "1")
    eng = health.maybe_start(0)
    sup = _stub_supervisor()
    eng._notify(_ev())  # engine-side delivery, not a direct call
    assert sup.suspect_hints == {1: 9.5}


# ---------------------------------------------------------------------------
# Inertness: every knob unset (the conftest autouse fixture clears CGX_*).
# ---------------------------------------------------------------------------


def test_engine_inert_with_env_unset():
    assert health.maybe_start(0) is None
    assert not health.active()
    assert health.get_engine() is None
    assert health.wait_begin(1, "k") is None
    health.wait_end(None)
    health.note_step(0.1)  # no engine: pure no-op
    assert health.add_consumer(lambda ev: None) is False
    assert watch.maybe_start_prom() is None
    # nothing leaked into the registry
    assert metrics.snapshot("cgx.health.") == {}


def test_engine_lifecycle_and_background_thread(monkeypatch):
    monkeypatch.setenv("CGX_HEALTH", "1")
    monkeypatch.setenv("CGX_HEALTH_INTERVAL_S", "0.02")
    eng = health.maybe_start(2)
    assert eng is not None and health.active()
    assert health.maybe_start(2) is eng  # idempotent
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if metrics.snapshot("cgx.health.step_ratio"):
            break
        time.sleep(0.02)
    assert metrics.snapshot("cgx.health.step_ratio") != {}
    health.stop()
    assert not health.active()


def test_maybe_start_rebinds_unknown_rank(monkeypatch):
    monkeypatch.setenv("CGX_HEALTH", "1")
    eng = health.maybe_start(None)  # make_train_step before dist init
    assert eng.rank == 0
    assert health.maybe_start(3) is eng  # PG init passes the real rank
    assert eng.rank == 3
    assert health.maybe_start(1) is eng  # first real rank wins
    assert eng.rank == 3


# ---------------------------------------------------------------------------
# Chaos acceptance: slow_rank flagged BEFORE the bridge timeout, the
# supervisor holds the hint, and the Prometheus port scrapes live.
# ---------------------------------------------------------------------------

_CHAOS_STALL_MS = 2500
_CHAOS_TIMEOUT_MS = 8000  # the bounded wait must never expire


def _health_chaos_main(rank: int, ws: int, initfile: str, mdir: str, q):
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, _REPO)
        os.environ["CGX_BRIDGE_TIMEOUT_MS"] = str(_CHAOS_TIMEOUT_MS)
        os.environ["CGX_HEALTH"] = "1"
        os.environ["CGX_HEALTH_INTERVAL_S"] = "0.1"
        os.environ["CGX_PROM_PORT"] = "0"
        os.environ["CGX_METRICS_DIR"] = mdir
        os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
        # rank 1 stalls 2.5 s entering its second collective — far below
        # the 8 s bounded wait, far above the 0.1 s evaluator ticks
        os.environ["CGX_FAULTS"] = (
            f"slow_rank:1@{_CHAOS_STALL_MS}ms@step=1"
        )
        import datetime

        import torch
        import torch.distributed as dist

        from torch_cgx_tpu.torch_backend.backend import ProcessGroupCGX
        from torch_cgx_tpu.robustness.supervisor import RecoverySupervisor
        from torch_cgx_tpu.utils.logging import metrics as m

        store = dist.FileStore(initfile, ws)
        pg = ProcessGroupCGX(store, rank, ws, datetime.timedelta(seconds=60))
        sup = RecoverySupervisor(store, pg)
        problems = []
        for _step in range(2):
            t = torch.full((4096,), float(rank + 1))
            pg.allreduce([t]).wait()
        expect = sum(float(r + 1) for r in range(ws))
        if not bool(torch.allclose(
            t, torch.full((4096,), expect), atol=0.5
        )):
            problems.append("wrong reduction")
        if rank == 0:
            # "strictly before the bridge timeout fires": the bounded
            # wait never expired at all — zero timeouts, zero retries —
            # yet the straggler event exists and reached the supervisor.
            if m.get("cgx.bridge_timeout") != 0:
                problems.append("bridge timeout fired")
            if m.get("cgx.recovery.retries") != 0:
                problems.append("retry rung engaged")
            if m.get("cgx.health.events.straggler") < 1:
                problems.append("no straggler event emitted")
            hints = sup.suspect_hints
            if 1 not in hints:
                problems.append(f"supervisor missed the hint: {hints}")
            if m.get("cgx.recovery.health_hints") < 1:
                problems.append("health_hints counter untouched")
            # live scrape while the job is still up
            try:
                port = json.load(
                    open(os.path.join(mdir, "prom-rank0.json"))
                )["port"]
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10
                ).read().decode()
                if "cgx_" not in body:
                    problems.append("no cgx_ samples in exposition")
                for line in body.strip().splitlines():
                    if not line.startswith("#") and not _SAMPLE_RE.match(
                        line
                    ):
                        problems.append(f"unparseable sample: {line!r}")
                        break
            except Exception as e:
                problems.append(f"prometheus scrape failed: {e}")
        pg.shutdown()
        q.put((rank, "; ".join(problems) or None))
    except Exception:
        q.put((rank, traceback.format_exc()))


@pytest.mark.torch_bridge
def test_chaos_slow_rank_flagged_before_bridge_timeout(tmp_path):
    """ISSUE 6 chaos acceptance (see module docstring)."""
    mdir = str(tmp_path / "metrics")
    initfile = tempfile.mktemp(prefix="cgx_health_store_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(
            target=_health_chaos_main, args=(r, 2, initfile, mdir, q)
        )
        for r in range(2)
    ]
    for p in procs:
        p.start()
    results = {}
    for _ in range(2):
        rank, err = q.get(timeout=180)
        results[rank] = err
    for p in procs:
        p.join(timeout=60)
        if p.is_alive():
            p.terminate()
    if os.path.exists(initfile):
        os.unlink(initfile)
    for rank, err in sorted(results.items()):
        assert err is None, f"rank {rank}: {err}"
    # on-disk audit trail: the straggler event stream names global rank 1
    events = [
        json.loads(line)
        for line in open(os.path.join(mdir, "health-rank0.jsonl"))
    ]
    stragglers = [e for e in events if e["kind"] == "straggler"]
    assert stragglers and stragglers[0]["suspect"] == 1, events
    # the stall the event measured sits strictly inside the timeout
    assert dict(stragglers[0]["detail"])["wait_s"] * 1000 < _CHAOS_TIMEOUT_MS
    # the supervisor's black box recorded the handoff
    flight = [
        json.loads(line)
        for line in open(os.path.join(mdir, "flightrec-rank0.jsonl"))
    ]
    assert any(
        e.get("kind") == "recovery" and e.get("phase") == "health_hint"
        and e.get("suspect") == 1
        for e in flight
    ), [e.get("phase") for e in flight if e.get("kind") == "recovery"]
    # the leader folded a cluster health view at shutdown
    cluster = os.path.join(mdir, "cluster-health.jsonl")
    assert os.path.exists(cluster)
    view = json.loads(open(cluster).readlines()[-1])
    assert 0 in view["ranks_reporting"]
