"""Memory observability plane tests (ISSUE 18).

Unit suite: the forecaster's least-squares math against a numpy
polyfit oracle, KV-pool gauge truth under churn/fork/exhaustion (the
published ``cgx.serve.pool_free``/``pool_dedup_pages`` gauges vs an
independent shadow model of every alloc/fork/free), arena
fragmentation vs a brute-force byte-map free-extent scan, the
sliding-window leak detector (strict monotonicity fires, a sawtooth
does not), the ``mem_pressure`` lead window, snapshot flush → the
``cgx_mem`` CLI round-trip, the leader-side cluster merge, the
planner's memory envelope + staging budget, health-event plumbing,
reset-reachability from the supervisor cascade, and inertness with
``CGX_MEMLEDGER`` unset.

Chaos acceptance: a ``leak_page`` fault run — every last-reference
drop silently loses its page — where the detector names
``serve.kv_pool`` strictly before the pool exhausts and the forecaster
raises ``mem_pressure`` at least one lead window before the wall. The
bit-identity half of the acceptance (env unset ⇒ staged programs /
store keys / wire bytes unchanged) is carried by the test_grad_sync
suite, which runs with all CGX_* env cleared.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

import numpy as np
import pytest

from torch_cgx_tpu.observability import health, memledger, watch
from torch_cgx_tpu.robustness import faults
from torch_cgx_tpu.serving import kv_cache as kv_mod
from torch_cgx_tpu.utils.logging import metrics

from test_faults import FakeStore

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


@pytest.fixture(autouse=True)
def _fresh():
    metrics.reset()
    faults.reset_injectors()
    yield
    memledger.stop()
    health.stop()
    faults.reset_injectors()
    metrics.reset()


def _install_ledger(monkeypatch, flush_s=1.0, window=3, rank=0):
    """A deterministic ledger: installed as the process singleton (so the
    note_alloc/note_release shims route to it) but never started — tests
    drive sample(now=...) by hand."""
    led = memledger.MemLedger(rank=rank, flush_s=flush_s, leak_window=window)
    monkeypatch.setattr(memledger, "_ledger", led)
    return led


# ---------------------------------------------------------------------------
# Forecaster math vs numpy oracle.
# ---------------------------------------------------------------------------


def test_trend_tte_matches_polyfit_oracle():
    from collections import deque

    rng = np.random.default_rng(3)
    ts = np.cumsum(rng.uniform(0.5, 1.5, size=12))
    free = 1000.0 - 37.0 * ts + rng.normal(0, 0.5, size=12)
    hist = deque(zip(ts.tolist(), free.tolist()))
    tte = memledger._trend_tte_s(hist)
    slope, _ = np.polyfit(ts - ts[0], free, 1)
    assert slope < 0
    assert tte == pytest.approx(free[-1] / -slope, rel=1e-6)


def test_trend_tte_none_on_flat_rising_or_short():
    from collections import deque

    assert memledger._trend_tte_s(deque([(0, 5.0), (1, 4.0)])) is None
    flat = deque([(float(i), 10.0) for i in range(6)])
    assert memledger._trend_tte_s(flat) is None
    rising = deque([(float(i), 10.0 + i) for i in range(6)])
    assert memledger._trend_tte_s(rising) is None
    # already exhausted with a downward trend: 0, not a division blow-up
    drained = deque([(0.0, 2.0), (1.0, 1.0), (2.0, 0.0)])
    assert memledger._trend_tte_s(drained) == 0.0


# ---------------------------------------------------------------------------
# KV-pool gauge truth: churn / fork / exhaustion vs a shadow model.
# ---------------------------------------------------------------------------


def _shadow_truth(held):
    """(free, dedup) from an independent seq -> pages shadow model."""
    counts: dict = {}
    for pages in held.values():
        for pid in pages:
            counts[pid] = counts.get(pid, 0) + 1
    dedup = sum(c - 1 for c in counts.values() if c > 1)
    return counts, dedup


def test_kv_pool_gauges_truthful_under_churn_and_fork():
    cache = kv_mod.PagedKvCache(max_pages=16, page_tokens=4)
    cache.publish_pool_gauges()  # gauges valid from birth, not first alloc
    rng = np.random.default_rng(0)
    held: dict = {}
    for i in range(300):
        r = rng.random()
        sid = f"s{rng.integers(0, 8)}"
        if r < 0.45:
            pid = cache.alloc(sid)
            if pid is not None:
                held.setdefault(sid, []).append(pid)
        elif r < 0.75 and sid in held:
            cache.free_seq(sid)
            held.pop(sid)
        elif sid in held:
            dst = f"f{i}"
            cache.fork(sid, dst)
            held[dst] = list(held[sid])
        counts, dedup = _shadow_truth(held)
        free_truth = cache.max_pages - len(counts)
        # The gauges ARE the pool's truth after every mutator — alloc,
        # free AND fork (the dedup-changing mutator the old
        # pool_free-only refresh missed).
        assert metrics.get("cgx.serve.pool_free") == free_truth
        assert metrics.get("cgx.serve.pool_dedup_pages") == dedup
        st = cache.pool_stats()
        assert st["free_pages"] == free_truth
        assert st["dedup_pages"] == dedup
        assert st["leaked_pages"] == 0


def test_kv_pool_exhaustion_gauge_and_ledger_tick_refresh():
    cache = kv_mod.PagedKvCache(max_pages=2, page_tokens=4)
    assert cache.alloc("a") is not None
    assert cache.alloc("a") is not None
    assert cache.alloc("a") is None  # backpressure, not an error
    assert metrics.get("cgx.serve.pool_free") == 0
    # Between decode steps nothing mutates — the ledger's sampler still
    # refreshes the gauges from live truth (satellite 2).
    metrics.set("cgx.serve.pool_free", 99.0)  # a stale scrape value
    rows = memledger._kv_rows()
    (row,) = [r for r in rows if r["pool"].startswith("serve.kv_pool")]
    assert metrics.get("cgx.serve.pool_free") == 0
    assert row["free_units"] == 0.0
    assert row["capacity_units"] == 2.0
    cache.free_seq("a")
    assert metrics.get("cgx.serve.pool_free") == 2


# ---------------------------------------------------------------------------
# Arena fragmentation vs a brute-force byte-map scan.
# ---------------------------------------------------------------------------


def _brute_force_extents(arena):
    """Free extents per generation from a byte occupancy map over the
    pending regions — independent of the head/tail arithmetic
    mem_stats() uses."""
    with arena._lock:
        caps = {g: gf.capacity for g, gf in arena._gens.items()}
        spans = [(r.gen, r.off, r.size) for r in arena._pending]
    extents = []
    for g, cap in caps.items():
        occ = np.zeros(cap, dtype=bool)
        for gen, off, size in spans:
            if gen == g:
                occ[off:off + size] = True
        run = 0
        for byte_used in occ:
            if byte_used:
                if run:
                    extents.append(run)
                run = 0
            else:
                run += 1
        if run:
            extents.append(run)
    return extents


def test_arena_frag_matches_brute_force_scan():
    from torch_cgx_tpu.torch_backend.shm import ShmArena

    acks: dict = {}
    arena = ShmArena(
        tempfile.gettempdir(),
        f"cgxmemtest-{os.getpid()}",
        poll_ack=lambda k: acks.get(k, 0),
        drop_keys=lambda ks: None,
        min_capacity=1 << 12,  # 4 KB ring
    )
    rng = np.random.default_rng(7)
    try:
        seen_frag = set()
        for i in range(60):
            if rng.random() < 0.6:
                size = int(rng.integers(256, 1280))
                arena.write(bytes(size), f"m{i}/ack", 1)
            else:
                pend = [k for k in (f"m{j}/ack" for j in range(i))
                        if k not in acks]
                if pend:
                    acks[rng.choice(pend)] = 1
            st = arena.mem_stats()
            brute = _brute_force_extents(arena)
            total, largest = sum(brute), max(brute) if brute else 0
            assert st["free_bytes"] == total
            assert st["largest_free_bytes"] == largest
            want = (1.0 - largest / total) if total > 0 else 0.0
            assert st["frag"] == pytest.approx(want, abs=1e-4)
            seen_frag.add(round(st["frag"], 2))
        # The schedule actually exercised fragmentation, not just one
        # trivial all-free/all-full state.
        assert len(seen_frag) >= 2 and max(seen_frag) > 0.0
    finally:
        arena.close()


def test_arena_region_table_names_hoarder_oldest_first():
    from torch_cgx_tpu.torch_backend.shm import ShmArena

    arena = ShmArena(
        tempfile.gettempdir(),
        f"cgxregtest-{os.getpid()}",
        poll_ack=lambda k: 0,
        drop_keys=lambda ks: None,
        min_capacity=1 << 12,
    )
    try:
        for i in range(3):
            arena.write(bytes(512), f"hoard{i}/ack", 2)
        table = arena.region_table(limit=8)
        assert [r["owner"] for r in table[:3]] == [
            "hoard0/ack", "hoard1/ack", "hoard2/ack",
        ]
        assert all(r["size"] == 512 and r["readers"] == 2 for r in table[:3])
        assert all(r["age_s"] >= 0.0 for r in table)
    finally:
        arena.close()


# ---------------------------------------------------------------------------
# Leak detector: strict monotonicity over the full window.
# ---------------------------------------------------------------------------


def test_leak_detector_fires_on_strict_growth_only(monkeypatch):
    led = _install_ledger(monkeypatch, flush_s=1.0, window=3)
    # Sawtooth: alloc bursts that settle never fire.
    for t in range(6):
        memledger.note_alloc("app.buf")
        if t % 2:
            memledger.note_release("app.buf")
            memledger.note_release("app.buf")
            memledger.note_alloc("app.buf")
        snap = led.sample(now=float(t))
        assert not [f for f in snap["findings"] if f["kind"] == "mem_leak"]
    led.reset("test")
    # Strict growth: one extra outstanding per sample names the owner
    # exactly when the window fills, not earlier.
    hits = []
    for t in range(4):
        memledger.note_alloc("serve.kv_pool")
        snap = led.sample(now=100.0 + t)
        hits.append([
            f["owner"] for f in snap["findings"] if f["kind"] == "mem_leak"
        ])
    assert hits[0] == [] and hits[1] == []
    assert hits[2] == ["serve.kv_pool"]
    assert led.leak_suspects() == ["serve.kv_pool"]
    assert metrics.get("cgx.mem.leak_suspects") == 1
    assert metrics.get("cgx.mem.events.mem_leak") >= 1


def test_forecaster_pressure_precedes_exhaustion_by_lead(monkeypatch):
    led = _install_ledger(monkeypatch, flush_s=1.0, window=3)
    lead_s = 3 * 1.0
    free = [100.0]

    def draining_pool():
        return [{
            "pool": "test.pool", "kind": "test",
            "used_bytes": int((100.0 - free[0]) * 1024),
            "capacity_bytes": 100 * 1024,
            "free_units": free[0], "capacity_units": 100.0,
            "frag": None, "detail": {},
        }]

    led.register_sampler(draining_pool)
    first_pressure = None
    first_empty = None
    for t in range(101):
        snap = led.sample(now=float(t))
        hit = [
            f for f in snap["findings"]
            if f["kind"] == "mem_pressure" and f["owner"] == "test.pool"
        ]
        if hit and first_pressure is None:
            first_pressure = t
            assert hit[0]["value"] <= lead_s
            # The published forecast gauge carries the same tte.
            assert metrics.get(
                "cgx.mem.pool_tte_s.test.pool"
            ) == pytest.approx(hit[0]["value"])
        if free[0] <= 0 and first_empty is None:
            first_empty = t
        free[0] -= 1.0
    assert first_pressure is not None and first_empty is not None
    # The whole point: the warning lands >= one lead window before the wall.
    assert first_empty - first_pressure >= lead_s
    assert metrics.get("cgx.mem.events.mem_pressure") >= 1


def test_peak_tracks_high_water_and_bench_hook(monkeypatch):
    led = _install_ledger(monkeypatch)
    # Exact-total oracle: silence the builtin samplers so ambient jax
    # arrays left live by earlier test files can't pad the byte count.
    monkeypatch.setattr(memledger, "_BUILTIN_SAMPLERS", ())
    big = [1 << 24]

    def pool():
        return [{
            "pool": "test.big", "kind": "test", "used_bytes": big[0],
            "capacity_bytes": 0, "free_units": 0.0, "capacity_units": 0.0,
            "frag": None, "detail": {},
        }]

    led.register_sampler(pool)
    led.sample(now=0.0)
    big[0] = 1 << 20  # shrink: peak must hold the high-water mark
    led.sample(now=1.0)
    assert led.peak_mb() == pytest.approx(16.0)
    assert metrics.get("cgx.mem.peak_mb") == pytest.approx(16.0)
    assert metrics.get("cgx.mem.total_mb") == pytest.approx(1.0)
    # The bench harness's module-level hook sees the same number.
    assert memledger.peak_mb() == pytest.approx(16.0)


# ---------------------------------------------------------------------------
# Chaos acceptance: leak_page named before exhaustion.
# ---------------------------------------------------------------------------


def test_leak_page_chaos_detector_names_pool_before_exhaustion(monkeypatch):
    monkeypatch.setenv("CGX_FAULTS", "leak_page:1.0")
    faults.reset_injectors()
    led = _install_ledger(monkeypatch, flush_s=1.0, window=3)
    cache = kv_mod.PagedKvCache(max_pages=12, page_tokens=4)
    first_leak = None
    first_pressure = None
    exhausted_at = None
    for t in range(13):
        pid = cache.alloc(f"s{t}")
        if pid is None:
            exhausted_at = t
            break
        # Last reference drops -> the injected fault swallows the page.
        assert cache.free_seq(f"s{t}") == 0
        snap = led.sample(now=float(t))
        kinds = {f["kind"]: f for f in snap["findings"]}
        if "mem_leak" in kinds and first_leak is None:
            first_leak = t
            assert kinds["mem_leak"]["owner"] == "serve.kv_pool"
        if "mem_pressure" in kinds and first_pressure is None:
            assert kinds["mem_pressure"]["owner"].startswith("serve.kv_pool")
            first_pressure = t
    assert exhausted_at is not None  # the fault really drains the pool
    assert cache.pool_stats()["leaked_pages"] == 12
    # The detector names the owning site strictly before the wall...
    assert first_leak is not None and first_leak < exhausted_at
    # ...and the forecaster leads the wall by at least the lead window.
    assert first_pressure is not None
    assert exhausted_at - first_pressure >= 3
    assert metrics.get("cgx.faults.leak_page") == 12
    # invalidate() rebuilds the free list: chaos-leaked pages come back
    # and the release settles the ledger delta.
    cache.invalidate("chaos cleanup")
    assert cache.pool_stats()["leaked_pages"] == 0
    assert cache.free_pages == 12
    site = led.sample(now=99.0)["sites"]["serve.kv_pool"]
    assert site["outstanding"] == 0


# ---------------------------------------------------------------------------
# Health plumbing, reset cascade, inertness.
# ---------------------------------------------------------------------------


def test_note_mem_event_shape_and_kind_validation():
    eng = health.HealthEngine(0)
    ev = eng.note_mem("mem_leak", 5.0, 3.0, owner="serve.kv_pool", grew_by=5)
    assert ev is not None and ev.kind == "mem_leak"
    detail = dict(ev.detail)
    assert detail["owner"] == "serve.kv_pool" and detail["grew_by"] == 5
    assert ev.threshold == 3.0
    with pytest.raises(ValueError):
        eng.note_mem("straggler", 1.0, 1.0)
    assert "mem_leak" in health.EVENT_KINDS
    assert "mem_pressure" in health.EVENT_KINDS


def test_supervisor_cascade_resets_ledger(monkeypatch):
    from torch_cgx_tpu.robustness import supervisor

    led = _install_ledger(monkeypatch)
    memledger.note_alloc("shm.arena", nbytes=4096)
    led.sample(now=0.0)
    assert led.sample(now=1.0)["sites"]
    supervisor.invalidate_trace_caches()
    snap = led.sample(now=2.0)
    assert snap["sites"] == {}  # pre-recovery history would fabricate leaks
    assert metrics.get("cgx.mem.resets") >= 1


def test_inert_when_unset(monkeypatch):
    monkeypatch.delenv("CGX_MEMLEDGER", raising=False)
    assert memledger.maybe_start(0) is None
    assert not memledger.active()
    assert memledger.peak_mb() is None
    # The hot-path hooks are a single global load, never an error.
    memledger.note_alloc("serve.kv_pool")
    memledger.note_release("serve.kv_pool")
    memledger.reset_ledger("noop")
    assert metrics.get("cgx.mem.samples") == 0


def test_maybe_start_first_wins_rank_rebind(monkeypatch):
    monkeypatch.setenv("CGX_MEMLEDGER", "1")
    led = memledger.maybe_start(None)
    assert led is not None and led.rank == 0
    assert memledger.maybe_start(3) is led
    assert led.rank == 3
    assert memledger.maybe_start(5) is led
    assert led.rank == 3  # first nonzero bind wins


# ---------------------------------------------------------------------------
# Snapshot flush -> CLI / report / cluster merge round-trips.
# ---------------------------------------------------------------------------


def test_flush_snapshot_and_cgx_mem_cli_roundtrip(
    monkeypatch, tmp_path, capsys
):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    led = _install_ledger(monkeypatch, window=3)
    cache = kv_mod.PagedKvCache(max_pages=4, page_tokens=4)
    cache.alloc("s")
    for _ in range(3):
        memledger.note_alloc("serve.kv_pool")  # force a leak finding
        led.flush()
    path = tmp_path / "mem-rank0.jsonl"
    assert path.exists()
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(recs) == 3
    pools = {r["pool"] for r in recs[-1]["pools"]}
    assert any(p.startswith("serve.kv_pool") for p in pools)
    from tools import cgx_mem

    assert cgx_mem.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "owner tree" in out and "serve.kv_pool" in out
    assert "leak suspects" in out
    assert cgx_mem.main([str(tmp_path), "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["ranks"] == [0]
    assert "serve.kv_pool" in summary["leak_suspects"]
    # cgx_report folds the same files into its == memory == section.
    from tools import cgx_report

    mem = cgx_report._memory_summary(str(tmp_path))
    assert mem is not None and mem["ranks"] == [0]
    assert "serve.kv_pool" in mem["leak_suspects"]
    assert cgx_mem.main(["/nonexistent-dir"]) == 2


def test_cluster_merge_over_store(monkeypatch, tmp_path):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    store = FakeStore()
    led = _install_ledger(monkeypatch, rank=1)
    memledger.note_alloc("shm.arena", nbytes=1 << 20)
    led.sample(now=0.0)
    assert watch.aggregate_mem_over_store(store, 1, 2) is None  # follower
    led.rebind_rank(0)
    view = watch.aggregate_mem_over_store(store, 0, 2)
    assert view is not None
    assert view["ranks_reporting"] == [0, 1]
    assert view["missing_ranks"] == []
    assert view["world_size"] == 2
    lines = (tmp_path / "cluster-mem.jsonl").read_text().splitlines()
    assert json.loads(lines[-1])["ranks_reporting"] == [0, 1]
    # A rank that never published is named, not waited on forever.
    view3 = watch.aggregate_mem_over_store(store, 0, 3, round_id=1,
                                           timeout_s=0.2)
    assert view3["missing_ranks"] == [1, 2]


def test_merge_noop_without_ledger():
    assert memledger.get_ledger() is None
    assert watch.aggregate_mem_over_store(FakeStore(), 0, 1) is None


# ---------------------------------------------------------------------------
# Planner: memory envelope + staging budget.
# ---------------------------------------------------------------------------


def test_memory_envelope_scales_with_depth():
    from torch_cgx_tpu.parallel import planner

    cm = planner.CostModel()
    e1 = cm.memory_envelope(1 << 20, ws=8, bits=4, bucket=512, chunks=1)
    e4 = cm.memory_envelope(1 << 20, ws=8, bits=4, bucket=512, chunks=4)
    assert e1["fusion_bytes"] == e4["fusion_bytes"] == 4.0 * (1 << 20)
    # Deeper pipeline -> smaller frames -> smaller staging footprint.
    assert e4["frame_bytes"] == pytest.approx(e1["frame_bytes"] / 4)
    assert e4["staging_bytes"] < e1["staging_bytes"]
    assert e4["total_bytes"] < e1["total_bytes"]
    # Degenerate shapes cost nothing rather than dividing by zero.
    z = cm.memory_envelope(0, ws=8, bits=4, bucket=512)
    assert z["total_bytes"] == 0.0


def test_staging_budget_gates_plan_and_keys(monkeypatch):
    from torch_cgx_tpu.parallel import planner

    monkeypatch.delenv("CGX_MEMLEDGER", raising=False)
    assert planner._staging_budget() is None
    key_off = planner.cache_key_component()
    monkeypatch.setenv("CGX_MEMLEDGER", "1")
    monkeypatch.setenv("CGX_SHM_MAX_MB", "64")
    assert planner._staging_budget() == 64 << 20
    # The budget is part of the planner's trace-key contribution: a
    # toggle retraces instead of serving a stale plan.
    assert planner.cache_key_component() != key_off
    # A budget below every candidate's staging forces the min-staging
    # (deepest) fallback rather than an infeasible plan.
    from torch_cgx_tpu.config import CompressionConfig

    cm = planner.CostModel()
    n = 1 << 22
    cc = CompressionConfig(bits=4, bucket_size=512)
    c_open, t_open = planner._best_chunks(cm, n, 8, 4, cc, "staged")
    c_tight, t_tight = planner._best_chunks(
        cm, n, 8, 4, cc, "staged", staging_budget=1
    )
    deepest = max(planner._slice_candidates(n, 8, cc))
    assert c_tight == deepest  # smallest frames, soonest reclaim
    assert cm.memory_envelope(n, 8, 4, 512, chunks=c_tight)[
        "staging_bytes"
    ] <= cm.memory_envelope(n, 8, 4, 512, chunks=c_open)["staging_bytes"]
    # A budget that fits everything changes nothing.
    c_loose, t_loose = planner._best_chunks(
        cm, n, 8, 4, cc, "staged", staging_budget=1 << 40
    )
    assert (c_loose, t_loose) == (c_open, t_open)
