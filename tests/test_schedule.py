"""Compiled collective schedules (ISSUE 9 — ``parallel/schedule.py``).

Covers the schedule compiler (chunk tables, the bit-equality contract of
column-block chunking), the software-pipelined staged executor (bit-equal
to the monolithic SRA on any payload, wire decode included; jaxpr-guarded
zero host callbacks and per-chunk kernel counts), the schedule LRU
(keying, hit/miss accounting, invalidation through BOTH
``allreduce.invalidate_layout_cache`` and
``supervisor.invalidate_trace_caches``), inertness with the knob unset,
the reverse-layer-order group emission, and the bridge's dependency-light
chunk-table duplicate.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.config import CompressionConfig
from torch_cgx_tpu.parallel import reducers, schedule
from torch_cgx_tpu.parallel.allreduce import (
    allreduce_tree,
    invalidate_layout_cache,
)
from torch_cgx_tpu.utils.compat import shard_map

WS = 4
BUCKET = 512


@pytest.fixture(autouse=True)
def _fresh_caches():
    schedule.schedule_cache_clear()
    yield
    schedule.schedule_cache_clear()


def _mesh(ws=WS):
    return Mesh(np.asarray(jax.devices()[:ws]), ("dp",))


def _run_sharded(fn, per_rank, ws=WS, n_out=1):
    mesh = _mesh(ws)
    out_specs = P("dp") if n_out == 1 else (P("dp"),) * n_out
    body = shard_map(
        fn, mesh=mesh, in_specs=P("dp"), out_specs=out_specs,
        check_vma=False,
    )
    arr = jax.device_put(
        jnp.asarray(per_rank), NamedSharding(mesh, P("dp"))
    )
    return jax.jit(body)(arr)


# ---------------------------------------------------------------------------
# Chunk tables.
# ---------------------------------------------------------------------------


def test_chunk_table_alignment_and_coverage():
    align = schedule.chunk_alignment(BUCKET)
    for width in (align * 8, align * 8 + 32, align * 3, 100_000):
        table = schedule.chunk_table(width, 4, BUCKET)
        # covers [0, width) contiguously
        off = 0
        for o, w in table:
            assert o == off
            off += w
        assert off == width
        # every interior boundary bucket-aligned
        for o, _w in table[1:]:
            assert o % align == 0


def test_chunk_table_degrades_below_depth():
    align = schedule.chunk_alignment(BUCKET)
    assert schedule.chunk_table(align - 32, 4, BUCKET) == ((0, align - 32),)
    assert schedule.chunk_table(align, 4, BUCKET) == ((0, align),)
    assert len(schedule.chunk_table(align * 2, 4, BUCKET)) == 2
    assert len(schedule.chunk_table(align * 16, 4, BUCKET)) == 4


def test_bridge_chunk_table_matches_compiler():
    """The bridge keeps a dependency-light duplicate
    (``backend._sched_chunk_table`` — it must not import the parallel
    package into every rank process); the two derivations must agree on
    every (width, depth, bucket)."""
    from torch_cgx_tpu.torch_backend import backend as be

    for width in (0, 100, 512, 16384, 100_000, 2**21):
        for chunks in (1, 2, 4, 8):
            for bucket in (128, 512, 1024):
                assert tuple(
                    be._sched_chunk_table(width, chunks, bucket)
                ) == schedule.chunk_table(width, chunks, bucket), (
                    width, chunks, bucket,
                )


# ---------------------------------------------------------------------------
# Staged pipelined executor: bit-equality + jaxpr guards.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [WS * BUCKET * 8, 100_000, 12_345])
def test_pipelined_bit_equal_to_monolithic(monkeypatch, n):
    """The column-block pipeline preserves SRA ownership and the bucket
    grid, so a deterministic pipelined run is bit-equal to the monolithic
    SRA on ANY payload — reduced output AND wire decode."""
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    monkeypatch.setenv("CGX_SCHED_CHUNKS", "4")
    cc = CompressionConfig(bits=4, bucket_size=BUCKET)
    sched = schedule.compiled_schedule(n, WS, cc)
    assert sched is not None and sched.depth >= 2
    rng = np.random.default_rng(0)
    per = rng.normal(size=(WS, n)).astype(np.float32)

    def mono(x):
        o, rt = reducers.sra_allreduce_with_wire(x[0], "dp", WS, cc, None)
        return o[None], rt[None]

    def pipe(x):
        o, rt = schedule.pipelined_quantized_allreduce(
            x[0], "dp", WS, cc, "SRA", None, sched, with_wire=True
        )
        return o[None], rt[None]

    om, om_rt = map(np.asarray, _run_sharded(mono, per, n_out=2))
    op, op_rt = map(np.asarray, _run_sharded(pipe, per, n_out=2))
    assert np.array_equal(om, op)
    assert np.array_equal(om_rt, op_rt)
    # error symmetry: all replicas hold identical bytes
    assert all(np.array_equal(op[0], op[r]) for r in range(WS))


def test_pipelined_jaxpr_per_chunk_kernels_no_callbacks(monkeypatch):
    """The staged pipeline stays pure — zero host callbacks — and runs
    exactly one quantize + one epilogue(+decode) composition PER CHUNK:
    the chunked program's codec invocation count scales with depth, and
    per-chunk collectives (all_to_all + all_gather each) are all present
    in one traced program."""
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    monkeypatch.setenv("CGX_SCHED_CHUNKS", "4")
    cc = CompressionConfig(bits=4, bucket_size=BUCKET)
    n = WS * BUCKET * 16
    sched = schedule.compiled_schedule(n, WS, cc)
    assert sched is not None
    depth = sched.depth

    def pipe(x):
        return schedule.pipelined_quantized_allreduce(
            x[0], "dp", WS, cc, "SRA", None, sched
        )[None]

    mesh = _mesh()
    body = shard_map(
        pipe, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False,
    )
    jaxpr = jax.make_jaxpr(body)(jnp.zeros((WS, n), jnp.float32))
    txt = str(jaxpr)
    assert "io_callback" not in txt and "pure_callback" not in txt

    def count_prims(jx, name):
        total = 0
        for eqn in jx.eqns:
            if eqn.primitive.name == name:
                total += 1
            for v in eqn.params.values():
                for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                    while hasattr(sub, "jaxpr"):  # ClosedJaxpr -> Jaxpr
                        sub = sub.jaxpr
                    if hasattr(sub, "eqns"):
                        total += count_prims(sub, name)
        return total

    def mono(x):
        return reducers.sra_allreduce(x[0], "dp", WS, cc, None)[None]

    mono_jx = jax.make_jaxpr(
        shard_map(
            mono, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,
        )
    )(jnp.zeros((WS, n), jnp.float32)).jaxpr
    inner = jaxpr.jaxpr
    # One full quantize->exchange->epilogue->allgather composition PER
    # CHUNK: every collective the monolithic program stages once (one
    # all_to_all + one all_gather per QTensor leaf) appears depth times.
    for prim in ("all_to_all", "all_gather"):
        per_mono = count_prims(mono_jx, prim)
        assert per_mono > 0
        assert count_prims(inner, prim) == depth * per_mono, prim


def test_pipelined_rejects_non_sra():
    cc = CompressionConfig(bits=4, bucket_size=BUCKET)
    sched = schedule.CompiledSchedule(
        table=((0, 512), (512, 512)), n=4096, ws=WS, chunk=1024, cc=cc
    )
    with pytest.raises(ValueError, match="SRA"):
        schedule.pipelined_quantized_allreduce(
            jnp.zeros(4096), "dp", WS, cc, "RING", None, sched
        )


# ---------------------------------------------------------------------------
# Engagement gates + the schedule LRU.
# ---------------------------------------------------------------------------


def test_compiled_schedule_gates(monkeypatch):
    cc = CompressionConfig(bits=4, bucket_size=BUCKET)
    n = WS * BUCKET * 16
    # unset (auto) on the CPU backend: inert
    monkeypatch.delenv("CGX_SCHEDULE", raising=False)
    assert schedule.compiled_schedule(n, WS, cc) is None
    monkeypatch.setenv("CGX_SCHEDULE", "off")
    assert schedule.compiled_schedule(n, WS, cc) is None
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    assert schedule.compiled_schedule(n, WS, cc) is not None
    # non-SRA reductions, ws==1, disabled compression: never pipelined
    assert schedule.compiled_schedule(n, WS, cc, reduction="RING") is None
    assert schedule.compiled_schedule(n, 1, cc) is None
    assert schedule.compiled_schedule(
        n, WS, CompressionConfig(bits=32)
    ) is None
    # payload too small for 2 chunks: None — and the negative result is
    # itself cached (second probe is a HIT, not a re-derive; a realistic
    # tree's tiny fusion slice probes every collective)
    schedule.schedule_cache_clear()
    assert schedule.compiled_schedule(64, WS, cc) is None
    misses = schedule.schedule_cache_stats()["misses"]
    assert schedule.compiled_schedule(64, WS, cc) is None
    stats = schedule.schedule_cache_stats()
    assert stats["misses"] == misses and stats["hits"] == 1


def test_schedule_cache_hits_and_knob_keying(monkeypatch):
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    cc = CompressionConfig(bits=4, bucket_size=BUCKET)
    n = WS * BUCKET * 16
    schedule.schedule_cache_clear()
    s1 = schedule.compiled_schedule(n, WS, cc)
    stats = schedule.schedule_cache_stats()
    assert (stats["hits"], stats["misses"]) == (0, 1)
    s2 = schedule.compiled_schedule(n, WS, cc)
    assert s2 is s1
    assert schedule.schedule_cache_stats()["hits"] == 1
    # a CGX_SCHED_CHUNKS flip is a different key — fresh plan, not stale
    monkeypatch.setenv("CGX_SCHED_CHUNKS", "2")
    s3 = schedule.compiled_schedule(n, WS, cc)
    assert s3 is not None and s3.depth == 2
    assert schedule.schedule_cache_stats()["misses"] == 2


def test_invalidation_drops_compiled_schedules(monkeypatch):
    """Satellite 4: BOTH invalidation entry points —
    ``allreduce.invalidate_layout_cache`` and
    ``supervisor.invalidate_trace_caches`` — must drop compiled schedules
    (a stale chunk plan after a PR 5 reconfigure would wedge the
    in-flight window against peers on the fresh world's plan)."""
    from torch_cgx_tpu.robustness import supervisor as sup

    monkeypatch.setenv("CGX_SCHEDULE", "on")
    cc = CompressionConfig(bits=4, bucket_size=BUCKET)
    n = WS * BUCKET * 16

    schedule.compiled_schedule(n, WS, cc)
    assert schedule.schedule_cache_stats()["misses"] == 1
    invalidate_layout_cache("test")
    assert schedule.schedule_cache_stats() == {"hits": 0, "misses": 0}
    assert not schedule._SCHED_CACHE

    schedule.compiled_schedule(n, WS, cc)
    assert schedule._SCHED_CACHE
    sup.invalidate_trace_caches()
    assert not schedule._SCHED_CACHE
    # the registry-version bump alone would also re-key, but the cache
    # must be EMPTY (stale plans must not age out while holding memory)
    assert schedule.schedule_cache_stats() == {"hits": 0, "misses": 0}


def test_cache_key_component_tracks_knobs(monkeypatch):
    monkeypatch.delenv("CGX_SCHEDULE", raising=False)
    monkeypatch.delenv("CGX_SCHED_CHUNKS", raising=False)
    base = schedule.cache_key_component()
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    assert schedule.cache_key_component() != base
    monkeypatch.setenv("CGX_SCHED_CHUNKS", "7")
    assert schedule.cache_key_component() == ("on", 7)


# ---------------------------------------------------------------------------
# allreduce_tree integration: inertness + reverse-order emission.
# ---------------------------------------------------------------------------


def _tree_sync(tree, ws=WS):
    mesh = _mesh(ws)

    def body(t):
        sq = jax.tree.map(lambda l: l[0], t)
        out = allreduce_tree(sq, mesh=mesh, axes=("dp",))
        return jax.tree.map(lambda l: l[None], out)

    sm = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False,
    ))
    return jax.tree.map(np.asarray, sm(tree))


def test_allreduce_tree_values_invariant_under_schedule(monkeypatch):
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    rng = np.random.default_rng(1)
    tree = {
        "big": jnp.asarray(
            rng.normal(size=(WS, 300, 300)).astype(np.float32)
        ),
        "mid": jnp.asarray(rng.normal(size=(WS, 64, 64)).astype(np.float32)),
        "tiny": jnp.asarray(rng.normal(size=(WS, 7)).astype(np.float32)),
    }
    monkeypatch.delenv("CGX_SCHEDULE", raising=False)
    base = _tree_sync(tree)
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    on = _tree_sync(tree)
    for k in tree:
        assert np.array_equal(base[k], on[k]), k
    assert schedule.schedule_cache_stats()["misses"] >= 1


def test_schedule_unset_stages_identical_program(monkeypatch):
    """The inertness pin at the program level: with CGX_SCHEDULE unset
    (auto, CPU backend) the traced program of allreduce_tree is
    IDENTICAL to the pre-schedule code — same jaxpr text, no pipelined
    chunks, no reverse-order emission."""
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    rng = np.random.default_rng(2)
    tree = {
        "a": jnp.zeros((WS, 200, 200), jnp.float32),
        "b": jnp.zeros((WS, 33), jnp.float32),
    }
    mesh = _mesh()

    def body(t):
        sq = jax.tree.map(lambda l: l[0], t)
        out = allreduce_tree(sq, mesh=mesh, axes=("dp",))
        return jax.tree.map(lambda l: l[None], out)

    sm = shard_map(
        body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False,
    )
    monkeypatch.delenv("CGX_SCHEDULE", raising=False)
    j_unset = str(jax.make_jaxpr(sm)(tree))
    monkeypatch.setenv("CGX_SCHEDULE", "off")
    j_off = str(jax.make_jaxpr(sm)(tree))
    assert j_unset == j_off
    del rng


def test_dispatch_order_reverses_groups():
    assert schedule.dispatch_order(4) == (3, 2, 1, 0)
    assert schedule.dispatch_order(1) == (0,)
    assert schedule.dispatch_order(0) == ()


def test_grad_sync_trace_cache_keys_schedule(monkeypatch):
    """make_train_step's build cache must key on the schedule component:
    a CGX_SCHEDULE flip between calls retraces instead of serving a
    trace from another scheduling era (values stay identical — pinned
    above — but the emission differs)."""
    import optax

    from torch_cgx_tpu.parallel import make_train_step

    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    mesh = _mesh(2)
    params = {"w": jnp.ones((BUCKET * 8,), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ jnp.ones((1,)) - p["w"].sum()) ** 2)

    opt = optax.sgd(1e-2)
    step = make_train_step(loss_fn, opt, mesh, axes=("dp",), donate=False)
    batch = {"x": jnp.ones((2, 1), jnp.float32)}
    opt_state = opt.init(params)
    monkeypatch.delenv("CGX_SCHEDULE", raising=False)
    step(params, opt_state, batch, 0)
    builds0 = int(
        __import__(
            "torch_cgx_tpu.utils.logging", fromlist=["metrics"]
        ).metrics.get("cgx.trace.train_step_builds")
    )
    step(params, opt_state, batch, 1)  # same era: cached, no rebuild
    from torch_cgx_tpu.utils.logging import metrics as _m

    assert int(_m.get("cgx.trace.train_step_builds")) == builds0
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    step(params, opt_state, batch, 2)  # new era: fresh build
    assert int(_m.get("cgx.trace.train_step_builds")) == builds0 + 1


# ---------------------------------------------------------------------------
# Registry/env hygiene for the suite.
# ---------------------------------------------------------------------------


def test_engaged_follows_mode(monkeypatch):
    monkeypatch.delenv("CGX_SCHEDULE", raising=False)
    assert schedule.engaged() is (jax.default_backend() == "tpu")
    monkeypatch.setenv("CGX_SCHEDULE", "on")
    assert schedule.engaged() is True
    monkeypatch.setenv("CGX_SCHEDULE", "off")
    assert schedule.engaged() is False
    monkeypatch.setenv("CGX_SCHEDULE", "bogus")
    with pytest.raises(ValueError):
        cgx_config.schedule_mode()


def test_sched_chunks_floor(monkeypatch):
    monkeypatch.setenv("CGX_SCHED_CHUNKS", "0")
    assert cgx_config.sched_chunks() == 1
    monkeypatch.delenv("CGX_SCHED_CHUNKS", raising=False)
    assert cgx_config.sched_chunks() == cgx_config.DEFAULT_SCHED_CHUNKS


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    raise SystemExit(pytest.main([__file__, "-q"]))
