"""In-XLA single-program quantized allreduce + topology router (ISSUE 8).

Covers the staged-program entry (``parallel/xla_allreduce.py``), the
topology router (``parallel/topology.py``), the staged<->bridge wire
parity contract (stage-1 frames bit-identical on any data; the full
exchange bit-identical on decode-exact data — the residual random-data
stage-2 gap is the documented host-vs-XLA decode ulp, codec_host.py), the
staged-purity jaxpr guard (zero host callbacks, exactly one
quantize/epilogue kernel pair per shard), the size-aware fused-epilogue
selection, and the routing components of the layout/trace caches.
"""

import json
import os
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.config import CompressionConfig
from torch_cgx_tpu.ops import codec as codec_mod
from torch_cgx_tpu.ops import dispatch
from torch_cgx_tpu.parallel import mesh as mesh_mod
from torch_cgx_tpu.parallel import reducers, topology, xla_allreduce
from torch_cgx_tpu.utils.compat import shard_map

WS = 8


def _flat_mesh():
    return mesh_mod.flat_mesh()


def run_flat(per_rank: np.ndarray, fn, ws=WS):
    mesh = Mesh(np.asarray(jax.devices()[:ws]), ("dp",))
    body = shard_map(
        lambda x: fn(x[0])[None],
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False,
    )
    arr = jax.device_put(
        jnp.asarray(per_rank), NamedSharding(mesh, P("dp"))
    )
    return np.asarray(jax.jit(body)(arr))


# ---------------------------------------------------------------------------
# Topology classification + routing.
# ---------------------------------------------------------------------------


def test_classify_slice_ids_taxonomy():
    c = topology.classify_slice_ids
    assert c([0]).kind == topology.TOPO_SINGLE
    assert c([3, 3, 3, 3]).kind == topology.TOPO_INTRA
    assert c([0, 1, 2, 3]).kind == topology.TOPO_CROSS
    t = c([0, 0, 1, 1, 1])
    assert t.kind == topology.TOPO_MIXED
    assert t.n_slices == 2 and t.max_per_slice == 3 and t.ws == 5


def test_classify_hosts_matches_bridge_classifier():
    """The bridge keeps a dependency-light duplicate of the router's
    taxonomy (it must not import the parallel package into every rank
    process); the two classifiers must agree on every host map."""
    from torch_cgx_tpu.torch_backend import backend as be

    cases = [
        ["a"], ["a", "a"], ["a", "b"], ["a", "a", "b"],
        ["a", "b", "c"], ["x", "y", "x", "y"], ["h"] * 6,
        ["a", "b", "b", "c", "c", "c"],
    ]
    for hosts in cases:
        assert be._host_topology(hosts) == topology.classify_hosts(hosts).kind, hosts


def _stub_mesh(slice_ids, axis_names=("dp",)):
    devs = np.asarray(
        [SimpleNamespace(slice_index=s, process_index=0, id=i)
         for i, s in enumerate(slice_ids)],
        dtype=object,
    )
    return SimpleNamespace(
        devices=devs.reshape([len(slice_ids)]), axis_names=axis_names
    )


def test_classify_mesh_axes_stub_devices():
    m = _stub_mesh([0, 0, 0, 0])
    assert topology.classify_mesh_axes(m, ("dp",)).kind == topology.TOPO_INTRA
    m = _stub_mesh([0, 1, 2, 3])
    assert topology.classify_mesh_axes(m, ("dp",)).kind == topology.TOPO_CROSS
    m = _stub_mesh([0, 0, 1, 1])
    t = topology.classify_mesh_axes(m, ("dp",))
    assert t.kind == topology.TOPO_MIXED and t.n_slices == 2
    # 2-axis mesh: the intra axis groups are intra-slice
    devs = np.asarray(
        [[SimpleNamespace(slice_index=r, process_index=0, id=r * 2 + c)
          for c in range(2)] for r in range(2)],
        dtype=object,
    )
    m2 = SimpleNamespace(devices=devs, axis_names=("cross", "intra"))
    assert (
        topology.classify_mesh_axes(m2, ("intra",)).kind == topology.TOPO_INTRA
    )
    assert (
        topology.classify_mesh_axes(m2, ("cross",)).kind == topology.TOPO_CROSS
    )
    assert (
        topology.classify_mesh_axes(m2, ("cross", "intra")).kind
        == topology.TOPO_MIXED
    )


def test_route_knob_gates(monkeypatch):
    m = _stub_mesh([0, 0, 0, 0])
    # default (auto) on the CPU backend: inert — UNROUTED
    d = topology.route(m, ("dp",))
    assert d.route == topology.ROUTE_UNROUTED
    # off: never routed, even "on TPU"
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "off")
    monkeypatch.setattr(dispatch, "_on_tpu", lambda: True)
    assert topology.route(m, ("dp",)).route == topology.ROUTE_UNROUTED
    # auto + TPU backend: staged for intra-slice
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "auto")
    assert topology.route(m, ("dp",)).route == topology.ROUTE_STAGED
    # on: staged anywhere
    monkeypatch.setattr(dispatch, "_on_tpu", lambda: False)
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    assert topology.route(m, ("dp",)).route == topology.ROUTE_STAGED
    # cross-slice stays on the bridge path
    assert (
        topology.route(_stub_mesh([0, 1, 2, 3]), ("dp",)).route
        == topology.ROUTE_BRIDGE
    )


def test_route_mixed_two_level_requires_on(monkeypatch):
    m = _stub_mesh([0, 0, 1, 1])
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "auto")
    monkeypatch.setattr(dispatch, "_on_tpu", lambda: True)
    # auto promises bit-identity -> mixed stays unrouted
    assert topology.route(m, ("dp",)).route == topology.ROUTE_UNROUTED
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    # a 1-axis caller inside shard_map cannot build the (cross, intra)
    # grid -> UNROUTED so telemetry/cache keys report the path that runs;
    # only a re-meshing caller (eager staged_allreduce) engages two-level
    d = topology.route(m, ("dp",))
    assert d.route == topology.ROUTE_UNROUTED and "re-mesh" in d.reason
    assert (
        topology.route(m, ("dp",), allow_remesh=True).route
        == topology.ROUTE_TWO_LEVEL
    )
    # a 2-axis (cross, intra) call engages it in-program
    devs = np.asarray(
        [[SimpleNamespace(slice_index=r, process_index=0, id=r * 2 + c)
          for c in range(2)] for r in range(2)],
        dtype=object,
    )
    m2 = SimpleNamespace(devices=devs, axis_names=("cross", "intra"))
    assert (
        topology.route(m2, ("cross", "intra")).route
        == topology.ROUTE_TWO_LEVEL
    )


def test_two_level_config_override():
    base = cgx_config.TopologyConfig(
        intra_reduction="SRA", cross_reduction="RING",
        intra_broadcast=False, intra_compress=True, cross_compress=True,
    )
    tl = topology.two_level_config(base)
    assert not tl.intra_compress  # ICI rides uncompressed
    assert tl.cross_compress  # only the cross exchange is quantized
    assert tl.intra_broadcast  # the leader scheme (psum_scatter form)
    assert tl.cross_reduction == "RING"


# ---------------------------------------------------------------------------
# Staged program: results, cache, purity.
# ---------------------------------------------------------------------------


def test_staged_allreduce_matches_flat_reducer(monkeypatch):
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    cc = CompressionConfig(bits=4, bucket_size=512)
    n = 4096
    rng = np.random.default_rng(7)
    per = rng.standard_normal((WS, n)).astype(np.float32)
    ref = run_flat(
        per, lambda x: reducers.quantized_allreduce(x, "dp", WS, cc, "SRA")
    )
    out = np.asarray(
        xla_allreduce.staged_allreduce(per, mesh=_flat_mesh(), cc=cc)
    )
    np.testing.assert_array_equal(out, ref)
    # error symmetry: every row identical
    assert np.unique(out, axis=0).shape[0] == 1


def test_staged_allreduce_constant_exact(monkeypatch):
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    cc = CompressionConfig(bits=4, bucket_size=512)
    per = np.stack(
        [np.full((1000,), r + 1, np.float32) for r in range(WS)]
    )
    out = np.asarray(
        xla_allreduce.staged_allreduce(per, mesh=_flat_mesh(), cc=cc)
    )
    np.testing.assert_array_equal(
        out[0], np.full((1000,), WS * (WS + 1) // 2, np.float32)
    )


def test_staged_allreduce_program_cache(monkeypatch):
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    xla_allreduce.program_cache_clear()
    cc = CompressionConfig(bits=4, bucket_size=512)
    per = np.ones((WS, 2048), np.float32)
    xla_allreduce.staged_allreduce(per, mesh=_flat_mesh(), cc=cc)
    assert xla_allreduce.program_cache_stats() == {"hits": 0, "misses": 1}
    xla_allreduce.staged_allreduce(per, mesh=_flat_mesh(), cc=cc)
    assert xla_allreduce.program_cache_stats() == {"hits": 1, "misses": 1}
    # a different payload shape is a different compiled program
    xla_allreduce.staged_allreduce(
        np.ones((WS, 4096), np.float32), mesh=_flat_mesh(), cc=cc
    )
    assert xla_allreduce.program_cache_stats() == {"hits": 1, "misses": 2}


def test_program_cache_env_flip_compiles_fresh(monkeypatch):
    """A trace-time env knob flip between eager calls must MISS the
    program cache — the compiled program baked the old knob in, and
    serving it would silently run the pre-flip configuration."""
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    xla_allreduce.program_cache_clear()
    cc = CompressionConfig(bits=4, bucket_size=512)
    per = np.asarray(
        np.random.default_rng(3).standard_normal((WS, 2048)), np.float32
    )
    m = _flat_mesh()
    a = np.asarray(xla_allreduce.staged_allreduce(per, mesh=m, cc=cc))
    monkeypatch.setenv("CGX_DEBUG_DUMMY_COMPRESSION", "1")
    b = np.asarray(xla_allreduce.staged_allreduce(per, mesh=m, cc=cc))
    assert xla_allreduce.program_cache_stats()["misses"] == 2
    exact = per.sum(axis=0)
    np.testing.assert_allclose(b[0], exact, atol=1e-4)  # dummy: exact wire
    assert not np.allclose(a[0], exact, atol=1e-4)  # 4-bit wire differs
    # flip back: the original program's key hits again, bit-identical
    monkeypatch.delenv("CGX_DEBUG_DUMMY_COMPRESSION")
    c = np.asarray(xla_allreduce.staged_allreduce(per, mesh=m, cc=cc))
    stats = xla_allreduce.program_cache_stats()
    assert stats["misses"] == 2 and stats["hits"] >= 1
    np.testing.assert_array_equal(a, c)


def test_staged_wire_frames_program_cached(monkeypatch):
    """staged_wire_frames rides the same bounded program cache — a second
    identical call must not retrace/recompile."""
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    xla_allreduce.program_cache_clear()
    cc = CompressionConfig(bits=4, bucket_size=512)
    per = np.ones((WS, 2048), np.float32)
    m = _flat_mesh()
    first = xla_allreduce.staged_wire_frames(per, mesh=m, cc=cc)
    assert xla_allreduce.program_cache_stats()["misses"] == 1
    second = xla_allreduce.staged_wire_frames(per, mesh=m, cc=cc)
    stats = xla_allreduce.program_cache_stats()
    assert stats == {"hits": 1, "misses": 1}
    for x, y in zip(first, second):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_staged_two_level_mixed_executes(monkeypatch):
    """A MIXED group under CGX_XLA_ALLREDUCE=on runs the reference
    two-level program (uncompressed ICI intra + compressed cross) on the
    real virtual devices — slice ids faked by id parity."""
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    monkeypatch.setattr(
        topology, "device_slice_id", lambda d: getattr(d, "id", 0) % 2
    )
    xla_allreduce.program_cache_clear()
    cc = CompressionConfig(bits=4, bucket_size=512)
    per = np.stack(
        [np.full((2048,), r + 1, np.float32) for r in range(WS)]
    )
    m = _flat_mesh()
    assert (
        topology.route(m, ("dp",), allow_remesh=True).route
        == topology.ROUTE_TWO_LEVEL
    )
    out = np.asarray(xla_allreduce.staged_allreduce(per, mesh=m, cc=cc))
    np.testing.assert_array_equal(
        out, np.full((WS, 2048), WS * (WS + 1) // 2, np.float32)
    )


def _walk_jaxpr(jx, visit):
    for eqn in jx.eqns:
        visit(eqn)
        for v in eqn.params.values():
            for item in v if isinstance(v, (list, tuple)) else [v]:
                if isinstance(item, jax.extend.core.ClosedJaxpr):
                    _walk_jaxpr(item.jaxpr, visit)
                elif isinstance(item, jax.extend.core.Jaxpr):
                    _walk_jaxpr(item, visit)


def _staged_jaxpr(ws, n, cc):
    mesh = Mesh(np.asarray(jax.devices()[:ws]), ("dp",))
    body = shard_map(
        lambda x: xla_allreduce.staged_quantized_allreduce(
            x[0], "dp", ws, cc
        )[None],
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False,
    )
    return jax.make_jaxpr(body)(jnp.zeros((ws, n), jnp.float32)).jaxpr


def test_staged_program_zero_host_callbacks(monkeypatch):
    """The staged-purity acceptance guard: even with every runtime
    observability knob armed, the staged program stages NO host callback
    — the host hop is exactly what it exists to remove."""
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    monkeypatch.setenv("CGX_METRICS_RUNTIME", "1")
    monkeypatch.setenv("CGX_QERR_STATS", "1")
    cc = CompressionConfig(bits=4, bucket_size=512)
    prims = set()
    _walk_jaxpr(
        _staged_jaxpr(4, 4096, cc), lambda e: prims.add(e.primitive.name)
    )
    bad = [p for p in prims if "callback" in p]
    assert not bad, f"host callbacks staged into the pure program: {bad}"


def test_staged_program_one_kernel_pair_per_shard(monkeypatch):
    """Exactly ONE quantize kernel + ONE fused epilogue kernel per shard
    (plus the single allgather decode) — the PR 4 codec-invocation
    contract holds through the staged entry point."""
    from collections import Counter

    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    monkeypatch.setenv("CGX_CODEC_IMPL", "pallas")
    monkeypatch.setenv("CGX_SRA_EPILOGUE", "fused")
    ws, b = 4, 128
    n = ws * 2 * codec_mod.CHUNK_BUCKETS * b
    cc = CompressionConfig(bits=4, bucket_size=b)
    counts = Counter()

    def visit(eqn):
        if eqn.primitive.name == "pallas_call":
            info = str(eqn.params.get("name_and_src_info", ""))
            counts[info.split(" ")[0]] += 1

    _walk_jaxpr(_staged_jaxpr(ws, n, cc), visit)
    assert counts.get("_quantize_flat_kernel", 0) == 1, counts
    assert counts.get("_sra_epilogue_kernel", 0) == 1, counts
    assert counts.get("_dequantize_flat_kernel", 0) == 1, counts
    assert sum(counts.values()) == 3, counts


# ---------------------------------------------------------------------------
# Staged <-> bridge wire parity (the compressed-exchange contract).
# ---------------------------------------------------------------------------


def _bridge_sra(per_rank: np.ndarray, cc: CompressionConfig):
    """The host bridge's SRA data path on ``per_rank`` inputs, executed
    in-process through the backend's OWN frame/fold functions (the same
    code a live ProcessGroupCGX rank runs, minus the shm/store hop).
    Returns (outputs (ws, n), stage1 frames {(src, dst): bytes},
    stage2 frames [bytes per rank])."""
    from torch_cgx_tpu.torch_backend import backend as be

    ws, n = per_rank.shape
    layers = [(0, n, cc)]
    sizes, offs = be._chunk_split(n, ws, layers)
    segs = [
        be._segments_in(layers, offs[r], offs[r] + sizes[r])
        for r in range(ws)
    ]
    fused = [per_rank[r].copy() for r in range(ws)]
    stage1 = {
        (s, d): be._compress_frames(fused[s], segs[d], False, None)
        for s in range(ws) for d in range(ws) if s != d
    }
    for r in range(ws):
        frames = {
            j: np.frombuffer(stage1[(j, r)], np.uint8)
            for j in range(ws) if j != r
        }
        be._sra_fold_chunk(
            fused[r], offs[r], offs[r] + sizes[r], segs[r], frames, r, ws,
            False,
        )
    stage2 = [
        be._requantize_frames(fused[r], segs[r], False, None)
        for r in range(ws)
    ]
    for r in range(ws):
        for j in range(ws):
            if j != r:
                be._decompress_frames(
                    np.frombuffer(stage2[j], np.uint8), segs[j], fused[r],
                    False, add=False,
                )
    return np.stack(fused), stage1, stage2


def _staged_frames(per_rank, cc, ws):
    mesh = Mesh(np.asarray(jax.devices()[:ws]), ("dp",))
    out, p1, m1, p2, m2 = xla_allreduce.staged_wire_frames(
        per_rank, mesh=mesh, cc=cc
    )
    return tuple(
        np.ascontiguousarray(np.asarray(a)) for a in (out, p1, m1, p2, m2)
    )


def _frame_bytes(meta, packed):
    return np.concatenate([
        np.ascontiguousarray(meta).reshape(-1).view(np.uint8),
        np.ascontiguousarray(packed).reshape(-1).view(np.uint8),
    ])


def test_staged_vs_bridge_full_wire_parity_exact_grid():
    """On decode-exact data (integer grid: unit and min exact, decode
    free of the host-vs-XLA fma ulp) EVERY wire byte of the compressed
    exchange — all ws*(ws-1) stage-1 frames and all ws stage-2 frames —
    is bit-identical between the staged program and the bridge SRA path,
    and the outputs agree bit-exactly end to end."""
    ws, bucket = 4, 512
    n = ws * 2048
    cc = CompressionConfig(bits=4, bucket_size=bucket)
    per = np.stack(
        [np.float32((np.arange(n) * (r + 3)) % 16) for r in range(ws)]
    )
    bridge_out, stage1, stage2 = _bridge_sra(per, cc)
    out, p1, m1, p2, m2 = _staged_frames(per, cc, ws)
    for s in range(ws):
        for d in range(ws):
            if s == d:
                continue
            np.testing.assert_array_equal(
                np.frombuffer(stage1[(s, d)], np.uint8),
                _frame_bytes(m1[s, d], p1[s, d]),
                err_msg=f"stage-1 frame {s}->{d}",
            )
    for r in range(ws):
        np.testing.assert_array_equal(
            np.frombuffer(stage2[r], np.uint8),
            _frame_bytes(m2[r], p2[r]),
            err_msg=f"stage-2 frame of rank {r}",
        )
    np.testing.assert_array_equal(out, bridge_out)


def test_staged_vs_bridge_stage1_parity_random():
    """On arbitrary data the stage-1 exchange (quantize of RAW chunks —
    no accumulate in the way) is bit-identical; end-to-end results agree
    within the documented host-vs-XLA decode ulp (codec_host.py: the
    host codec rounds unit*level before adding, XLA may fuse the fma —
    which can shift a requantized stage-2 byte by one level)."""
    ws, bucket = 4, 512
    n = ws * 2048
    cc = CompressionConfig(bits=4, bucket_size=bucket)
    per = np.random.default_rng(3).standard_normal((ws, n)).astype(
        np.float32
    )
    bridge_out, stage1, _ = _bridge_sra(per, cc)
    out, p1, m1, _, _ = _staged_frames(per, cc, ws)
    for s in range(ws):
        for d in range(ws):
            if s == d:
                continue
            np.testing.assert_array_equal(
                np.frombuffer(stage1[(s, d)], np.uint8),
                _frame_bytes(m1[s, d], p1[s, d]),
                err_msg=f"stage-1 frame {s}->{d}",
            )
    np.testing.assert_allclose(out, bridge_out, atol=2e-5, rtol=1e-5)


def test_bridge_fold_order_pinned():
    """The bridge's stage-1 accumulate association is the dispatcher's
    ``ordered_rowsum`` fold (v0 + v1 + ... ascending, raw own chunk at
    its rank position) — NOT the old own-chunk-first in-place add, which
    differs by a last ulp for me >= 2. Uses association-sensitive values
    through the dummy (exact-decode) codec so ONLY the fold order is
    measured."""
    from torch_cgx_tpu.torch_backend import backend as be

    n, ws, me = 32, 4, 2
    big = np.float32(2.0 ** 24)
    rows = np.stack([
        np.full((n,), big, np.float32),
        np.full((n,), 1.0, np.float32),
        np.full((n,), -big, np.float32),  # the raw own chunk
        np.full((n,), 1.0, np.float32),
    ])
    segs = [be._Segment(0, n, 4, 512)]
    frames = {
        j: np.ascontiguousarray(rows[j]).view(np.uint8)
        for j in range(ws) if j != me
    }
    fused = rows[me].copy()
    be._sra_fold_chunk(fused, 0, n, segs, frames, me, ws, dummy=True)
    # ascending fold: ((big + 1) + -big) + 1 = 1.0 (big+1 rounds to big)
    expect = np.asarray(
        dispatch.ordered_rowsum(jnp.asarray(rows))
    )
    np.testing.assert_array_equal(fused, expect)
    np.testing.assert_array_equal(fused, np.full((n,), 1.0, np.float32))
    # the OLD own-first association would have produced 2.0 — the fold
    # orders are genuinely distinguishable on this data
    own_first = rows[me].copy()
    for j in range(ws):
        if j != me:
            own_first = own_first + rows[j]
    np.testing.assert_array_equal(own_first, np.full((n,), 2.0, np.float32))


# ---------------------------------------------------------------------------
# Size-aware fused-epilogue selection (the BENCH_LOG small-chunk fix).
# ---------------------------------------------------------------------------


def _reduce_capable_q(rows: int, chunks: int = 2, bucket: int = 128):
    n = chunks * codec_mod.CHUNK_BUCKETS * bucket
    cc = CompressionConfig(bits=4, bucket_size=bucket)
    xs = jnp.asarray(
        np.random.default_rng(0).standard_normal((rows, n)), jnp.float32
    )
    return dispatch.quantize_batch(xs, cc, None)


def test_fused_epilogue_size_threshold(monkeypatch):
    from torch_cgx_tpu.ops import codec_pallas

    q = _reduce_capable_q(rows=4)  # 4 * 8192 = 32768 decoded elements
    assert codec_pallas.supports_reduce(q)
    monkeypatch.setattr(dispatch, "_on_tpu", lambda: True)
    # auto + payload below the default 2^20 floor -> staged
    monkeypatch.setenv("CGX_SRA_EPILOGUE", "auto")
    assert not dispatch.fused_epilogue_would_run(q)
    # floor lowered below the payload -> fused
    monkeypatch.setenv("CGX_SRA_EPILOGUE_MIN_ELEMS", "1024")
    assert dispatch.fused_epilogue_would_run(q)
    # floor raised above it -> staged again (the crossover knob)
    monkeypatch.setenv("CGX_SRA_EPILOGUE_MIN_ELEMS", str(1 << 22))
    assert not dispatch.fused_epilogue_would_run(q)
    # "fused" forces the kernel at ANY size (test/bench knob)
    monkeypatch.setenv("CGX_SRA_EPILOGUE", "fused")
    assert dispatch.fused_epilogue_would_run(q)
    # "staged" forces it off at any size
    monkeypatch.setenv("CGX_SRA_EPILOGUE", "staged")
    monkeypatch.setenv("CGX_SRA_EPILOGUE_MIN_ELEMS", "1")
    assert not dispatch.fused_epilogue_would_run(q)


def test_fused_epilogue_threshold_default_covers_bench_regression(
    monkeypatch,
):
    """The exact BENCH_LOG regression shape (1 MB payload over 8 ranks =
    2^18 decoded elements, fused 6.5 ms vs staged 1.0 ms) now selects
    STAGED under auto; the 512 MB winner shape still selects fused."""
    small = _reduce_capable_q(rows=8, chunks=8)  # 8 * 32768 = 2^18
    big = _reduce_capable_q(rows=8, chunks=64)  # 8 * 2^18 = 2^21
    monkeypatch.setattr(dispatch, "_on_tpu", lambda: True)
    monkeypatch.setenv("CGX_SRA_EPILOGUE", "auto")
    assert small.batch_rows * small.numel == 1 << 18
    assert not dispatch.fused_epilogue_would_run(small)
    assert dispatch.fused_epilogue_would_run(big)


# ---------------------------------------------------------------------------
# Cache keys + grad_sync integration + observability.
# ---------------------------------------------------------------------------


def test_layout_cache_keys_on_route(monkeypatch):
    from torch_cgx_tpu.parallel import allreduce as ar

    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    mesh = _flat_mesh()
    tree = {"w": np.ones((WS, 64, 8), np.float32)}

    def _sync(t):
        reduced = ar.allreduce_tree(
            jax.tree.map(lambda l: l[0], t), mesh=mesh, axes=("dp",)
        )
        return jax.tree.map(lambda l: l[None], reduced)

    def trace():
        body = shard_map(
            _sync, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,
        )
        jax.make_jaxpr(body)(tree)

    ar.layout_cache_clear()
    trace()
    trace()
    stats = ar.layout_cache_stats()
    assert stats == {"hits": 1, "misses": 1}
    # flipping the routing knob must derive a fresh plan, not hit stale
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    trace()
    stats = ar.layout_cache_stats()
    assert stats["misses"] == 2, stats


def test_grad_sync_bit_identical_with_knob_on(monkeypatch):
    """CGX_XLA_ALLREDUCE=on re-routes intra-slice slices through the
    staged wrappers — same composition, same wire bytes: the synced
    gradients are bit-identical to the knob-unset run (the acceptance
    'results matching the bridge path' at the gradient level)."""
    from torch_cgx_tpu.parallel import gradient_sync

    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    mesh = _flat_mesh()
    rng = np.random.default_rng(11)
    grads = {
        "w": rng.standard_normal((WS, 32, 16)).astype(np.float32),
        "b": rng.standard_normal((WS, 40)).astype(np.float32),
    }

    def run():
        body = shard_map(
            lambda t: jax.tree.map(
                lambda l: l[None],
                gradient_sync(
                    jax.tree.map(lambda l: l[0], t), mesh=mesh, axes=("dp",)
                ),
            ),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False,
        )
        arr = jax.device_put(
            jax.tree.map(jnp.asarray, grads),
            NamedSharding(mesh, P("dp")),
        )
        return jax.tree.map(np.asarray, jax.jit(body)(arr))

    base = run()
    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    routed = run()
    jax.tree.map(np.testing.assert_array_equal, base, routed)


def test_staged_observability(monkeypatch, tmp_path):
    """Staged calls emit the CAT_COLLECTIVE trace instant + cgx.xla.*
    counters (the bridge's timeline spans vanish for staged traffic —
    this is what keeps cgx_trace/cgx_top attribution truthful)."""
    from torch_cgx_tpu.observability import timeline
    from torch_cgx_tpu.utils.logging import metrics

    monkeypatch.setenv("CGX_XLA_ALLREDUCE", "on")
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    timeline.reset()
    xla_allreduce.program_cache_clear()
    before = metrics.get("cgx.xla.staged_calls")
    cc = CompressionConfig(bits=4, bucket_size=512)
    per = np.ones((WS, 2048), np.float32)
    xla_allreduce.staged_allreduce(per, mesh=_flat_mesh(), cc=cc)
    assert metrics.get("cgx.xla.staged_calls") == before + 1
    assert metrics.get("cgx.xla.staged_programs") >= 1
    timeline.flush()
    spans = [
        json.loads(line)
        for p in tmp_path.glob("spans-rank*.jsonl")
        for line in p.read_text().splitlines()
    ]
    inst = [
        e for e in spans
        if e.get("name") == "xla_allreduce" and e.get("kind") == "instant"
    ]
    assert inst, "no CAT_COLLECTIVE instant for the staged program"
    assert inst[0]["cat"] == timeline.CAT_COLLECTIVE
    assert inst[0]["route"] == topology.ROUTE_STAGED
    timeline.reset()
