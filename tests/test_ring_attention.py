"""Sequence-parallel attention vs the dense oracle.

Strategy per SURVEY.md §4: the reference has no attention code, so the
oracle is this framework's own dense_attention on the gathered sequence —
ring/Ulysses must reproduce it to f32 tolerance for causal and full
attention, any batch/head shape, on the virtual 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torch_cgx_tpu.models.attention import dense_attention
from torch_cgx_tpu.parallel.ring_attention import (
    make_sp_attention,
    ring_attention,
    ulysses_attention,
)
from torch_cgx_tpu.utils.compat import shard_map


def _mesh(ws):
    return Mesh(np.asarray(jax.devices()[:ws]), ("sp",))


def _qkv(b=2, h=4, s=64, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def _run_sharded(fn, mesh, q, k, v, mask=None):
    """fn(q, k, v[, mask]) under shard_map, qkv sequence-sharded over 'sp'
    (and the optional (B, S) mask sharded on its sequence dim)."""
    spec = P(None, None, "sp", None)
    in_specs = (spec, spec, spec)
    args = [jax.device_put(x, NamedSharding(mesh, spec)) for x in (q, k, v)]
    if mask is not None:
        mspec = P(None, "sp")
        in_specs = in_specs + (mspec,)
        args.append(jax.device_put(mask, NamedSharding(mesh, mspec)))
    sharded = jax.jit(
        shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=spec)
    )
    return np.asarray(sharded(*args))


@pytest.mark.parametrize("ws", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(ws, causal):
    mesh = _mesh(ws)
    q, k, v = _qkv()
    expected = np.asarray(dense_attention(q, k, v, causal=causal))

    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name="sp", causal=causal)

    out = _run_sharded(fn, mesh, q, k, v)
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("ws", [2, 4])
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(ws, causal):
    mesh = _mesh(ws)
    q, k, v = _qkv(h=8)
    expected = np.asarray(dense_attention(q, k, v, causal=causal))

    def fn(q, k, v):
        return ulysses_attention(q, k, v, axis_name="sp", causal=causal)

    out = _run_sharded(fn, mesh, q, k, v)
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = _mesh(4)
    q, k, v = _qkv(h=6)

    def fn(q, k, v):
        return ulysses_attention(q, k, v, axis_name="sp", causal=True)

    with pytest.raises(ValueError, match="not divisible"):
        _run_sharded(fn, mesh, q, k, v)


def _padding_mask(b=2, s=64, seed=3):
    """Random trailing-padding mask: batch i keeps a random prefix (always
    at least the first token, so no query row is fully masked under
    causal)."""
    rng = np.random.default_rng(seed)
    keep = rng.integers(1, s + 1, size=(b,))
    return jnp.asarray(np.arange(s)[None, :] < keep[:, None])


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [True, False])
def test_sp_attention_padding_mask_matches_dense(impl, causal):
    """Key-padding masks under sequence parallelism: the ring rotates the
    mask slice with its K/V block; Ulysses all_gathers the slices — both
    must reproduce the dense oracle on every non-padded query row."""
    ws = 4
    mesh = _mesh(ws)
    q, k, v = _qkv()
    mask = _padding_mask()
    expected = np.asarray(dense_attention(q, k, v, causal=causal, mask=mask))
    attn = make_sp_attention("sp", impl=impl)

    def fn(q, k, v, m):
        return attn(q, k, v, causal=causal, mask=m)

    out = _run_sharded(fn, mesh, q, k, v, mask=mask)
    valid = np.asarray(mask)  # (B, S): compare non-padded query rows only
    for bi in range(out.shape[0]):
        np.testing.assert_allclose(
            out[bi][:, valid[bi]], expected[bi][:, valid[bi]],
            rtol=2e-5, atol=2e-5,
        )


def test_sp_attention_rejects_nonlocal_mask():
    """Masks must be the (B, S_local) slice, not the global (B, S) mask —
    a global mask inside shard_map is a shape bug, caught loudly."""
    attn = make_sp_attention("sp", impl="ring")
    q, k, v = _qkv(s=8)
    mesh = _mesh(2)

    def fn(q, k, v):
        # closed-over GLOBAL mask: (2, 8) against s_local = 4
        return attn(q, k, v, causal=False, mask=jnp.ones((2, 8), bool))

    with pytest.raises(NotImplementedError, match="key-padding"):
        _run_sharded(fn, mesh, q, k, v)


def test_ring_ws1_falls_back_to_dense():
    mesh = _mesh(1)
    q, k, v = _qkv(s=32)
    expected = np.asarray(dense_attention(q, k, v, causal=True))

    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name="sp", causal=True)

    out = _run_sharded(fn, mesh, q, k, v)
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_gpt2_with_ring_attention_matches_dense():
    """End-to-end: GPT-2 forward with sequence-sharded activations + ring
    attention equals the dense single-device forward."""
    from torch_cgx_tpu.models import GPT2, GPT2Config

    mesh = _mesh(4)
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)

    dense_model = GPT2(cfg)
    params = dense_model.init(jax.random.PRNGKey(0), tokens)
    expected = np.asarray(dense_model.apply(params, tokens, train=False))

    sp_model = GPT2(cfg, attn_fn=make_sp_attention("sp", impl="ring"))

    def fwd(params, tokens, positions):
        return sp_model.apply(params, tokens, positions=positions, train=False)

    tok_spec = P(None, "sp")
    positions = jnp.broadcast_to(jnp.arange(64)[None, :], tokens.shape)
    sharded = jax.jit(
        shard_map(
            fwd,
            mesh=mesh,
            in_specs=(P(), tok_spec, tok_spec),
            out_specs=tok_spec,
            check_vma=False,
        )
    )
    out = np.asarray(
        sharded(
            jax.device_put(params, NamedSharding(mesh, P())),
            jax.device_put(tokens, NamedSharding(mesh, tok_spec)),
            jax.device_put(positions, NamedSharding(mesh, tok_spec)),
        )
    )
    np.testing.assert_allclose(out, expected, rtol=5e-4, atol=5e-4)


def test_gpt2_with_sp_padding_mask_matches_dense():
    """GPT-2 forward with a key-padding mask under ring sequence
    parallelism equals the dense masked forward on non-padded positions."""
    from torch_cgx_tpu.models import GPT2, GPT2Config

    mesh = _mesh(4)
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    s = 64
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, s), 0, cfg.vocab_size)
    mask = _padding_mask(b=2, s=s, seed=9)

    dense_model = GPT2(cfg)
    params = dense_model.init(jax.random.PRNGKey(0), tokens)
    expected = np.asarray(
        dense_model.apply(params, tokens, attn_mask=mask, train=False)
    )

    sp_model = GPT2(cfg, attn_fn=make_sp_attention("sp", impl="ring"))

    def fwd(params, tokens, positions, m):
        return sp_model.apply(
            params, tokens, positions=positions, attn_mask=m, train=False
        )

    tok_spec = P(None, "sp")
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], tokens.shape)
    sharded = jax.jit(
        shard_map(
            fwd,
            mesh=mesh,
            in_specs=(P(), tok_spec, tok_spec, tok_spec),
            out_specs=tok_spec,
            check_vma=False,
        )
    )
    out = np.asarray(
        sharded(
            jax.device_put(params, NamedSharding(mesh, P())),
            jax.device_put(tokens, NamedSharding(mesh, tok_spec)),
            jax.device_put(positions, NamedSharding(mesh, tok_spec)),
            jax.device_put(mask, NamedSharding(mesh, tok_spec)),
        )
    )
    valid = np.asarray(mask)
    for bi in range(2):
        np.testing.assert_allclose(
            out[bi][valid[bi]], expected[bi][valid[bi]], rtol=5e-4, atol=5e-4
        )


def test_sp_lm_loss_matches_dense():
    """sp_lm_loss on a sequence-sharded mesh == lm_loss on the full
    sequence (boundary targets fetched from the right neighbor)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from torch_cgx_tpu.models.gpt2 import lm_loss, sp_lm_loss

    sp, b, s, v = 4, 2, 64, 50
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, v, size=(b, s)), jnp.int32)
    logits = jnp.asarray(rng.normal(size=(b, s, v)), jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))

    got = jax.jit(
        shard_map(
            lambda lg, tk: sp_lm_loss(lg, tk, "sp"),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp")),
            out_specs=P(),
            check_vma=False,
        )
    )(logits, tokens)
    want = lm_loss(logits, tokens)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-6)


def test_sp_train_step_matches_dense(monkeypatch):
    """One make_train_step with sp_axis (ring attention + sp_lm_loss,
    bits=32 so the gradient sync is exact) must produce the same params as
    a dense 1-device step on the same batch."""
    import optax

    from jax.sharding import Mesh
    from torch_cgx_tpu.models import GPT2, GPT2Config
    from torch_cgx_tpu.models.gpt2 import lm_loss, sp_lm_loss
    from torch_cgx_tpu.parallel import make_train_step, replicate, shard_batch
    from torch_cgx_tpu.parallel.ring_attention import make_sp_attention

    sp, b, s = 4, 4, 64
    cfg = GPT2Config.tiny(max_seq=s, dtype=jnp.float32)
    model_sp = GPT2(cfg, attn_fn=make_sp_attention("sp", impl="ring"))
    model_d = GPT2(cfg)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(b, s)), jnp.int32)
    params0 = model_d.init(jax.random.PRNGKey(0), tokens[:1])["params"]
    opt = optax.sgd(0.1)

    # SP run: dp=1 x sp=4
    mesh = Mesh(np.asarray(jax.devices()[:sp]).reshape(1, sp), ("dp", "sp"))

    def loss_sp(p, batch):
        s_local = batch.shape[1]
        pos = jax.lax.axis_index("sp") * s_local + jnp.arange(s_local)
        return sp_lm_loss(
            model_sp.apply({"params": p}, batch, positions=pos), batch, "sp"
        )

    step = make_train_step(loss_sp, opt, mesh, axes=("dp",), sp_axis="sp",
                           donate=False)
    p_sp, _, loss_sp_val = step(
        replicate(params0, mesh),
        replicate(opt.init(params0), mesh),
        shard_batch(tokens, mesh, ("dp",), sp_axis="sp"),
        jnp.int32(0),
    )

    # Dense single-device reference
    def loss_d(p):
        return lm_loss(model_d.apply({"params": p}, tokens), tokens)

    ld, g = jax.value_and_grad(loss_d)(params0)
    upd, _ = opt.update(g, opt.init(params0), params0)
    p_d = optax.apply_updates(params0, upd)

    np.testing.assert_allclose(float(loss_sp_val), float(ld), rtol=1e-5)
    for a, bb in zip(jax.tree.leaves(p_sp), jax.tree.leaves(p_d)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=5e-5, atol=1e-5
        )


def test_ulysses_compressed_hops_close_to_plain():
    """hop_cc on the Ulysses reshard: output tracks the uncompressed path
    within the quantization envelope and gradients flow (STE)."""
    from torch_cgx_tpu.config import CompressionConfig
    from torch_cgx_tpu.parallel.ring_attention import ulysses_attention

    ws = 4
    mesh = Mesh(np.asarray(jax.devices()[:ws]), ("sp",))
    b, h, s, d = 2, 4, 128, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    cc = CompressionConfig(bits=8, bucket_size=64)
    spec = P(None, None, "sp")

    def run(hop_cc):
        def fn(qq, kk, vv):
            return ulysses_attention(qq, kk, vv, axis_name="sp",
                                     hop_cc=hop_cc)

        return np.asarray(
            jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec,) * 3,
                                  out_specs=spec, check_vma=False))(q, k, v)
        )

    plain = run(None)
    comp = run(cc)
    assert comp.shape == plain.shape
    assert not np.array_equal(comp, plain)
    assert np.abs(comp - plain).max() < 0.05, np.abs(comp - plain).max()

    def loss(qq):
        def fn(x, kk, vv):
            return ulysses_attention(x, kk, vv, axis_name="sp", hop_cc=cc)

        out = shard_map(fn, mesh=mesh, in_specs=(spec,) * 3,
                            out_specs=spec, check_vma=False)(qq, k, v)
        return jnp.sum(out**2)

    g = np.asarray(jax.jit(jax.grad(loss))(q))
    assert np.isfinite(g).all() and np.abs(g).max() > 0
