"""Recovery supervisor suite (ISSUE 5 tentpole).

Unit layers run single-process: the rendezvous protocol over an
in-memory store (threads as ranks), the snapshot/rollback substrate, the
generation-tagged shm headers with drain-on-epoch-bump, and the retry
rung healing a ``flap`` fault. The chaos soak spawns three real torch
bridge ranks, SIGKILLs one mid-training, and asserts the acceptance
criteria: training completes on the survivor set, the generation bumps
exactly once, the evicted rank is named in the flight-recorder dump, and
the post-rollback replayed steps are bit-identical to a fault-free
survivor-only run.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import tempfile
import threading
import time
import traceback

import numpy as np
import pytest

from torch_cgx_tpu import checkpoint as ckpt
from torch_cgx_tpu import config as cfg
from torch_cgx_tpu.robustness import (
    BridgeTimeoutError,
    EvictedError,
    RecoveryFailedError,
    StaleGenerationError,
    faults,
    rendezvous as rdz,
)
from torch_cgx_tpu.robustness.supervisor import (
    RecoveryPolicy,
    RecoverySupervisor,
    invalidate_trace_caches,
)
from torch_cgx_tpu.utils.logging import metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _fresh():
    faults.reset_injectors()
    metrics.reset()
    cfg.clear_registry()
    yield
    faults.reset_injectors()
    cfg.clear_registry()


class FakeStore:
    """Minimal c10d-Store look-alike (same shape as test_faults')."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def set(self, k, v):
        with self._lock:
            self._d[k] = bytes(v) if not isinstance(v, bytes) else v

    def get(self, k):
        with self._lock:
            if k not in self._d:
                raise KeyError(k)
            return self._d[k]

    def add(self, k, v):
        with self._lock:
            cur = int(self._d.get(k, b"0")) + int(v)
            self._d[k] = str(cur).encode()
            return cur

    def delete_key(self, k):
        with self._lock:
            self._d.pop(k, None)


# ---------------------------------------------------------------------------
# Policy plumbing.
# ---------------------------------------------------------------------------


def test_policy_from_env(monkeypatch):
    monkeypatch.setenv("CGX_RECOVERY_RETRIES", "3")
    monkeypatch.setenv("CGX_RECOVERY_BACKOFF_MS", "250")
    monkeypatch.setenv("CGX_RECOVERY_CORRUPT_THRESHOLD", "5")
    monkeypatch.setenv("CGX_SNAPSHOT_EVERY", "4")
    p = RecoveryPolicy.from_env()
    assert (p.retries, p.backoff_ms, p.corrupt_threshold, p.snapshot_every) \
        == (3, 250.0, 5, 4)


def test_policy_defaults_are_inert(monkeypatch):
    for k in ("CGX_RECOVERY_RETRIES", "CGX_RECOVERY_BACKOFF_MS",
              "CGX_SNAPSHOT_EVERY"):
        monkeypatch.delenv(k, raising=False)
    p = RecoveryPolicy.from_env()
    assert p.retries == 0 and p.snapshot_every == 0


# ---------------------------------------------------------------------------
# Generation rendezvous over the store.
# ---------------------------------------------------------------------------


def _negotiate_concurrently(store, calls):
    """Run several negotiate() calls as threads; returns {rank: outcome}
    where outcome is a Decision or a raised exception."""
    out = {}

    def run(kw):
        try:
            out[kw["me"]] = rdz.negotiate(store, **kw)
        except Exception as e:  # noqa: BLE001 — the outcome IS the assert
            out[kw["me"]] = e

    threads = [
        threading.Thread(target=run, args=(kw,), daemon=True) for kw in calls
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return out


def test_rendezvous_evicts_the_suspect():
    store = FakeStore()
    base = dict(generation=1, participants=[0, 1, 2], timeout_s=10.0,
                poll_s=0.01)
    out = _negotiate_concurrently(store, [
        dict(base, me=0, suspects=[1]),
        dict(base, me=2, suspects=[1]),
    ])
    for r in (0, 2):
        d = out[r]
        assert isinstance(d, rdz.Decision), d
        assert d.survivors == (0, 2)
        assert d.evicted == (1,)
        assert d.generation == 1
        assert not d.degrade


def test_rendezvous_merges_partial_suspect_views():
    # Only ONE survivor's heartbeat window saw the corpse; the other rank
    # timed out anonymously. The union of votes must still evict.
    store = FakeStore()
    base = dict(generation=1, participants=[0, 1, 2], timeout_s=10.0,
                poll_s=0.01)
    out = _negotiate_concurrently(store, [
        dict(base, me=0, suspects=[1]),
        dict(base, me=2, suspects=[]),
    ])
    assert out[0].survivors == (0, 2)
    assert out[2].survivors == (0, 2)


def test_rendezvous_degrade_vote_propagates():
    store = FakeStore()
    base = dict(generation=2, participants=[0, 1], timeout_s=10.0,
                poll_s=0.01)
    out = _negotiate_concurrently(store, [
        dict(base, me=0, degrade=True),
        dict(base, me=1),
    ])
    assert out[0].degrade and out[1].degrade
    assert out[0].survivors == (0, 1) and out[0].evicted == ()


def test_rendezvous_late_arrival_adopts_decision_and_gets_evicted():
    store = FakeStore()
    base = dict(generation=1, participants=[0, 1, 2], timeout_s=10.0,
                poll_s=0.01)
    out = _negotiate_concurrently(store, [
        dict(base, me=0, suspects=[1]),
        dict(base, me=2, suspects=[1]),
    ])
    assert isinstance(out[0], rdz.Decision)
    # The falsely-suspected rank shows up late and alive: it must adopt
    # the published decision and learn of its own eviction.
    with pytest.raises(EvictedError):
        rdz.negotiate(
            store, generation=1, me=1, participants=[0, 1, 2],
            timeout_s=5.0, poll_s=0.01,
        )


def test_rendezvous_agrees_on_min_snapshot_step():
    # Survivors can drift whole steps apart around a fault (a send-only
    # rank never blocks on the dead peer): the decision must pin the
    # replay step to the MINIMUM of the survivor votes so everyone
    # replays the same steps.
    store = FakeStore()
    base = dict(generation=1, participants=[0, 1, 2], timeout_s=10.0,
                poll_s=0.01)
    out = _negotiate_concurrently(store, [
        dict(base, me=0, suspects=[1], snapshot_step=6),
        dict(base, me=2, suspects=[1], snapshot_step=4),
    ])
    assert out[0].replay_step == 4
    assert out[2].replay_step == 4
    # No survivor holds a snapshot -> no agreed replay point.
    store2 = FakeStore()
    out2 = _negotiate_concurrently(store2, [
        dict(base, me=0, suspects=[1]),
        dict(base, me=2, suspects=[1]),
    ])
    assert out2[0].replay_step is None


def test_rendezvous_times_out_without_quorum():
    store = FakeStore()
    with pytest.raises(RecoveryFailedError, match="did not converge"):
        rdz.negotiate(
            store, generation=1, me=0, participants=[0, 1],
            timeout_s=0.3, poll_s=0.01,
        )
    assert metrics.get("cgx.recovery.rendezvous_failed") == 1


# ---------------------------------------------------------------------------
# Snapshot / rollback substrate.
# ---------------------------------------------------------------------------


def test_memory_snapshot_roundtrip_with_registry():
    cfg.register_layer(0, 0, 128, 4, 64)
    tree = {"w": np.arange(8.0, dtype=np.float32), "step": np.int64(5)}
    snap = ckpt.snapshot_in_memory(tree, 6)
    tree["w"][:] = -1.0  # post-snapshot mutation must not leak in
    cfg.clear_registry()
    assert cfg.registered_layer_sizes(0) is None
    out = ckpt.restore_in_memory(snap)
    np.testing.assert_array_equal(out["w"], np.arange(8.0, dtype=np.float32))
    assert cfg.registered_layer_sizes(0) == [128]
    # the restored tree is a fresh copy: mutate and restore again
    out["w"][:] = 9.0
    out2 = ckpt.restore_in_memory(snap)
    np.testing.assert_array_equal(out2["w"], np.arange(8.0, dtype=np.float32))


class _StubGroup:
    generation = 0
    global_rank = 0
    global_ranks = [0]


def test_supervisor_snapshot_rollback():
    sup = RecoverySupervisor(FakeStore(), _StubGroup(),
                             policy=RecoveryPolicy(snapshot_every=2))
    state = np.ones(4, np.float32)
    sup.take_snapshot(3, state)
    state *= 7.0
    step, back = sup.rollback()
    assert step == 3
    np.testing.assert_array_equal(back, np.ones(4, np.float32))
    assert metrics.get("cgx.recovery.snapshots") == 1
    assert metrics.get("cgx.recovery.rollbacks") == 1


def test_supervisor_snapshot_ring_and_agreed_step_rollback():
    # The ring retains snapshot_keep points so the rendezvous can pin
    # the replay step BEHIND this rank's newest snapshot; an agreed step
    # outside the ring returns None (run_steps then dies loudly).
    sup = RecoverySupervisor(
        FakeStore(), _StubGroup(),
        policy=RecoveryPolicy(snapshot_every=1, snapshot_keep=3),
    )
    for s in range(6):
        sup.take_snapshot(s, np.full(2, float(s), np.float32))
    assert sup.last_snapshot.step == 5
    step, back = sup.rollback(4)  # behind newest, inside the ring
    assert step == 4
    np.testing.assert_array_equal(back, np.full(2, 4.0, np.float32))
    assert sup.rollback(1) is None  # aged out (keep=3 -> steps 3,4,5)
    step, _ = sup.rollback()  # no agreed step: newest
    assert step == 5


def test_invalidate_trace_caches_bumps_registry_version():
    v0 = cfg.registry_version()
    invalidate_trace_caches()
    assert cfg.registry_version() == v0 + 1


def test_invalidate_trace_caches_resets_qerr_sampling():
    # ISSUE 6 satellite: the flightrec qerr subsample cadence
    # (allreduce._QERR_SEEN) must restart with the registry-version bump —
    # post-recovery programs are a NEW qerr stream, and a stale per-layer
    # counter would skip its first observations on the dead generation's
    # phase.
    from torch_cgx_tpu.parallel import allreduce as ar

    ar._QERR_SEEN.clear()
    ar._QERR_SEEN.update({"layer0/w": 17, "layer1/b": 3})
    invalidate_trace_caches()
    assert ar._QERR_SEEN == {}


# ---------------------------------------------------------------------------
# Generation-tagged shm headers + drain-on-epoch-bump.
# ---------------------------------------------------------------------------


def _channel_pair(store, tmp_path):
    from torch_cgx_tpu.torch_backend.shm import ShmChannel

    writer = ShmChannel(store, rank=0, directory=str(tmp_path))
    reader = ShmChannel(store, rank=1, directory=str(tmp_path))
    return writer, reader


def test_epoch0_header_format_unchanged(tmp_path):
    # Bit-identity guard: with recovery never engaged the wire header
    # keeps the legacy 5-field format, byte for byte.
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("k", b"x" * 256)
        hdr = bytes(store.get("cgxshm/k")).decode()
        assert len(hdr.rsplit(":", 5)) == 5  # only 4 separators
        assert not hdr.rsplit(":", 1)[1].startswith("e")
        out = reader.take("k")
        assert out.tobytes() == b"x" * 256
    finally:
        writer.close()
        reader.close()


def test_stale_epoch_message_discarded(tmp_path):
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("old", b"a" * 128)  # framed at epoch 0
        reader.bump_epoch(1)
        with pytest.raises(StaleGenerationError, match="generation 0"):
            reader.take("old")
        assert metrics.get("cgx.recovery.stale_discards") == 1
        # post-bump traffic flows: writer joins the new generation
        writer.bump_epoch(1)
        writer.put("new", b"b" * 128)
        hdr = bytes(store.get("cgxshm/new")).decode()
        assert hdr.rsplit(":", 1)[1] == "e1"
        assert reader.take("new").tobytes() == b"b" * 128
    finally:
        writer.close()
        reader.close()


def test_epoch_bump_abandons_pending_regions(tmp_path):
    store = FakeStore()
    from torch_cgx_tpu.torch_backend.shm import ShmChannel

    writer = ShmChannel(store, rank=0, directory=str(tmp_path))
    try:
        for i in range(4):
            writer.put(f"k{i}", b"z" * 1024)  # never taken, never acked
        assert len(writer._arena._pending) == 4
        writer.bump_epoch(3)
        assert writer._arena._pending == []  # drained
        assert metrics.get("cgx.recovery.epoch_bumps") == 1
    finally:
        writer.close()


def test_flap_heals_via_retry_rung(tmp_path, monkeypatch):
    # Rung 1 acceptance: a transiently-dropped header (published late) is
    # absorbed by the re-armed bounded wait — no escalation, data intact.
    monkeypatch.setenv("CGX_FAULTS", "flap:400ms@step=0")
    monkeypatch.setenv("CGX_BRIDGE_TIMEOUT_MS", "150")
    monkeypatch.setenv("CGX_RECOVERY_RETRIES", "4")
    monkeypatch.setenv("CGX_RECOVERY_BACKOFF_MS", "30")
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        payload = np.arange(2048, dtype=np.uint8).tobytes()
        writer.put("k", payload)
        assert metrics.get("cgx.faults.flap") == 1
        out = reader.take("k")  # first wait expires; a retry lands it
        assert out.tobytes() == payload
        assert metrics.get("cgx.recovery.retries") >= 1
        assert metrics.get("cgx.bridge_timeout") == 0
    finally:
        writer.close()
        reader.close()


def test_flap_without_retries_still_times_out(tmp_path, monkeypatch):
    # With the retry rung unarmed the old semantics hold exactly.
    monkeypatch.setenv("CGX_FAULTS", "flap:600ms@step=0")
    monkeypatch.setenv("CGX_BRIDGE_TIMEOUT_MS", "150")
    monkeypatch.delenv("CGX_RECOVERY_RETRIES", raising=False)
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("k", b"q" * 512)
        with pytest.raises(BridgeTimeoutError):
            reader.take("k")
    finally:
        writer.close()
        reader.close()


def test_slow_rank_injector_delay():
    inj = faults.FaultInjector(
        faults.parse_faults("slow_rank:0@120ms"), seed=0, rank=0
    )
    t0 = time.monotonic()
    inj.delay("slow_rank")
    assert time.monotonic() - t0 >= 0.12
    other = faults.FaultInjector(
        faults.parse_faults("slow_rank:1@120ms"), seed=0, rank=0
    )
    t0 = time.monotonic()
    other.delay("slow_rank")  # rank gate: not this rank
    assert time.monotonic() - t0 < 0.1


# ---------------------------------------------------------------------------
# JAX-side rollback hook (make_train_step snapshot_every).
# ---------------------------------------------------------------------------


def test_make_train_step_snapshot_hook(monkeypatch):
    """``make_train_step(snapshot_every=2)``: the wrapper host-copies the
    step INPUTS every 2nd step; ``step.rollback()`` re-installs them and
    replaying from there is bit-identical to the uninterrupted run."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from torch_cgx_tpu.parallel import make_train_step, replicate, shard_batch

    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_COMPRESSION_BUCKET_SIZE", "64")
    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("dp",))
    rng = np.random.default_rng(0)
    Wt = rng.normal(size=(16, 4)).astype(np.float32)
    batches = []
    for _ in range(4):
        x = rng.normal(size=(32, 16)).astype(np.float32)
        batches.append((x, x @ Wt))

    def loss_fn(p, b):
        return jnp.mean((b[0] @ p["w"] - b[1]) ** 2)

    opt = optax.adam(1e-2)
    step = make_train_step(
        loss_fn, opt, mesh, donate=False, snapshot_every=2
    )
    params = replicate({"w": jnp.zeros((16, 4), jnp.float32)}, mesh)
    opt_state = replicate(opt.init({"w": jnp.zeros((16, 4), jnp.float32)}), mesh)
    p, s = params, opt_state
    for i, (x, y) in enumerate(batches):
        b = shard_batch((x, y), mesh)
        p, s, _ = step(p, s, b, jnp.int32(i))
    final = np.asarray(p["w"])
    snap = step.last_snapshot()
    assert snap is not None and snap.step == 2
    assert metrics.get("cgx.recovery.snapshots") == 2  # steps 0 and 2
    # rollback and replay steps 2..3: bit-identical to the straight run
    rb_step, (p2, s2) = step.rollback()
    assert rb_step == 2
    for i in (2, 3):
        b = shard_batch(batches[i], mesh)
        p2, s2, _ = step(p2, s2, b, jnp.int32(i))
    np.testing.assert_array_equal(final, np.asarray(p2["w"]))


def test_make_train_step_no_snapshots_by_default(monkeypatch):
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from torch_cgx_tpu.parallel import make_train_step, replicate, shard_batch

    monkeypatch.delenv("CGX_SNAPSHOT_EVERY", raising=False)
    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("dp",))

    def loss_fn(p, b):
        return jnp.mean((b[0] @ p["w"] - b[1]) ** 2)

    opt = optax.adam(1e-2)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    x = np.ones((32, 16), np.float32)
    y = np.ones((32, 4), np.float32)
    params = replicate({"w": jnp.zeros((16, 4), jnp.float32)}, mesh)
    opt_state = replicate(opt.init({"w": jnp.zeros((16, 4), jnp.float32)}), mesh)
    step(params, opt_state, shard_batch((x, y), mesh), jnp.int32(0))
    assert step.last_snapshot() is None
    assert step.rollback() is None
    assert metrics.get("cgx.recovery.snapshots") == 0


# ---------------------------------------------------------------------------
# Chaos soak: kill a rank mid-training, survive, replay bit-identically.
# ---------------------------------------------------------------------------

_SOAK_WS = 3
_SOAK_STEPS = 12
# Kill OFF the snapshot cadence (snapshots at 0,2,4,... — kill at 5) so
# the rollback has real distance: step 4 completed at ws=3, is rolled
# back over, and replays at ws=2.
_SOAK_KILL_STEP = 5
_SOAK_NUMEL = 8192


def _soak_grad(global_rank: int, step: int) -> np.ndarray:
    """Deterministic per-(GLOBAL rank, step) gradient — the survivor-only
    control run regenerates the identical contributions."""
    rng = np.random.default_rng(1000 * (global_rank + 1) + step)
    return rng.normal(size=_SOAK_NUMEL).astype(np.float32)


def _soak_step_fn(states):
    import torch

    def step_fn(group, state, idx):
        states[idx] = state.copy()
        t = torch.from_numpy(_soak_grad(group.global_rank, idx).copy())
        group.allreduce([t]).wait()
        return state - 0.01 * t.numpy()

    return step_fn


def _soak_main(rank: int, ws: int, initfile: str, mdir: str, q) -> None:
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, _REPO)
        os.environ["CGX_BRIDGE_TIMEOUT_MS"] = "2500"
        os.environ["CGX_RECOVERY_RETRIES"] = "1"
        os.environ["CGX_RECOVERY_BACKOFF_MS"] = "50"
        os.environ["CGX_SNAPSHOT_EVERY"] = "2"
        os.environ["CGX_METRICS_DIR"] = mdir
        os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
        os.environ["CGX_FAULTS"] = f"kill_rank:1@step={_SOAK_KILL_STEP}"
        import datetime

        import torch.distributed as dist

        from torch_cgx_tpu.torch_backend.backend import ProcessGroupCGX
        from torch_cgx_tpu.robustness.supervisor import RecoverySupervisor
        from torch_cgx_tpu.robustness import faults as faults_mod
        from torch_cgx_tpu.utils.logging import metrics as m

        store = dist.FileStore(initfile, ws)
        pg = ProcessGroupCGX(
            store, rank, ws, datetime.timedelta(seconds=60)
        )
        sup = RecoverySupervisor(store, pg)
        states: dict = {}
        final = sup.run_steps(
            np.zeros(_SOAK_NUMEL, np.float32), _SOAK_STEPS,
            _soak_step_fn(states),
        )
        problems = []
        if sup.generation != 1:
            problems.append(f"generation {sup.generation} != 1")
        if sup.survivors != [0, 2]:
            problems.append(f"survivors {sup.survivors} != [0, 2]")
        rb = sup.last_rollback_step
        if rb is None or rb > _SOAK_KILL_STEP:
            problems.append(f"bad rollback step {rb}")
        if m.get("cgx.recovery.evictions") != 1:
            problems.append(
                f"evictions counter {m.get('cgx.recovery.evictions')}"
            )
        if m.get("cgx.recovery.replayed_steps") < 1:
            problems.append("no replayed steps counted")
        # -- control: fault-free survivor-only run from the rollback
        # point, on a FRESH generation-namespaced group. Bit-identity of
        # the final parameters proves the replayed steps matched.
        os.environ.pop("CGX_FAULTS", None)
        faults_mod.reset_injectors()
        survivors = sup.survivors
        pg2 = ProcessGroupCGX(
            store, survivors.index(pg.global_rank), len(survivors),
            datetime.timedelta(seconds=60),
            generation=500, global_ranks=survivors,
        )
        control = states[rb].copy()
        fn = _soak_step_fn({})
        for idx in range(rb, _SOAK_STEPS):
            control = fn(pg2, control, idx)
        bit_identical = bool(np.array_equal(final, control))
        if not bit_identical:
            problems.append(
                "replayed run differs from fault-free survivor-only run "
                f"(max abs diff {np.abs(final - control).max()})"
            )
        pg.shutdown()
        pg2.shutdown()
        q.put((rank, "; ".join(problems) or None))
    except Exception:
        q.put((rank, traceback.format_exc()))


@pytest.mark.torch_bridge
def test_chaos_soak_kill_rank_recovers_and_replays(tmp_path):
    """ISSUE 5 chaos acceptance: a 3-rank run loses rank 1 to SIGKILL
    mid-training and completes on the survivors — generation bumped
    exactly once, evicted rank named in the flight-recorder dump,
    post-rollback replay bit-identical to a fault-free survivor-only
    run, ``cgx.recovery.*`` counters emitted, and the report CLI renders
    the recovery section."""
    mdir = str(tmp_path / "metrics")
    initfile = tempfile.mktemp(prefix="cgx_sup_store_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(
            target=_soak_main, args=(r, _SOAK_WS, initfile, mdir, q)
        )
        for r in range(_SOAK_WS)
    ]
    for p in procs:
        p.start()
    results = {}
    for _ in range(2):  # rank 1 dies by design and never reports
        rank, err = q.get(timeout=240)
        results[rank] = err
    for p in procs:
        p.join(timeout=60)
        if p.is_alive():
            p.terminate()
    assert sorted(results) == [0, 2], results
    for rank, err in sorted(results.items()):
        assert err is None, f"rank {rank}: {err}"
    from torch_cgx_tpu.robustness.faults import KILL_EXIT_CODE

    assert procs[1].exitcode == KILL_EXIT_CODE, procs[1].exitcode
    if os.path.exists(initfile):
        os.unlink(initfile)
    # -- flight-recorder acceptance: the eviction left an audit trail --
    path = os.path.join(mdir, "flightrec-rank0.jsonl")
    assert os.path.exists(path), (
        os.listdir(mdir) if os.path.isdir(mdir) else "no metrics dir"
    )
    events = [json.loads(line) for line in open(path)]
    rec = [e for e in events if e.get("kind") == "recovery"]
    assert any(
        e.get("phase") == "evicted_peers" and e.get("evicted") == [1]
        for e in rec
    ), rec
    assert any(e.get("phase") == "reconfigure" for e in rec)
    assert any(e.get("phase") == "rollback" for e in rec)
    # -- report CLI renders the recovery section --
    import subprocess as sp

    proc = sp.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"),
         mdir, "--json"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    js = json.loads(proc.stdout)
    assert js.get("recovery"), js.keys()
    assert js["recovery"]["generation"] >= 1
    assert 1 in js["recovery"]["evicted"]
    # counters fold per-rank maxima then SUM across the two survivors
    assert js["recovery"]["counters"].get("cgx.recovery.evictions", 0) >= 1
    text = sp.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"), mdir],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert text.returncode == 0
    assert "== recovery" in text.stdout


# ---------------------------------------------------------------------------
# slow_rank absorbed by the retry rung through the real bridge.
# ---------------------------------------------------------------------------


def _slow_main(rank: int, ws: int, initfile: str, q) -> None:
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, _REPO)
        os.environ["CGX_BRIDGE_TIMEOUT_MS"] = "700"
        os.environ["CGX_RECOVERY_RETRIES"] = "3"
        os.environ["CGX_RECOVERY_BACKOFF_MS"] = "50"
        os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
        # rank 1 sleeps 1.2 s at its first collective entry: longer than
        # one bounded wait, far shorter than the retry budget.
        os.environ["CGX_FAULTS"] = "slow_rank:1@1200ms@step=0"
        import datetime

        import torch
        import torch.distributed as dist

        from torch_cgx_tpu.torch_backend.backend import ProcessGroupCGX
        from torch_cgx_tpu.utils.logging import metrics as m

        store = dist.FileStore(initfile, ws)
        pg = ProcessGroupCGX(store, rank, ws, datetime.timedelta(seconds=30))
        t = torch.full((4096,), float(rank + 1))
        pg.allreduce([t]).wait()
        expect = sum(float(r + 1) for r in range(ws))
        ok = bool(torch.allclose(t, torch.full((4096,), expect), atol=0.5))
        retries = m.get("cgx.recovery.retries")
        pg.shutdown()
        q.put((rank, None if ok else "wrong reduction", retries))
    except Exception:
        q.put((rank, traceback.format_exc(), 0))


@pytest.mark.torch_bridge
def test_slow_rank_absorbed_by_retry_rung(tmp_path):
    """A straggler (alive heartbeat, 1.2 s stall vs a 0.7 s wait bound)
    must NOT be evicted: the fast rank's expired wait re-arms and the
    collective completes with the correct reduction."""
    initfile = tempfile.mktemp(prefix="cgx_slow_store_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_slow_main, args=(r, 2, initfile, q))
        for r in range(2)
    ]
    for p in procs:
        p.start()
    results = {}
    for _ in range(2):
        rank, err, retries = q.get(timeout=120)
        results[rank] = (err, retries)
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    if os.path.exists(initfile):
        os.unlink(initfile)
    for rank, (err, _r) in sorted(results.items()):
        assert err is None, f"rank {rank}: {err}"
    # the fast rank's wait expired at least once and was re-armed
    assert results[0][1] >= 1, results
