"""Serving data plane (ISSUE 15): paged quantized KV-cache wire for
disaggregated prefill/decode with continuous batching.

Covers the acceptance set:

* 8-bit KV decode bit envelope — greedy decode TOKEN-IDENTICAL to the
  raw-f16 baseline on the test model (and to the full-model recompute);
* paged-allocator stress — alloc/free/refcount under churn, prefix
  forks, double-free detection, pool exhaustion backpressure;
* chaos — a prefill worker killed mid-stream degrades through the
  bounded failover rung (local prefill) instead of wedging decode;
* transport hardening — frame checksum, publish-after-write ordering,
  wire-spec mismatch rejection;
* knob→cache-key completeness + the recovery cascade into the serving
  memos (supervisor.invalidate_trace_caches);
* the planner's serve terms and the SLO controller's budget law.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_cgx_tpu import config as cfg_mod
from torch_cgx_tpu.models.gpt2 import GPT2, GPT2Config
from torch_cgx_tpu.serving import kv_cache as kv_mod
from torch_cgx_tpu.serving import scheduler as sched_mod
from torch_cgx_tpu.serving import transport as tp
from torch_cgx_tpu.serving.prefill import PrefillWorker
from torch_cgx_tpu.serving.scheduler import (
    ContinuousBatchScheduler,
    GPT2Server,
    Request,
    ServeConfig,
)
from torch_cgx_tpu.serving.slo import ServeSloController
from torch_cgx_tpu.serving.transport import (
    KvPageReceiver,
    KvPageSender,
    frame_page,
    unframe_page,
)
from torch_cgx_tpu.utils.logging import metrics
from torch_cgx_tpu.wire import edges

from test_faults import FakeStore

PAGE = 8
DEADLINE_S = 300.0


@pytest.fixture(autouse=True)
def _clear_edge_registry():
    """The SLO controller registers kv_page edge configs; a registered
    edge outlives the conftest layer-registry clear and would override
    the CGX_KV_BITS env default in later tests (registered configs win
    by design — the pollution must be cleaned, not the precedence)."""
    edges.clear_edges()
    yield
    edges.clear_edges()


@pytest.fixture(scope="module")
def model_setup():
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32), train=False
    )
    return cfg, model, params


def _serve_cfg(**kw):
    base = dict(page_tokens=PAGE, max_batch=4, max_pages=48, max_seq=64,
                ship_depth=2)
    base.update(kw)
    return ServeConfig(**base)


def _prompts(cfg, n, lens=None, seed=1):
    rng = np.random.default_rng(seed)
    lens = lens or [13 + 3 * i for i in range(n)]
    return [
        [int(t) for t in rng.integers(0, cfg.vocab_size, ln)]
        for ln in lens[:n]
    ]


def _run_local(cfg, params, prompts, gen=10, sv=None):
    server = GPT2Server(cfg, params, sv or _serve_cfg())
    sched = ContinuousBatchScheduler(server)
    reqs = [
        Request(id=f"r{i}", tokens=list(p), max_new_tokens=gen)
        for i, p in enumerate(prompts)
    ]
    for r in reqs:
        sched.submit(r)
    assert sched.run(deadline_s=DEADLINE_S), "serving run wedged"
    return [r.output for r in reqs]


# ---------------------------------------------------------------------------
# Bit envelope: greedy decode token identity.
# ---------------------------------------------------------------------------


# Slow tier: the exhaustive full-model oracle (~30 s);
# test_8bit_kv_token_identical_to_f16 keeps the decode-path token
# identity in tier-1.
@pytest.mark.slow
def test_decode_matches_full_model_greedy(model_setup, monkeypatch):
    """Raw-KV serving decode == full-model greedy recompute, token for
    token (the paged-cache forward is the module's math)."""
    cfg, model, params = model_setup
    monkeypatch.setenv("CGX_KV_BITS", "0")
    prompt = _prompts(cfg, 1, lens=[21])[0]
    (out,) = _run_local(cfg, params, [prompt], gen=8)
    seq = list(prompt)
    ref = []
    for _ in range(8):
        logits = model.apply(
            params, jnp.asarray([seq], jnp.int32), train=False
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        ref.append(nxt)
        seq.append(nxt)
    assert out == ref


def test_8bit_kv_token_identical_to_f16(model_setup, monkeypatch):
    """The acceptance bit envelope: 8-bit quantized KV pages decode to
    the SAME greedy tokens as raw f16 shipping on the test model —
    multi-request, multi-page, with tail commits crossing page
    boundaries mid-generation."""
    cfg, _model, params = model_setup
    prompts = _prompts(cfg, 3, lens=[21, 16, 11])
    monkeypatch.setenv("CGX_KV_BITS", "0")
    raw = _run_local(cfg, params, prompts, gen=12)
    monkeypatch.setenv("CGX_KV_BITS", "8")
    q8 = _run_local(cfg, params, prompts, gen=12)
    assert q8 == raw
    # The quantized arm really quantized: kv_page wire bytes were
    # accounted below the raw f32 footprint.
    snap = metrics.snapshot("cgx.wire.bytes_")
    assert snap.get("cgx.wire.bytes_wire.kv_page", 0) > 0
    assert (
        snap["cgx.wire.bytes_wire.kv_page"]
        < snap["cgx.wire.bytes_raw.kv_page"] / 2
    )


def test_4bit_kv_stays_in_envelope(model_setup, monkeypatch):
    """4-bit KV is NOT required to be token-identical — but the decode
    must complete and produce the right shape of output (the envelope
    degrades gracefully, never crashes)."""
    cfg, _model, params = model_setup
    monkeypatch.setenv("CGX_KV_BITS", "4")
    prompts = _prompts(cfg, 2, lens=[13, 16])
    outs = _run_local(cfg, params, prompts, gen=6)
    assert all(len(o) == 6 for o in outs)


# ---------------------------------------------------------------------------
# Paged allocator stress.
# ---------------------------------------------------------------------------


def test_allocator_churn_no_leaks():
    cache = kv_mod.PagedKvCache(max_pages=32, page_tokens=8)
    rng = np.random.default_rng(0)
    live = {}
    for round_idx in range(200):
        sid = f"s{rng.integers(0, 12)}"
        if sid in live and rng.random() < 0.4:
            freed = cache.free_seq(sid)
            assert freed == len(live.pop(sid))
        else:
            pid = cache.alloc(sid)
            if pid is None:
                continue  # pool pressure is backpressure, not an error
            live.setdefault(sid, []).append(pid)
            assert cache.refcount(pid) == 1
    for sid in list(live):
        cache.free_seq(sid)
    assert cache.free_pages == 32
    assert cache.live_pages == 0


def test_allocator_fork_refcounts():
    cache = kv_mod.PagedKvCache(max_pages=8, page_tokens=4)
    for _ in range(3):
        cache.alloc("base")
    shared = cache.fork("base", "child")
    assert shared == cache.pages_of("base")
    for pid in shared:
        assert cache.refcount(pid) == 2
    # base frees: shared pages survive under the child's refcount
    assert cache.free_seq("base") == 0
    for pid in shared:
        assert cache.refcount(pid) == 1
    assert cache.free_seq("child") == len(shared)
    assert cache.free_pages == 8


def test_allocator_exhaustion_and_counters():
    cache = kv_mod.PagedKvCache(max_pages=2, page_tokens=4)
    assert cache.alloc("a") is not None
    assert cache.alloc("a") is not None
    before = metrics.get("cgx.serve.pool_exhausted")
    assert cache.alloc("a") is None
    assert metrics.get("cgx.serve.pool_exhausted") == before + 1


def test_allocator_invalidate_bumps_generation():
    cache = kv_mod.PagedKvCache(max_pages=4, page_tokens=4)
    cache.alloc("s")
    gen = cache.generation
    kv_mod.invalidate_page_tables("test")
    assert cache.generation == gen + 1
    assert not cache.has_seq("s")
    assert cache.free_pages == 4


# ---------------------------------------------------------------------------
# Transport hardening.
# ---------------------------------------------------------------------------


def test_frame_roundtrip_and_checksum():
    payload = np.random.default_rng(0).bytes(333)
    buf = frame_page(3, tp.K_PAGE, 7, 8, 512, 1024, payload)
    f = unframe_page(buf)
    assert (f.layer, f.kind, f.page_idx, f.bits, f.bucket, f.numel) == (
        3, tp.K_PAGE, 7, 8, 512, 1024
    )
    assert f.payload == payload
    corrupted = bytearray(buf)
    corrupted[-1] ^= 0xFF
    from torch_cgx_tpu.robustness.errors import WireCorruptionError

    with pytest.raises(WireCorruptionError):
        unframe_page(bytes(corrupted))
    # checksum off: the sentinel crc skips the verify
    un = frame_page(0, tp.META, 0, 0, 0, 0, b"{}", checksum=False)
    assert unframe_page(un).payload == b"{}"


def test_publish_after_write_poll_never_blocks():
    store = FakeStore()
    sender = KvPageSender(store, "s0", depth=2)
    recv = KvPageReceiver(store)
    recv.add_stream("s0")
    assert recv.poll() == []  # nothing published: returns, not blocks
    sender.post_meta({"frames": 3, "pages": 1, "prompt_tokens": 4,
                      "page_tokens": 4, "tail_tokens": 0,
                      "first_token": 1})
    sender.post_page(0, tp.K_PAGE, 0, 8, 512, 16, b"x" * 16)
    sender.post_page(0, tp.V_PAGE, 0, 8, 512, 16, b"y" * 16)
    deadline = time.monotonic() + 30.0
    got = []
    while len(got) < 3 and time.monotonic() < deadline:
        got.extend(recv.poll())
        time.sleep(0.005)
    sender.stop()
    assert [f.kind for _s, f in got] == [tp.META, tp.K_PAGE, tp.V_PAGE]
    assert recv.complete("s0")


def test_stream_spec_mismatch_fails_over_to_local(model_setup):
    """A stream whose frames carry the wrong wire spec (prefill resolved
    different kv_page bits than decode) is rejected at ingest and the
    request completes through the local-prefill rung — never a wedge,
    never a silently mis-decoded page."""
    cfg, _model, params = model_setup
    store = FakeStore()
    recv = KvPageReceiver(store)
    server = GPT2Server(cfg, params, _serve_cfg())
    sched = ContinuousBatchScheduler(server, receiver=recv)
    req = Request(id="bad", tokens=_prompts(cfg, 1, lens=[PAGE])[0],
                  max_new_tokens=4)
    sched.submit(req, remote=True)
    sender = KvPageSender(store, "bad", depth=4)
    spec = sched._prog.specs[0]
    n_frames = 1 + 2 * cfg.n_layer + 2 * cfg.n_layer
    sender.post_meta({
        "frames": n_frames, "pages": 1, "prompt_tokens": PAGE,
        "page_tokens": PAGE, "tail_tokens": 0, "first_token": 1,
    })
    wrong_bits = 3
    assert wrong_bits != spec.bits
    for layer in range(cfg.n_layer):
        for kind in (tp.K_PAGE, tp.V_PAGE):
            sender.post_page(layer, kind, 0, wrong_bits, 64, spec.flat,
                             b"\x00" * 64)
        for kind in (tp.K_TAIL, tp.V_TAIL):
            sender.post_page(layer, kind, 0, 0, 0, 0, b"")
    before = metrics.get("cgx.serve.ingest_errors")
    assert sched.run(deadline_s=DEADLINE_S)
    sender.stop()
    assert len(req.output) == 4
    assert metrics.get("cgx.serve.ingest_errors") == before + 1


# ---------------------------------------------------------------------------
# Disaggregated end-to-end + chaos.
# ---------------------------------------------------------------------------


def test_remote_prefill_matches_local(model_setup, monkeypatch):
    cfg, _model, params = model_setup
    monkeypatch.setenv("CGX_SERVE_PREFILL_TIMEOUT_MS", "60000")
    prompts = _prompts(cfg, 3, lens=[16, 16, 24])
    store = FakeStore()
    recv = KvPageReceiver(store)
    server = GPT2Server(cfg, params, _serve_cfg())
    sched = ContinuousBatchScheduler(server, receiver=recv)
    worker = PrefillWorker(server, store)
    reqs = [
        Request(id=f"r{i}", tokens=list(p), max_new_tokens=8)
        for i, p in enumerate(prompts)
    ]
    for r in reqs:
        sched.submit(r, remote=True)
    t = threading.Thread(
        target=lambda: [worker.serve(r.id, r.tokens) for r in reqs]
    )
    t.start()
    ok = sched.run(deadline_s=DEADLINE_S)
    t.join(timeout=30)
    worker.stop()
    assert ok
    assert metrics.get("cgx.serve.prefill_failovers") == 0
    local = _run_local(cfg, params, prompts, gen=8)
    assert [r.output for r in reqs] == local


def test_prefill_death_mid_stream_degrades_not_wedges(
    model_setup, monkeypatch
):
    """Chaos: the prefill worker dies after shipping only a PARTIAL
    stream (some frames published, completion never arrives). Decode
    must detect the stall within the bounded failover window, re-prefill
    locally, and finish every request — the PR 5 degrade-don't-die
    contract on the serving plane."""
    cfg, _model, params = model_setup
    monkeypatch.setenv("CGX_SERVE_PREFILL_TIMEOUT_MS", "500")
    store = FakeStore()
    recv = KvPageReceiver(store)
    server = GPT2Server(cfg, params, _serve_cfg())
    sched = ContinuousBatchScheduler(server, receiver=recv)
    prompts = _prompts(cfg, 2, lens=[24, 16])
    reqs = [
        Request(id=f"r{i}", tokens=list(p), max_new_tokens=6)
        for i, p in enumerate(prompts)
    ]
    for r in reqs:
        sched.submit(r, remote=True)
    # Worker "dies" mid-stream: r0's meta + a few frames publish, then
    # nothing — and r1's stream never even opens.
    sender = KvPageSender(store, "r0", depth=2)
    sender.post_meta({
        "frames": 99, "pages": 2, "prompt_tokens": 24,
        "page_tokens": PAGE, "tail_tokens": 0, "first_token": 1,
    })
    sender.post_page(0, tp.K_PAGE, 0, 8, 512, 16, b"z" * 16)
    t0 = time.monotonic()
    ok = sched.run(deadline_s=DEADLINE_S)
    wall = time.monotonic() - t0
    sender.stop()
    assert ok, "decode wedged behind a dead prefill worker"
    assert metrics.get("cgx.serve.prefill_failovers") == 2.0
    assert [len(r.output) for r in reqs] == [6, 6]
    # Degraded output is still CORRECT output (local prefill is the
    # same math).
    assert [r.output for r in reqs] == _run_local(
        cfg, params, prompts, gen=6
    )
    # Bounded: stall detection + recovery, not a 300 s timeout crawl.
    assert wall < DEADLINE_S / 2


def test_request_id_flow_survives_prefill_death(
    model_setup, monkeypatch, tmp_path
):
    """ISSUE 17: the request_id thread survives the failover rung. A
    prefill worker that dies after meta + one frame forces the local
    re-prefill; the span stream must still carry ONE coherent flow for
    the request (submit -> failover -> local prefill -> admit), and the
    critical-path engine must decompose its TTFT with the failover
    counted — traceability must not die with the worker."""
    from torch_cgx_tpu.observability import critpath, timeline

    cfg, _model, params = model_setup
    monkeypatch.setenv("CGX_SERVE_PREFILL_TIMEOUT_MS", "500")
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    timeline.reset()
    try:
        store = FakeStore()
        recv = KvPageReceiver(store)
        server = GPT2Server(cfg, params, _serve_cfg())
        sched = ContinuousBatchScheduler(server, receiver=recv)
        (prompt,) = _prompts(cfg, 1, lens=[24])
        req = Request(id="r0", tokens=list(prompt), max_new_tokens=6)
        sched.submit(req, remote=True)
        sender = KvPageSender(store, "r0", depth=2)
        sender.post_meta({
            "frames": 99, "pages": 2, "prompt_tokens": 24,
            "page_tokens": PAGE, "tail_tokens": 0, "first_token": 1,
        })
        # the dead worker's META frame already stamped the request id:
        # the wire stream joins back to the request without the
        # scheduler's stream registry
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:
                meta_frame_bytes = store.get("cgxkv/r0/1")
                break
            except KeyError:
                time.sleep(0.01)
        assert b'"request_id": "r0"' in meta_frame_bytes
        sender.post_page(0, tp.K_PAGE, 0, 8, 512, 16, b"z" * 16)
        assert sched.run(deadline_s=DEADLINE_S)
        sender.stop()
        assert len(req.output) == 6
        timeline.flush()
        flow = critpath.analyze(str(tmp_path), use_cache=False)["requests"]
        assert "r0" in flow, flow
        r0 = flow["r0"]
        assert r0["failovers"] >= 1
        assert r0["events"] >= 3  # submit + failover + prefill + admit
        assert r0["ttft_s"] is not None and r0["ttft_s"] > 0.0
        c = r0["components"]
        # the local re-prefill is attributed as prefill, and the stall
        # window that preceded the failover shows up (other/admission),
        # the decomposition summing to the TTFT
        assert c["prefill"] > 0.0
        assert sum(c.values()) == pytest.approx(r0["ttft_s"], abs=0.01)
    finally:
        timeline.reset()


def test_continuous_batching_admits_midstream(model_setup):
    """More requests than lanes: later requests admit as earlier lanes
    complete (the batch never drains), and every output matches the
    request's own single-request run."""
    cfg, _model, params = model_setup
    sv = _serve_cfg(max_batch=2, max_pages=64)
    prompts = _prompts(cfg, 5, lens=[16, 13, 11, 16, 24])
    outs = _run_local(cfg, params, prompts, gen=7, sv=sv)
    assert metrics.get("cgx.serve.requests_completed") >= 5
    for i, p in enumerate(prompts):
        (solo,) = _run_local(cfg, params, [p], gen=7, sv=sv)
        assert outs[i] == solo, f"request {i} diverged under batching"


# ---------------------------------------------------------------------------
# Knob→cache-key completeness + the recovery cascade.
# ---------------------------------------------------------------------------


def test_serve_knobs_rekey_decode_program(model_setup, monkeypatch):
    cfg, _model, params = model_setup
    server = GPT2Server(cfg, params, _serve_cfg())
    k0 = sched_mod._program_key(server)
    monkeypatch.setenv("CGX_KV_BITS", "4")
    k1 = sched_mod._program_key(server)
    assert k0 != k1, "CGX_KV_BITS flip must re-key the decode program"
    monkeypatch.delenv("CGX_KV_BITS")
    assert sched_mod._program_key(server) == k0
    # the serving knobs ride the shared trace fingerprint too
    fp0 = cfg_mod.trace_knob_fingerprint()
    monkeypatch.setenv("CGX_SERVE_MAX_BATCH", "3")
    assert cfg_mod.trace_knob_fingerprint() != fp0


def test_registry_write_rekeys_program(model_setup):
    cfg, _model, params = model_setup
    server = GPT2Server(cfg, params, _serve_cfg())
    k0 = sched_mod._program_key(server)
    edges.set_edge_config(
        edges.EDGE_KV_PAGE, "^layer_0$",
        edges.EdgeConfig(cc=cfg_mod.CompressionConfig(bits=5,
                                                      bucket_size=0)),
    )
    assert sched_mod._program_key(server) != k0
    specs = sched_mod._resolved_specs(server)
    assert specs[0].bits == 5
    assert specs[1].bits == cfg_mod.kv_bits()


def test_supervisor_cascade_reaches_serving(model_setup):
    """supervisor.invalidate_trace_caches must drop the decode-program
    LRU and bump every live cache's generation; a mid-flight scheduler
    then re-derives (re-prefills) and still completes correctly."""
    from torch_cgx_tpu.robustness.supervisor import invalidate_trace_caches

    cfg, _model, params = model_setup
    server = GPT2Server(cfg, params, _serve_cfg())
    sched = ContinuousBatchScheduler(server)
    prompts = _prompts(cfg, 2, lens=[16, 13])
    reqs = [
        Request(id=f"r{i}", tokens=list(p), max_new_tokens=6)
        for i, p in enumerate(prompts)
    ]
    for r in reqs:
        sched.submit(r)
    # run a few steps, then yank the rug mid-generation
    for _ in range(3):
        sched.step()
    gen_before = sched.cache.generation
    invalidate_trace_caches()
    assert sched.cache.generation == gen_before + 1
    assert len(sched_mod._PROGRAM_CACHE) == 0
    assert sched.run(deadline_s=DEADLINE_S)
    assert [r.output for r in reqs] == _run_local(
        cfg, params, prompts, gen=6
    )


# ---------------------------------------------------------------------------
# Planner serve terms.
# ---------------------------------------------------------------------------


def test_predict_serve_prices_quantization():
    from torch_cgx_tpu.parallel.planner import CostModel

    m = CostModel.default()
    kv_b = 2 * 2 * 128 * 4
    ttft_q, _ = m.predict_serve(96, kv_b, 2, 8, 512, 16, 4)
    ttft_raw, _ = m.predict_serve(96, kv_b, 2, 0, 512, 16, 4)
    assert ttft_q < ttft_raw, "8-bit pages must predict faster than f16"
    # deeper shipping pipelines never predict slower
    ttft_d1, _ = m.predict_serve(96, kv_b, 2, 8, 512, 16, 1)
    assert ttft_q <= ttft_d1 + 1e-12


def test_solve_serve_plan_picks_candidates():
    from torch_cgx_tpu.parallel import planner

    plan = planner.solve_serve_plan(96, 2 * 2 * 128 * 4, 2, 8, 512)
    assert plan.page_tokens in planner.SERVE_PAGE_CANDIDATES
    assert plan.ship_depth in planner.SERVE_DEPTH_CANDIDATES
    assert plan.predicted_ttft_s > 0
    assert metrics.get("cgx.plan.serve_page_tokens") == plan.page_tokens


def test_serve_config_from_env_uses_planner(model_setup, monkeypatch):
    cfg, _model, _params = model_setup
    sv = ServeConfig.from_env(cfg)
    from torch_cgx_tpu.parallel import planner

    assert sv.page_tokens in planner.SERVE_PAGE_CANDIDATES
    monkeypatch.setenv("CGX_KV_PAGE_TOKENS", "8")
    monkeypatch.setenv("CGX_KV_SHIP_DEPTH", "2")
    sv2 = ServeConfig.from_env(cfg)
    assert (sv2.page_tokens, sv2.ship_depth) == (8, 2)


# ---------------------------------------------------------------------------
# SLO controller.
# ---------------------------------------------------------------------------


def test_slo_controller_drops_and_recovers_bits(monkeypatch):
    monkeypatch.setenv("CGX_KV_BITS", "8")
    ctl = ServeSloController(
        ttft_slo_ms=100.0, every=0, min_bits=2, max_bits=8
    )
    assert ctl.engaged
    # violate: TTFT p90 far over target
    for _ in range(20):
        metrics.observe("cgx.serve.ttft_ms", 400.0)
    ctl.update()
    assert ctl.budget == 7
    cc = kv_mod.resolve_kv_config("layer_0")
    assert cc is not None and cc.bits == 7
    v0 = cfg_mod.registry_version()
    # hold: p90 between 0.8x and 1.0x of slo -> no movement, no churn
    metrics.reset()
    for _ in range(20):
        metrics.observe("cgx.serve.ttft_ms", 90.0)
    ctl.update()
    assert ctl.budget == 7
    assert cfg_mod.registry_version() == v0
    # comfortable: p90 well under target -> budget recovers
    metrics.reset()
    for _ in range(20):
        metrics.observe("cgx.serve.ttft_ms", 10.0)
    ctl.update()
    assert ctl.budget == 8
    cc = kv_mod.resolve_kv_config("layer_0")
    assert cc is not None and cc.bits == 8


def test_slo_controller_per_layer_solve_with_qerr(monkeypatch):
    """With kv_page qerr telemetry streaming, the budget re-allocates
    ACROSS layers (the scoped WireController solve): the error-heavy
    layer keeps more bits under the same average budget."""
    monkeypatch.setenv("CGX_KV_BITS", "8")
    from torch_cgx_tpu.wire import dispatch as wire_dispatch

    wire_dispatch.note_external_edge(
        "kv_page", "layer_0", numel=4096, bits=8,
        raw_bytes=16384, wire_bytes=4096,
    )
    wire_dispatch.note_external_edge(
        "kv_page", "layer_1", numel=4096, bits=8,
        raw_bytes=16384, wire_bytes=4096,
    )
    for _ in range(10):
        metrics.observe("cgx.qerr.wire:kv_page:layer_0", 0.10)
        metrics.observe("cgx.qerr.wire:kv_page:layer_1", 0.001)
    ctl = ServeSloController(
        ttft_slo_ms=100.0, every=0, min_bits=2, max_bits=8,
        min_observations=1,
    )
    for _ in range(20):
        metrics.observe("cgx.serve.ttft_ms", 400.0)
    alloc = ctl.update()
    b0 = alloc.get("wire:kv_page:layer_0")
    b1 = alloc.get("wire:kv_page:layer_1")
    assert b0 is not None and b1 is not None
    assert b0 > b1, "noisier layer must keep more bits"
    assert kv_mod.resolve_kv_config("layer_0").bits == b0
    assert kv_mod.resolve_kv_config("layer_1").bits == b1


def test_slo_scoped_controller_leaves_training_edges_alone(monkeypatch):
    """The serving objective must never re-bit a training edge: a
    ring_kv qerr stream outside the kv_page scope stays untouched by the
    SLO solve."""
    monkeypatch.setenv("CGX_KV_BITS", "8")
    from torch_cgx_tpu.wire import dispatch as wire_dispatch

    wire_dispatch.note_external_edge(
        "kv_page", "layer_0", numel=4096, bits=8,
        raw_bytes=16384, wire_bytes=4096,
    )
    edges.set_edge_config(
        edges.EDGE_RING_KV, "^train$",
        edges.EdgeConfig(cc=cfg_mod.CompressionConfig(bits=6,
                                                      bucket_size=0)),
    )
    wire_dispatch.note_external_edge(
        "ring_kv", "train", numel=4096, bits=6,
        raw_bytes=16384, wire_bytes=4096,
    )
    for _ in range(10):
        metrics.observe("cgx.qerr.wire:kv_page:layer_0", 0.05)
        metrics.observe("cgx.qerr.wire:ring_kv:train", 0.05)
    ctl = ServeSloController(
        ttft_slo_ms=100.0, every=0, min_observations=1
    )
    for _ in range(20):
        metrics.observe("cgx.serve.ttft_ms", 400.0)
    alloc = ctl.update()
    assert all(k.startswith("wire:kv_page:") for k in alloc)
    ring = edges.resolve_edge(edges.EDGE_RING_KV, "train")
    assert ring is not None and ring.cc.bits == 6


# ---------------------------------------------------------------------------
# Page codec layout cross-checks (pool rows == host wire bytes).
# ---------------------------------------------------------------------------


def test_host_wire_bytes_drop_into_pool_rows():
    """The transport's host-codec page bytes and the decode pool's own
    jit commit produce IDENTICAL pool rows — the zero-re-encoding
    contract the receiver relies on."""
    from torch_cgx_tpu.ops import codec_host, paged_kv

    spec = paged_kv.PageSpec(
        page_tokens=PAGE, n_head=4, d_head=32, bits=8, bucket_size=512
    )
    rng = np.random.default_rng(3)
    row = rng.standard_normal(spec.flat).astype(np.float32)
    packed_j, meta_j = paged_kv.quantize_page_rows(row[None], spec)
    q_host = codec_host.quantize(row, spec.bits, spec.bucket_size)
    buf = np.asarray(q_host.to_bytes())
    rehydrated = codec_host.from_bytes(
        buf, spec.flat, spec.bits, spec.bucket_size, np.float32
    )
    np.testing.assert_array_equal(
        np.asarray(packed_j[0]), rehydrated.packed
    )
    np.testing.assert_array_equal(
        np.asarray(meta_j[0]), rehydrated.meta
    )
    assert buf.nbytes == spec.wire_bytes()


def test_rekey_drains_active_lanes_without_token_loss(
    model_setup, monkeypatch
):
    """An SLO/knob re-key mid-generation must NOT evict active lanes:
    admission pauses, the running lane finishes under the old program
    (keeping every generated token), and the new width adopts at the
    drain point — while a waiting request admitted after adoption runs
    under the new bits."""
    cfg, _model, params = model_setup
    monkeypatch.setenv("CGX_KV_BITS", "8")
    drains0 = metrics.get("cgx.serve.rekey_drains")
    adopts0 = metrics.get("cgx.serve.bits_adoptions")
    server = GPT2Server(cfg, params, _serve_cfg(max_batch=2))
    sched = ContinuousBatchScheduler(server)
    first = Request(id="a", tokens=_prompts(cfg, 1, lens=[16])[0],
                    max_new_tokens=8)
    sched.submit(first)
    for _ in range(3):
        sched.step()
    tokens_so_far = list(first.output)
    assert tokens_so_far, "lane should be generating"
    # the SLO controller's write: re-keys the program mid-flight
    monkeypatch.setenv("CGX_KV_BITS", "5")
    second = Request(id="b", tokens=_prompts(cfg, 1, lens=[16])[0],
                     max_new_tokens=4)
    sched.submit(second)
    sched.step()
    # drain pending: the running lane kept its tokens, b not admitted
    assert first.output[: len(tokens_so_far)] == tokens_so_far
    assert metrics.get("cgx.serve.rekey_drains") == drains0 + 1
    assert sched.run(deadline_s=DEADLINE_S)
    assert len(first.output) == 8 and len(second.output) == 4
    assert metrics.get("cgx.serve.bits_adoptions") == adopts0 + 1
    assert sched_mod._resolved_specs(server)[0].bits == 5
    # nothing leaked: every page returned to the pool
    assert sched.cache.free_pages == sched.cache.max_pages


def test_prefill_ahead_bounded_by_free_lanes(model_setup):
    """One scheduler step must not prefill the whole waiting queue:
    prefill-ahead is bounded by free lanes, so queued requests hold no
    pool pages until a lane can actually take them."""
    cfg, _model, params = model_setup
    before = metrics.get("cgx.serve.local_prefills")
    server = GPT2Server(cfg, params, _serve_cfg(max_batch=2))
    sched = ContinuousBatchScheduler(server)
    for i, p in enumerate(_prompts(cfg, 6, lens=[16] * 6)):
        sched.submit(Request(id=f"r{i}", tokens=list(p),
                             max_new_tokens=4))
    sched.step()
    prefilled = metrics.get("cgx.serve.local_prefills") - before
    assert prefilled <= 2, (
        f"step prefilled {prefilled} requests for 2 lanes"
    )
    assert sched.run(deadline_s=DEADLINE_S)


def test_sender_retry_keeps_seq_dense():
    """A transient store failure mid-ship must not burn a sequence
    number: the retried frame publishes under the SAME seq, so the
    receiver's dense walk still completes the stream."""

    class FlakyStore(FakeStore):
        def __init__(self):
            super().__init__()
            self.fail_next = 1

        def set(self, k, v):
            if "cgxkv/" in k and self.fail_next:
                self.fail_next -= 1
                raise RuntimeError("transient store failure")
            super().set(k, v)

    store = FlakyStore()
    sender = KvPageSender(store, "s0", depth=4)
    recv = KvPageReceiver(store)
    recv.add_stream("s0")
    sender.post_meta({"frames": 2, "pages": 0, "prompt_tokens": 1,
                      "page_tokens": 4, "tail_tokens": 0,
                      "first_token": 0})
    sender.post_page(0, tp.K_TAIL, 0, 0, 0, 4, b"\x00" * 8)
    deadline = time.monotonic() + 30.0
    got = []
    while len(got) < 2 and time.monotonic() < deadline:
        got.extend(recv.poll())
        time.sleep(0.005)
    sender.stop()
    assert len(got) == 2, "retried frame never became fetchable"
    assert recv.complete("s0")


def test_tps_only_slo_recovers(monkeypatch):
    """A tokens/s-only SLO must recover bits when throughput is back
    over target — not just drop them (the one-way ratchet bug)."""
    monkeypatch.setenv("CGX_KV_BITS", "8")
    ctl = ServeSloController(tps_slo=100.0, every=0)
    metrics.set("cgx.serve.tokens_per_s", 50.0)
    ctl.update()
    assert ctl.budget == 7
    metrics.set("cgx.serve.tokens_per_s", 200.0)
    ctl.update()
    assert ctl.budget == 8


def test_training_controller_excludes_kv_page_labels(monkeypatch):
    """Colocated train-and-serve: the DEFAULT (unscoped) training
    controller must not ingest serving kv_page telemetry — re-widthing
    serving pages from the training objective is the cross-plane write
    the scoping exists to prevent."""
    from torch_cgx_tpu.wire import dispatch as wire_dispatch
    from torch_cgx_tpu.wire.controller import WireController

    monkeypatch.setenv("CGX_KV_BITS", "8")
    wire_dispatch.note_external_edge(
        "kv_page", "layer_0", numel=4096, bits=8,
        raw_bytes=16384, wire_bytes=4096,
    )
    for _ in range(10):
        metrics.observe("cgx.qerr.wire:kv_page:layer_0", 0.05)
    ctl = WireController(avg_bits=4.0, every=0, min_observations=1)
    alloc = ctl.update()
    assert not any(k.startswith("wire:kv_page:") for k in alloc)
    assert kv_mod.resolve_kv_config("layer_0").bits == 8
