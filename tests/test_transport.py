"""Fault-tolerant socket transport tests (PR 20).

Three layers:

- **Unit** (no torch): the ``SocketTransport`` plane pair over a fake
  c10d store — roundtrip bit-identity, crc framing, bounded fetch,
  the reconnect/replay ladder under injected ``conn_reset`` /
  ``partial_write``, degrade-to-store under ``partition``, the
  ``TransportStore`` routing shim, the ``maybe_wrap_store`` identity
  pin, and the cross-host store-counter liveness judge.
- **Grammar**: the CGX_FAULTS network modes parse (and reject junk —
  a typo silently injecting nothing makes a chaos run vacuously
  green).
- **Bridge** (multi-process, ``torch_bridge``-marked): the real
  ``"cgx"`` backend with ``CGX_TRANSPORT=socket`` — bit-identity
  against the legacy store path, the conn_reset replay soak, the
  partition degrade (strictly before CGX_BRIDGE_TIMEOUT_MS, training
  continues), SIGKILL eviction naming, and the two-"hosts" heartbeat
  regression.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time
import traceback

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from torch_cgx_tpu import config as cfg  # noqa: E402
from torch_cgx_tpu.robustness import faults  # noqa: E402
from torch_cgx_tpu.robustness import heartbeat as hb  # noqa: E402
from torch_cgx_tpu.torch_backend import transport as tp  # noqa: E402
from torch_cgx_tpu.utils.logging import metrics  # noqa: E402


@pytest.fixture(autouse=True)
def _reset():
    faults.reset_injectors()
    metrics.reset()
    yield
    faults.reset_injectors()


class FakeStore:
    """Minimal c10d-Store look-alike with the wait/check surface the
    transport's store fallback uses."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def set(self, k, v):
        with self._lock:
            self._d[k] = v if isinstance(v, bytes) else bytes(v)

    def get(self, k):
        with self._lock:
            if k not in self._d:
                raise KeyError(k)
            return self._d[k]

    def add(self, k, v):
        with self._lock:
            cur = int(self._d.get(k, b"0")) + int(v)
            self._d[k] = str(cur).encode()
            return cur

    def check(self, keys):
        with self._lock:
            return all(k in self._d for k in keys)

    def wait(self, keys, *a):
        deadline = time.monotonic() + 0.2
        while time.monotonic() < deadline:
            if self.check(keys):
                return
            time.sleep(0.01)
        raise RuntimeError(f"wait timeout {keys}")

    def delete_key(self, k):
        with self._lock:
            return self._d.pop(k, None) is not None

    def keys(self):
        with self._lock:
            return list(self._d)


def _mk_plane(store, my_id, rank=None, **kw):
    kw.setdefault("io_timeout_s", 2.0)
    kw.setdefault("ping_s", 0.2)
    return tp.SocketTransport(
        store, my_id=my_id, addr_key=lambda p: f"tpaddr/{p}",
        rank=rank, **kw,
    )


# ---------------------------------------------------------------------------
# CGX_FAULTS network grammar
# ---------------------------------------------------------------------------


def test_net_fault_grammar():
    specs = {
        s.mode: s for s in faults.parse_faults(
            "conn_reset:400ms@rank=1,partial_write,"
            "slow_link:200ms@edge=tcp,partition:1s@ranks=0,1"
        )
    }
    assert set(specs) == set(faults.NET_MODES)
    assert specs["conn_reset"].delay_ms == 400.0
    assert specs["conn_reset"].rank == 1
    # An ungated partial_write would truncate EVERY frame: defaults to
    # the first send event.
    assert specs["partial_write"].step == 0
    # slow_link IS an edge fault — the edge defaults even unspelled.
    assert faults.parse_faults("slow_link:200ms")[0].edge == "tcp"
    assert specs["partition"].ranks == (0, 1)
    assert specs["partition"].delay_ms == 1000.0


@pytest.mark.parametrize(
    "raw",
    [
        "conn_reset",  # window modes need a duration
        "slow_link@edge=tcp",
        "partition:10s",  # partition needs endpoints
        "partition:10s@ranks=0,1,2",  # exactly two
        "partition@ranks=0,1",  # and a duration
        "slow_link:200ms@edge=dcn",  # tcp-only edge
        "conn_reset:1s@ranks=0,1",  # ranks= is partition-only
    ],
)
def test_net_fault_grammar_rejects(raw):
    with pytest.raises(ValueError):
        faults.parse_faults(raw)


def test_partition_window_gates_on_pair(monkeypatch):
    monkeypatch.setenv("CGX_FAULTS", "partition:10s@ranks=0,1")
    inj0 = faults.get_injector(0)
    inj2 = faults.get_injector(2)
    assert inj0.window("partition", peer=1)  # opens + holds
    assert inj0.window("partition", peer=1)
    assert not inj0.window("partition", peer=2)  # wrong pair
    assert not inj2.window("partition", peer=3)  # rank outside the pair
    assert not inj0.window("conn_reset")  # un-specced mode


def test_conn_reset_window_expires(monkeypatch):
    monkeypatch.setenv("CGX_FAULTS", "conn_reset:100ms")
    inj = faults.get_injector(0)
    assert inj.window("conn_reset")
    time.sleep(0.15)
    assert not inj.window("conn_reset")


# ---------------------------------------------------------------------------
# SocketTransport plane pair (unit)
# ---------------------------------------------------------------------------


def test_socket_roundtrip_bit_identical():
    store = FakeStore()
    a = _mk_plane(store, "0")
    b = _mk_plane(store, "1")
    try:
        small = b"\x00\x01hello\xff"
        big = bytes(os.urandom(1 << 20))
        a.post("k/small", small, to=["1"])
        a.post("k/big", big, to=["1"])
        assert b.fetch("k/small", timeout_s=5.0) == small
        assert b.fetch("k/big", timeout_s=5.0) == big
        # Mailbox entries pop on fetch — a second fetch times out.
        with pytest.raises(tp.TransportTimeout):
            b.fetch("k/small", timeout_s=0.3)
        snap = metrics.snapshot()
        assert snap.get("cgx.transport.posts", 0) >= 2
        assert snap.get("cgx.transport.frames_rx", 0) >= 2
        assert snap.get("cgx.transport.link_down", 0) == 0
    finally:
        a.close()
        b.close()


def test_fetch_bounded_and_abortable():
    store = FakeStore()
    b = _mk_plane(store, "9")
    try:
        t0 = time.monotonic()
        with pytest.raises(tp.TransportTimeout) as ei:
            b.fetch("never/posted", timeout_s=0.3)
        assert time.monotonic() - t0 < 2.0  # bounded, not a hang
        assert "never/posted" in str(ei.value)

        class Poison(RuntimeError):
            pass

        def boom():
            raise Poison("aborted")

        with pytest.raises(Poison):
            b.fetch("never/posted", timeout_s=5.0, abort_check=boom)
    finally:
        b.close()


def test_fetch_store_fallback_probe():
    """A key only the plain store has (a degraded WRITER's flush) is
    still delivered by the dual-probe fetch."""
    store = FakeStore()
    b = _mk_plane(store, "9")
    try:
        store.set("deg/key", b"from-the-store")
        assert b.fetch("deg/key", timeout_s=5.0) == b"from-the-store"
        assert b.poll("deg/key")  # store side of poll
        assert metrics.snapshot().get("cgx.transport.store_fetches", 0) >= 1
    finally:
        b.close()


def test_conn_reset_replay_bit_identical(monkeypatch):
    """A reconnect ladder that outlasts the reset window replays the
    resend ring: same seq, same bytes, no degrade."""
    monkeypatch.setenv("CGX_FAULTS", "conn_reset:300ms@rank=0")
    store = FakeStore()
    a = _mk_plane(store, "0", rank=0, retries=20, backoff_ms=50)
    b = _mk_plane(store, "1", rank=1)
    try:
        payload = bytes(os.urandom(64 * 1024))
        a.post("replay/k0", payload, to=["1"])
        assert b.fetch("replay/k0", timeout_s=15.0) == payload
        lk = a.link("1")
        deadline = time.monotonic() + 5.0
        while (
            lk.resends < 1 and lk.reconnects < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        assert lk.state == tp._ST_CONNECTED
        assert lk.resends >= 1 or lk.reconnects >= 1, lk.snapshot()
        snap = metrics.snapshot()
        assert snap.get("cgx.transport.link_down", 0) == 0
        assert snap.get("cgx.transport.degraded_posts", 0) == 0
        # After the window: plain traffic flows on the same link.
        a.post("replay/k1", b"post-window", to=["1"])
        assert b.fetch("replay/k1", timeout_s=10.0) == b"post-window"
    finally:
        a.close()
        b.close()


def test_partition_degrades_to_store(monkeypatch):
    """An exhausted ladder degrades the edge: the ring flushes to the
    store under the SAME keys with bit-identical bytes, the reader's
    store probe delivers, and the health callback names the peer."""
    monkeypatch.setenv("CGX_FAULTS", "partition:30s@ranks=0,1")
    store = FakeStore()
    downs = []
    a = _mk_plane(
        store, "0", rank=0, retries=2, backoff_ms=20, io_timeout_s=0.5,
        on_link_down=lambda peer, peer_rank: downs.append(
            (peer, peer_rank)
        ),
    )
    b = _mk_plane(store, "1", rank=1)
    try:
        payload = bytes(os.urandom(4096))
        a.post("part/k0", payload, to=["1"])
        assert b.fetch("part/k0", timeout_s=15.0) == payload
        deadline = time.monotonic() + 10.0
        while a.down_peers() != ["1"] and time.monotonic() < deadline:
            time.sleep(0.05)
        assert a.down_peers() == ["1"]
        assert downs == [("1", 1)]
        snap = metrics.snapshot()
        assert snap.get("cgx.transport.link_down", 0) >= 1
        assert snap.get("cgx.transport.degraded_posts", 0) >= 1
        # Degraded edge: later posts go straight to the store path,
        # same key, same bytes.
        a.post("part/k1", b"still-delivered", to=["1"])
        assert b.fetch("part/k1", timeout_s=10.0) == b"still-delivered"
        assert store.get("part/k1") == b"still-delivered"
    finally:
        a.close()
        b.close()


def test_partial_write_torn_frame_resent(monkeypatch):
    """A torn first frame (header+body truncated mid-wire) is discarded
    by the receiver and redelivered intact by the replay."""
    monkeypatch.setenv("CGX_FAULTS", "partial_write")
    store = FakeStore()
    a = _mk_plane(store, "0", rank=0, retries=10, backoff_ms=30)
    b = _mk_plane(store, "1", rank=1, io_timeout_s=0.5)
    try:
        payload = bytes(os.urandom(32 * 1024))
        a.post("torn/k0", payload, to=["1"])
        assert b.fetch("torn/k0", timeout_s=15.0) == payload
        # The replay's ``resends`` bump races the delivery by a few
        # instructions (sender-thread bookkeeping) — poll briefly.
        lk = a.link("1")
        deadline = time.monotonic() + 5.0
        while lk.resends < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert lk.resends >= 1, lk.snapshot()
        assert metrics.snapshot().get("cgx.transport.link_down", 0) == 0
    finally:
        a.close()
        b.close()


def test_status_snapshot_shape():
    store = FakeStore()
    a = _mk_plane(store, "0")
    try:
        a.post("s/k", b"x", to=["1", "2"])
        rows = a.status()
        assert {r["peer"] for r in rows} == {"1", "2"}
        for r in rows:
            for col in (
                "state", "unacked", "queued", "reconnects", "resends",
                "last_send_age_s", "last_ack_age_s",
            ):
                assert col in r
    finally:
        a.close()


# ---------------------------------------------------------------------------
# TransportStore shim + identity pin
# ---------------------------------------------------------------------------


class _FakePlane:
    def __init__(self):
        self.posts = []
        self.box = {}

    def post(self, key, payload, to=()):
        self.posts.append((key, bytes(payload), tuple(to)))
        self.box[key] = bytes(payload)

    def poll(self, key):
        return key in self.box

    def fetch(self, key, timeout_s, abort_check=None, peer=None):
        if key not in self.box:
            raise tp.TransportTimeout(key, timeout_s)
        return self.box.pop(key)


def test_transport_store_routing_and_exclude():
    base = FakeStore()
    plane = _FakePlane()
    ts = tp.TransportStore(
        base, plane, peers=("rx",), prefixes=("cgxkv/s1/",),
        fetch_timeout_s=1.0, exclude=("/rereq/",),
    )
    # Routed payload key: framed post toward the construction peers,
    # never the base store.
    ts.set("cgxkv/s1/0001", b"page")
    assert plane.posts == [("cgxkv/s1/0001", b"page", ("rx",))]
    assert "cgxkv/s1/0001" not in base.keys()
    assert ts.check(["cgxkv/s1/0001"])
    assert bytes(ts.get("cgxkv/s1/0001")) == b"page"
    # Excluded control key under the routed prefix: plain store (its
    # reader set differs from the page stream's peers).
    ts.set("cgxkv/s1/rereq/0", b"3")
    assert plane.posts[1:] == []
    assert base.get("cgxkv/s1/rereq/0") == b"3"
    # Un-prefixed keys and counters pass through untouched.
    ts.set("other/key", b"v")
    assert base.get("other/key") == b"v"
    assert ts.add("cgxkv/s1/n", 2) == 2
    assert int(base.get("cgxkv/s1/n")) == 2
    # Routed delete is a no-op (mailbox pops on fetch).
    assert ts.delete_key("cgxkv/s1/0002") is True
    assert ts.delete_key("other/key") is True
    assert "other/key" not in base.keys()


def test_maybe_wrap_store_identity_pin(monkeypatch):
    """CGX_TRANSPORT unset (or any non-socket mode): the wrap is the
    identity — no plane, no address key, no behavioural delta."""
    base = FakeStore()
    for mode in (None, "", "store", "shm", "auto"):
        if mode is None:
            monkeypatch.delenv("CGX_TRANSPORT", raising=False)
        else:
            monkeypatch.setenv("CGX_TRANSPORT", mode)
        assert tp.maybe_wrap_store(
            base, endpoint="e", peers=("p",), prefixes=("cgxkv/",)
        ) is base
        assert base.keys() == []
    from torch_cgx_tpu.serving import transport as serving_tp

    monkeypatch.delenv("CGX_TRANSPORT", raising=False)
    assert serving_tp.maybe_socket_store(base, endpoint="kvrx") is base


def test_transport_mode_rejects_junk(monkeypatch):
    monkeypatch.setenv("CGX_TRANSPORT", "carrier-pigeon")
    with pytest.raises(ValueError):
        cfg.transport_mode()


def test_maybe_wrap_store_socket_roundtrip(monkeypatch):
    monkeypatch.setenv("CGX_TRANSPORT", "socket")
    base = FakeStore()
    rx = tp.maybe_wrap_store(
        base, endpoint="rx", peers=(), prefixes=("cgxkv/s/",),
        fetch_timeout_s=5.0,
    )
    txs = tp.maybe_wrap_store(
        base, endpoint="tx", peers=("rx",), prefixes=("cgxkv/s/",),
        fetch_timeout_s=5.0,
    )
    try:
        assert isinstance(rx, tp.TransportStore)
        payload = bytes(os.urandom(8192))
        txs.set("cgxkv/s/0", payload)
        assert bytes(rx.get("cgxkv/s/0")) == payload
        assert "cgxkv/s/0" not in base.keys()
        # The publish-after-write counters still live on the real store.
        txs.add("cgxkv/s/n", 1)
        assert rx.add("cgxkv/s/n", 0) == 1
    finally:
        txs.transport_plane.close()
        rx.transport_plane.close()


# ---------------------------------------------------------------------------
# Cross-host store-counter liveness (satellite 1)
# ---------------------------------------------------------------------------


def test_remote_liveness_convicts_stalled_counter():
    store = FakeStore()
    live_pid, dead_pid = 11111, 22222
    store.add(hb.store_heartbeat_key(live_pid), 1)
    store.add(hb.store_heartbeat_key(dead_pid), 1)
    judge = hb.RemoteLiveness(store, stale_s=0.15)
    # First probe can never convict: the judge needs its own history.
    assert judge.suspects([live_pid, dead_pid]) == []
    for _ in range(4):
        time.sleep(0.06)
        store.add(hb.store_heartbeat_key(live_pid), 1)  # keeps advancing
        judge.observe([live_pid, dead_pid])
    assert judge.suspects([live_pid, dead_pid]) == [dead_pid]
    assert (
        metrics.snapshot().get("cgx.heartbeat.remote_suspect_checks", 0)
        >= 1
    )


def test_attach_store_publishes_and_is_idempotent(tmp_path):
    store = FakeStore()
    hb.attach_store(str(tmp_path), store)
    key = hb.store_heartbeat_key(os.getpid())
    first = int(store.get(key))  # first bump lands before any wait
    assert first >= 1
    hb.attach_store(str(tmp_path), store)  # same store object: no dup
    deadline = time.monotonic() + 3.0
    while int(store.get(key)) == first and time.monotonic() < deadline:
        time.sleep(0.1)
    assert int(store.get(key)) > first  # the shared ticker advances it


def test_two_hosts_liveness_regression(tmp_path):
    """Two 'hosts' (distinct heartbeat dirs) sharing one store: the
    file-mtime judge can't see across, the counter judge can — and only
    convicts the host whose ticker stopped."""
    store = FakeStore()
    host_a, host_b = tmp_path / "a", tmp_path / "b"
    host_a.mkdir(), host_b.mkdir()
    pid_b = 54321

    class _B:
        """Host B's publisher, hand-cranked so the test can stop it."""

        def tick(self):
            store.add(hb.store_heartbeat_key(pid_b), 1)

    b = _B()
    b.tick()
    # Host A's real heartbeat publishes through the store.
    hb.attach_store(str(host_a), store)
    pid_a = os.getpid()
    judge = hb.RemoteLiveness(store, stale_s=0.3)
    judge.observe([pid_a, pid_b])
    for _ in range(5):
        time.sleep(0.1)
        b.tick()
        judge.observe([pid_a, pid_b])
    assert judge.suspects([pid_a, pid_b]) == []  # both alive
    # Host B stops ticking; host A's shared ticker keeps its counter
    # advancing — only B converts to a suspect.
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        time.sleep(0.1)
        if judge.suspects([pid_a, pid_b]) == [pid_b]:
            break
    assert judge.suspects([pid_a, pid_b]) == [pid_b]


# ---------------------------------------------------------------------------
# Bridge tests: the real "cgx" backend over the socket plane.
# ---------------------------------------------------------------------------


def _bridge_main(rank, ws, initfile, body_name, env, q):
    """Fresh-spawn bootstrap: CGX_* env must be set BEFORE backend
    construction (the transport engages at init_process_group time), so
    these tests cannot ride test_torch_backend's persistent pool."""
    sys.path.insert(0, _REPO)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.update(env)
    payload = None
    try:
        import torch.distributed as dist
        import torch_cgx_tpu.torch_backend  # noqa: F401

        dist.init_process_group(
            "cgx", init_method=f"file://{initfile}", rank=rank,
            world_size=ws,
        )
        payload = globals()[body_name](rank, ws)
        err = None
    except Exception:
        err = traceback.format_exc()
    finally:
        try:
            import torch.distributed as dist

            dist.destroy_process_group()
        except Exception:
            pass
        q.put((rank, err, payload))


def _run_bridge(body, ws, env, timeout=180.0, expect_dead=()):
    """Spawn ``ws`` fresh ranks; returns {rank: payload}. Ranks listed
    in ``expect_dead`` may die without reporting (SIGKILL chaos)."""
    import multiprocessing as mp

    initfile = tempfile.mktemp(prefix="cgx_tp_store_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(
            target=_bridge_main,
            args=(r, ws, initfile, body.__name__, dict(env), q),
        )
        for r in range(ws)
    ]
    for p in procs:
        p.start()
    errors, payloads = [], {}
    for _ in range(ws - len(expect_dead)):
        try:
            rank, err, payload = q.get(timeout=timeout)
        except Exception:
            errors.append("timeout waiting for a rank (hang?)")
            break
        if err is not None:
            errors.append(f"rank {rank}:\n{err}")
        payloads[rank] = payload
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
            p.join(timeout=10)
    if os.path.exists(initfile):
        os.unlink(initfile)
    assert not errors, "\n".join(errors)
    return payloads


def _body_collectives(rank, ws):
    """A few collectives whose results travel back for cross-mode
    bit-comparison."""
    import torch
    import torch.distributed as dist

    out = {}
    t = torch.arange(4096, dtype=torch.float32) * (rank + 1) / 7.0
    dist.all_reduce(t)
    out["allreduce"] = t.numpy().tobytes()
    b = torch.arange(512, dtype=torch.float32) * (rank * 3 + 1)
    dist.broadcast(b, src=0)
    out["broadcast"] = b.numpy().tobytes()
    gs = [torch.zeros(128) for _ in range(ws)]
    dist.all_gather(gs, torch.full((128,), float(rank + 1) / 3.0))
    out["allgather"] = b"".join(g.numpy().tobytes() for g in gs)
    dist.barrier()
    from torch_cgx_tpu.utils.logging import metrics as m

    out["metrics"] = {
        k: v for k, v in m.snapshot().items()
        if k.startswith("cgx.transport.")
    }
    return out


@pytest.mark.torch_bridge
def test_socket_bridge_bit_identical_vs_store_ws2():
    """CGX_TRANSPORT=socket produces byte-identical collective results
    to the legacy store path — and actually rides the socket plane."""
    legacy = _run_bridge(_body_collectives, 2, {"CGX_SHM": "0"})
    socketed = _run_bridge(
        _body_collectives, 2,
        {"CGX_SHM": "0", "CGX_TRANSPORT": "socket"},
    )
    for rank in (0, 1):
        for op in ("allreduce", "broadcast", "allgather"):
            assert socketed[rank][op] == legacy[rank][op], (rank, op)
        assert legacy[rank]["metrics"].get("cgx.transport.posts", 0) == 0
        assert socketed[rank]["metrics"].get("cgx.transport.posts", 0) > 0


def _body_conn_reset_soak(rank, ws):
    import torch
    import torch.distributed as dist

    for step in range(6):
        t = torch.full((2048,), float(rank + 1 + step))
        dist.all_reduce(t)
        want = float(sum(r + 1 + step for r in range(ws)))
        assert torch.equal(t, torch.full((2048,), want)), (step, t[:4])
    dist.barrier()
    from torch_cgx_tpu.utils.logging import metrics as m

    snap = m.snapshot()
    return {
        k: snap.get(k, 0)
        for k in (
            "cgx.transport.reconnects", "cgx.transport.resends",
            "cgx.transport.link_down", "cgx.transport.conn_errors",
        )
    }


@pytest.mark.torch_bridge
@pytest.mark.faults
def test_conn_reset_chaos_replays_bit_identical_ws2():
    """A 400 ms reset window on rank 0 with a ladder that outlasts it:
    the soak completes bit-identical via ring replay — no degrade."""
    payloads = _run_bridge(
        _body_conn_reset_soak, 2,
        {
            "CGX_SHM": "0",
            "CGX_TRANSPORT": "socket",
            "CGX_FAULTS": "conn_reset:400ms@rank=0",
            "CGX_TRANSPORT_RETRIES": "12",
            "CGX_TRANSPORT_BACKOFF_MS": "40",
        },
    )
    hit = payloads[0]
    assert hit["cgx.transport.conn_errors"] >= 1, hit
    assert (
        hit["cgx.transport.reconnects"] + hit["cgx.transport.resends"]
    ) >= 1, hit
    for rank in (0, 1):
        assert payloads[rank]["cgx.transport.link_down"] == 0, payloads


def _body_partition_degrade(rank, ws):
    import time as _t

    import torch
    import torch.distributed as dist

    steps = []
    for step in range(3):
        t0 = _t.monotonic()
        t = torch.full((1024,), float(rank + 1))
        dist.all_reduce(t)
        steps.append(_t.monotonic() - t0)
        want = float(sum(r + 1 for r in range(ws)))
        assert torch.equal(t, torch.full((1024,), want)), (step, t[:4])
    dist.barrier()
    from torch_cgx_tpu.utils.logging import metrics as m

    snap = m.snapshot()
    return {
        "steps_s": steps,
        "link_down": snap.get("cgx.transport.link_down", 0),
        "degraded_posts": snap.get("cgx.transport.degraded_posts", 0),
        "bridge_timeouts": snap.get("cgx.bridge_timeout", 0),
    }


@pytest.mark.torch_bridge
@pytest.mark.faults
def test_partition_degrades_before_bridge_timeout_ws2():
    """A 60 s partition across the only edge: the ladder exhausts in
    well under CGX_BRIDGE_TIMEOUT_MS, the edge degrades to the store
    (link_down fires), and training CONTINUES — no unbounded stall,
    no timeout error."""
    bridge_timeout_s = 20.0
    payloads = _run_bridge(
        _body_partition_degrade, 2,
        {
            "CGX_SHM": "0",
            "CGX_TRANSPORT": "socket",
            "CGX_FAULTS": "partition:60s@ranks=0,1",
            "CGX_TRANSPORT_RETRIES": "2",
            "CGX_TRANSPORT_BACKOFF_MS": "20",
            "CGX_TRANSPORT_IO_TIMEOUT_MS": "500",
            "CGX_BRIDGE_TIMEOUT_MS": str(int(bridge_timeout_s * 1000)),
        },
    )
    assert sum(p["link_down"] for p in payloads.values()) >= 1, payloads
    assert sum(p["degraded_posts"] for p in payloads.values()) >= 1
    for rank, p in payloads.items():
        assert p["bridge_timeouts"] == 0, (rank, p)
        # Degrade is detection, not a timeout: every step lands
        # strictly inside the bridge window.
        assert max(p["steps_s"]) < bridge_timeout_s, (rank, p)


def _body_sigkill_eviction(rank, ws):
    import signal

    import torch
    import torch.distributed as dist

    dist.barrier()
    if rank == 1:
        os.kill(os.getpid(), signal.SIGKILL)
    t = torch.full((256,), 1.0)
    try:
        dist.all_reduce(t)
    except RuntimeError as e:
        msg = str(e)
        assert "timed out" in msg, msg
        return {"error": msg}
    raise AssertionError("expected a bridge timeout")


@pytest.mark.torch_bridge
@pytest.mark.faults
def test_sigkill_peer_named_timeout_under_socket_ws2():
    """A SIGKILL'd peer under CGX_TRANSPORT=socket surfaces exactly as
    on the store path: a bounded BridgeTimeoutError — with the dead
    rank named via the degraded transport edge."""
    payloads = _run_bridge(
        _body_sigkill_eviction, 2,
        {
            "CGX_SHM": "0",
            "CGX_TRANSPORT": "socket",
            "CGX_TRANSPORT_RETRIES": "2",
            "CGX_TRANSPORT_BACKOFF_MS": "20",
            "CGX_TRANSPORT_IO_TIMEOUT_MS": "500",
            "CGX_BRIDGE_TIMEOUT_MS": "4000",
        },
        expect_dead=(1,),
    )
    msg = payloads[0]["error"]
    assert "socket transport" in msg, msg
    assert "suspected dead peer rank(s): [1]" in msg, msg


def _body_cross_host_heartbeat(rank, ws):
    import signal

    import torch
    import torch.distributed as dist

    dist.barrier()
    if rank == 1:
        os.kill(os.getpid(), signal.SIGKILL)
    t = torch.full((256,), 1.0)
    try:
        dist.all_reduce(t)
    except RuntimeError as e:
        msg = str(e)
        assert "timed out" in msg, msg
        return {"error": msg}
    raise AssertionError("expected a bridge timeout")


@pytest.mark.torch_bridge
@pytest.mark.faults
def test_two_hosts_heartbeat_names_dead_peer_ws2(tmp_path):
    """Two 'hosts' (distinct CGX_SHM_HOST_ID + heartbeat dirs): the
    file-mtime judge is blind across hosts, so naming the SIGKILL'd
    peer proves the store-counter liveness path (satellite 1). The
    recovery retry gives the counter judge the observation history a
    conviction needs."""
    dirs = [tmp_path / "hostA", tmp_path / "hostB"]
    for d in dirs:
        d.mkdir()
    env = {
        "CGX_BRIDGE_TIMEOUT_MS": "2600",
        "CGX_RECOVERY_RETRIES": "2",
        "CGX_RECOVERY_BACKOFF_MS": "100",
    }
    import multiprocessing as mp

    initfile = tempfile.mktemp(prefix="cgx_tp_store_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = []
    for r in range(2):
        renv = dict(env)
        renv["CGX_SHM_HOST_ID"] = f"host{'AB'[r]}"
        renv["CGX_SHM_DIR"] = str(dirs[r])
        procs.append(
            ctx.Process(
                target=_bridge_main,
                args=(
                    r, 2, initfile, "_body_cross_host_heartbeat", renv, q,
                ),
            )
        )
    for p in procs:
        p.start()
    rank, err, payload = q.get(timeout=180)
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    if os.path.exists(initfile):
        os.unlink(initfile)
    assert err is None, f"rank {rank}:\n{err}"
    assert rank == 0
    assert "suspected dead peer rank(s): [1]" in payload["error"], payload


# ---------------------------------------------------------------------------
# Operator surfaces: cgx_top link column + cgx_report transport section.
# ---------------------------------------------------------------------------


def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"tp_test_{name}", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cgx_top_link_column(tmp_path):
    import json

    cgx_top = _load_tool("cgx_top")
    with open(tmp_path / "metrics-rank0.jsonl", "w") as f:
        f.write(json.dumps({
            "ts": 1000.0,
            "counters": {"cgx.transport.frames_tx": 12.0,
                         "cgx.transport.reconnects": 2.0},
            "gauges": {}, "histograms": {},
        }) + "\n")
    frame = cgx_top.render(str(tmp_path), {})
    assert "link" in frame
    assert "ok+r2" in frame
    # a degraded edge flips the cell to degN
    with open(tmp_path / "metrics-rank0.jsonl", "a") as f:
        f.write(json.dumps({
            "ts": 1002.0,
            "counters": {"cgx.transport.frames_tx": 20.0,
                         "cgx.transport.link_down": 1.0},
            "gauges": {"cgx.transport.degraded_edges": 1.0},
            "histograms": {},
        }) + "\n")
    assert "deg1" in cgx_top.render(str(tmp_path), {})
    # transport off (no cgx.transport.* traffic) renders '-'
    off = tmp_path / "off"
    off.mkdir()
    with open(off / "metrics-rank0.jsonl", "w") as f:
        f.write(json.dumps({
            "ts": 1000.0, "counters": {"cgx.step.count": 1.0},
            "gauges": {}, "histograms": {},
        }) + "\n")
    line = [
        ln for ln in cgx_top.render(str(off), {}).splitlines()
        if ln.strip().startswith("0 ")
    ]
    assert line, "rank row missing"


def test_cgx_report_transport_section(tmp_path):
    import json

    cgx_report = _load_tool("cgx_report")
    with open(tmp_path / "flightrec-rank0.jsonl", "w") as f:
        f.write(json.dumps({
            "kind": "transport_link_down", "peer": "1",
            "why": "retries exhausted", "flushed": 3, "retries": 2,
            "ts": 10.0,
        }) + "\n")
        f.write(json.dumps({
            "kind": "transport_reconnect", "peer": "1", "replay": 2,
            "ts": 5.0,
        }) + "\n")
    with open(tmp_path / "metrics-rank0.jsonl", "w") as f:
        f.write(json.dumps({
            "ts": 1000.0,
            "counters": {"cgx.transport.posts": 7.0,
                         "cgx.transport.frames_tx": 9.0,
                         "cgx.transport.frames_rx": 4.0,
                         "cgx.transport.bytes_tx": 2e6,
                         "cgx.transport.bytes_rx": 1e6,
                         "cgx.transport.resends": 2.0,
                         "cgx.transport.reconnects": 1.0,
                         "cgx.transport.link_down": 1.0,
                         "cgx.transport.degraded_posts": 3.0},
            "gauges": {"cgx.transport.degraded_edges": 1.0},
            "histograms": {},
        }) + "\n")
    summary = cgx_report.summarize(cgx_report.load_dir(str(tmp_path)))
    t = summary["transport"]
    assert t["posts"] == 7 and t["frames_tx"] == 9
    assert t["degraded_edges"] == 1 and t["degraded_posts"] == 3
    # events sorted by ts: reconnect (5.0) before link_down (10.0)
    assert [e["kind"] for e in t["events"]] == ["reconnect", "link_down"]
    # the gauge is a level — it must NOT leak into the summed counters
    assert "cgx.transport.degraded_edges" not in summary["counters"]
    text = cgx_report.render(summary)
    assert "== transport (supervised socket data plane) ==" in text
    assert "DEGRADED edges: 1" in text
    assert "retries exhausted" in text
    # a dir with no transport traffic has no transport section
    empty = tmp_path / "empty"
    empty.mkdir()
    s2 = cgx_report.summarize(cgx_report.load_dir(str(empty)))
    assert "transport" not in s2
