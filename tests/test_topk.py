"""Top-K gradient sparsification (parallel/topk.py).

No reference counterpart (its compressor hierarchy is max-min + dummy,
compressor.h:130,145); oracles are analytic: exact reduction whenever k
covers every device's support, EF carrying exactly the unshipped
complement (and catching up the next step), exact psum for ineligible
leaves, and replica bit-identity."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from torch_cgx_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from torch_cgx_tpu.parallel import (
    TopKState,
    flat_mesh,
    init_topk,
    init_topk_state,
    make_train_step,
    replicate,
    shard_batch,
    topk_transform,
)
from torch_cgx_tpu.parallel.topk import _k_for, eligible

WS = 8


def _run_tx(per_rank_tree, ratio=0.125, steps=1, average=True):
    """Apply the transform `steps` times to per-rank gradient trees.
    Returns (per-device reduced stacks, per-device es stack of the first
    eligible leaf or None)."""
    mesh = flat_mesh()
    trees = (
        per_rank_tree
        if isinstance(per_rank_tree, list)
        else [per_rank_tree] * WS
    )
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *trees)
    specs = jax.tree.map(lambda _: P("dp"), stacked)
    tx = topk_transform(mesh=mesh, ratio=ratio, average=average)

    def run(local):
        local = jax.tree.map(lambda l: l[0], local)
        state = tx.init(local)
        red = None
        for _ in range(steps):
            red, state = tx.update(local, state)
        e0 = next((e for e in state.es if e is not None), None)
        return (
            jax.tree.map(lambda l: l[None], red),
            None if e0 is None else e0[None],
        )

    out, es = jax.jit(
        shard_map(
            run, mesh=mesh, in_specs=(specs,),
            out_specs=(specs, P("dp")), check_vma=False,
        )
    )(jax.device_put(stacked, NamedSharding(mesh, P("dp"))))
    return jax.tree.map(lambda l: np.asarray(l), out), (
        None if es is None else np.asarray(es)
    )


def test_exact_when_k_covers_support():
    """Every device's gradient has <= k nonzeros: the sparse allreduce is
    the exact mean (extra picks ship zeros, which add nothing) and every
    residual is exactly zero."""
    # ratio under the ws-aware receive gate (8*k*ws < 2*n*4*(ws-1)/ws
    # at ws=8 needs k/n < ~0.109): 0.0625 keeps the leaf eligible.
    n, ratio = 512, 0.0625  # k = 32
    k = _k_for(n, ratio)
    rng = np.random.default_rng(0)
    trees = []
    dense_sum = np.zeros(n, np.float32)
    for r in range(WS):
        g = np.zeros(n, np.float32)
        pos = rng.choice(n, size=k // 2, replace=False)
        g[pos] = rng.normal(size=k // 2).astype(np.float32) + (r + 1)
        dense_sum += g
        trees.append({"w": jnp.asarray(g)})
    out, es = _run_tx(trees, ratio=ratio)
    for r in range(WS):
        np.testing.assert_allclose(
            out["w"][r], dense_sum / WS, rtol=1e-6, atol=1e-7
        )
    np.testing.assert_array_equal(es, np.zeros_like(es))


def test_ef_carries_complement_and_catches_up():
    """Identical gradients on every rank: step 1 ships the k largest
    coordinates (residual = the complement, exactly), and because EF
    re-feeds the complement, two steps ship the 2k largest — the dropped
    mass drains instead of being lost."""
    n, ratio = 512, 0.0625  # k = 32
    k = _k_for(n, ratio)
    rng = np.random.default_rng(1)
    g = rng.normal(size=n).astype(np.float32)
    tree = {"w": jnp.asarray(g)}

    out1, es1 = _run_tx(tree, ratio=ratio, steps=1)
    order = np.argsort(-np.abs(g))
    top, rest = order[:k], order[k:]
    expect = np.zeros(n, np.float32)
    expect[top] = g[top]
    np.testing.assert_allclose(out1["w"][0], expect, rtol=1e-6, atol=1e-7)
    resid = np.zeros(n, np.float32)
    resid[rest] = g[rest]
    np.testing.assert_allclose(es1[0], resid, rtol=1e-6, atol=1e-7)

    # Step 2 re-feeds the complement, so unshipped coordinates enter with
    # DOUBLE weight (M2 = g + tail(g)) and compete against the already-
    # drained top — simulate the exact EF dynamics as the oracle.
    def simulate(steps):
        e = np.zeros_like(g)
        for _ in range(steps):
            m = g + e
            idx = np.argsort(-np.abs(m), kind="stable")[:k]
            e = m.copy()
            e[idx] = 0.0
        return e

    _, es2 = _run_tx(tree, ratio=ratio, steps=2)
    np.testing.assert_allclose(es2[0], simulate(2), rtol=1e-6, atol=1e-7)


def test_ineligible_leaf_exact_psum():
    """A tiny leaf (below the minimal size) rides an exact averaged psum
    and keeps no residual."""
    trees = [
        {"b": jnp.full((8,), float(r + 1), jnp.float32)} for r in range(WS)
    ]
    out, es = _run_tx(trees, ratio=0.125)
    assert es is None
    np.testing.assert_allclose(
        out["b"][0], np.full(8, np.mean(np.arange(1, WS + 1)), np.float32)
    )


def test_replica_bit_identity():
    """Different gradients per rank: the reconstruction is computed from
    all_gathered pairs every device sees identically, so outputs are
    bit-identical across devices."""
    rng = np.random.default_rng(2)
    trees = [
        {"w": jnp.asarray(rng.normal(size=512).astype(np.float32))}
        for _ in range(WS)
    ]
    out, _ = _run_tx(trees, ratio=0.125)
    for r in range(1, WS):
        np.testing.assert_array_equal(out["w"][r], out["w"][0])


def test_eligibility_and_validation():
    assert eligible(jnp.zeros((512,), jnp.float32), 0.01)
    assert not eligible(jnp.zeros((8,), jnp.float32), 0.01)
    assert not eligible(jnp.zeros((512,), jnp.int32), 0.01)
    assert not eligible(jnp.zeros((64,), jnp.float32), 0.9)  # pairs >= dense
    # byte-aware: a pair costs 8 bytes whatever the leaf dtype, so bf16
    # leaves (2 bytes dense) need ratio < 1/4 where f32 needs < 1/2
    assert eligible(jnp.zeros((512,), jnp.bfloat16), 0.2)
    assert not eligible(jnp.zeros((512,), jnp.bfloat16), 0.3)
    mesh = flat_mesh()
    with pytest.raises(ValueError, match="ratio"):
        topk_transform(mesh=mesh, ratio=1.5)
    tx = topk_transform(mesh=mesh, ratio=0.1)
    state = tx.init({"w": jnp.zeros((512,), jnp.float32)})
    with pytest.raises(ValueError, match="different parameter tree"):
        tx.update({"a": jnp.zeros((512,)), "b": jnp.zeros((512,))}, state)


def test_eligibility_world_size_aware():
    """The receive-side gate (advisor r5 low #1): the all_gather delivers
    ws*k pairs per rank, so a ratio that passes the send gate can still
    move more traffic than the ~2*n*itemsize dense allreduce receive at
    large world sizes — eligibility must tighten with ws."""
    leaf = jnp.zeros((4096,), jnp.float32)
    ratio = 0.2  # k = 820: send 8k < 4n passes the ws-blind gate
    assert eligible(leaf, ratio)  # ws=1 default: old behavior preserved
    assert eligible(leaf, ratio, ws=2)  # rx 2*8k=13k < 2*4n*(1/2)=16k
    # ws=8: rx = 8*8*820 = 52k bytes vs dense 2*4n*(7/8) = 28k — sparse
    # would RECEIVE ~2x the dense traffic; the gate must refuse.
    assert not eligible(leaf, ratio, ws=8)
    # a genuinely sparse ratio stays eligible at any realistic ws
    assert eligible(leaf, 0.01, ws=64)
    # init plumbs ws through: the same leaf flips from eligible to psum
    assert init_topk({"w": leaf}, ratio, ws=2).es[0] is not None
    assert init_topk({"w": leaf}, ratio, ws=8).es[0] is None


def test_make_train_step_topk_converges():
    """End-to-end: make_train_step(topk_ratio=...) trains the toy problem
    to a large loss reduction with bit-identical replicas."""
    mesh = flat_mesh()
    rng = np.random.default_rng(0)
    params = {
        "w1": jnp.asarray(rng.normal(0, 0.3, (16, 64)), jnp.float32),
        "w2": jnp.asarray(rng.normal(0, 0.3, (64, 1)), jnp.float32),
    }
    xs = jnp.asarray(rng.normal(size=(256, 16)), jnp.float32)
    ys = jnp.sin(xs.sum(axis=1, keepdims=True))

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((jnp.tanh(x @ p["w1"]) @ p["w2"] - y) ** 2)

    opt = optax.adam(3e-3)
    # 0.1 stays under the ws-aware receive gate at ws=8 for the 1024-
    # element w1 (w2 is small enough that it rides the exact psum).
    step = make_train_step(loss_fn, opt, mesh=mesh, topk_ratio=0.1)
    p = replicate(params, mesh)
    st = replicate(opt.init(params), mesh)
    tk = init_topk_state(params, mesh, 0.1)
    first = last = None
    for i in range(150):
        p, st, tk, loss = step(
            p, st, tk, shard_batch((xs, ys), mesh), jnp.int32(i)
        )
        if first is None:
            first = float(loss)
        last = float(loss)
    assert first / last > 10, (first, last)
    for leaf in jax.tree.leaves(p):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
    # the residual is alive: top-k at 10% genuinely drops mass every step
    ef_mag = max(
        float(jnp.abs(e).max()) for e in tk.es if e is not None
    )
    assert ef_mag > 0
