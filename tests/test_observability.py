"""Observability subsystem tests (ISSUE 2 tentpole + satellites).

Covers the typed instrument registry (backward-compat with the seed's
flat-counter API), the flight recorder (ring bounds, dump-on-failure
through the real shm channel under CGX_FAULTS injection), the periodic
exporter and store-riding cross-rank aggregation, the SRA/Ring counter
instrumentation on the JAX allreduce paths, the env-gated quantization
error stats, and the ``tools/cgx_report.py`` renderer — including the
acceptance chaos run: ``kill_rank`` + ``CGX_METRICS_DIR`` must leave a
dump naming the failed collective and the suspected dead rank, and the
report CLI must render it.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from torch_cgx_tpu.observability import exporter as obs_exporter
from torch_cgx_tpu.observability import flightrec, instruments, timeline
from torch_cgx_tpu.robustness import (
    BridgeTimeoutError,
    WireCorruptionError,
    faults,
)
from torch_cgx_tpu.utils.logging import metrics

from test_faults import FakeStore, _channel_pair

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _fresh():
    faults.reset_injectors()
    metrics.reset()
    flightrec.reset()
    timeline.reset()
    obs_exporter.stop_exporter()
    yield
    faults.reset_injectors()
    metrics.reset()
    flightrec.reset()
    timeline.reset()
    obs_exporter.stop_exporter()


# ---------------------------------------------------------------------------
# Instruments: typed registry behind the seed's flat API.
# ---------------------------------------------------------------------------


def test_registry_backward_compat():
    metrics.add("cgx.c")
    metrics.add("cgx.c", 2.0)
    metrics.set("cgx.g", 7.5)
    assert metrics.get("cgx.c") == 3.0
    assert metrics.get("cgx.g") == 7.5
    assert metrics.get("cgx.never") == 0.0
    snap = metrics.snapshot("cgx.")
    assert snap["cgx.c"] == 3.0 and snap["cgx.g"] == 7.5
    metrics.reset()
    assert metrics.get("cgx.c") == 0.0 and metrics.snapshot() == {}


def test_histogram_quantiles_and_flatten():
    for v in range(1, 101):
        metrics.observe("cgx.h", float(v))
    st = metrics.histogram_stats("cgx.h")
    assert st["count"] == 100 and st["sum"] == 5050.0
    assert st["min"] == 1.0 and st["max"] == 100.0
    assert 45.0 <= st["p50"] <= 56.0
    assert 85.0 <= st["p90"] <= 96.0
    snap = metrics.snapshot("cgx.h")
    assert snap["cgx.h.count"] == 100 and "cgx.h.p99" in snap
    # get() on a histogram reports its observation count
    assert metrics.get("cgx.h") == 100.0


def test_histogram_reservoir_bounded():
    h = instruments.Histogram()
    for v in range(10 * instruments.RESERVOIR):
        h.observe(float(v))
    assert h.count == 10 * instruments.RESERVOIR  # exact over all time
    assert len(h._recent) == instruments.RESERVOIR  # bounded memory
    # quantiles describe the recent window, not ancient history
    assert h.quantile(0.5) > 8 * instruments.RESERVOIR


def test_typed_snapshot_separates_instruments():
    metrics.add("cgx.c", 4.0)
    metrics.set("cgx.g", 1.0)
    metrics.observe("cgx.h", 0.25)
    t = metrics.snapshot_typed()
    assert t["counters"] == {"cgx.c": 4.0}
    assert t["gauges"] == {"cgx.g": 1.0}
    assert t["histograms"]["cgx.h"]["count"] == 1


# ---------------------------------------------------------------------------
# Satellite: trace_span must record the sample when the body raises.
# ---------------------------------------------------------------------------


def test_trace_span_records_duration_on_raise():
    from torch_cgx_tpu.utils.tracing import trace_span

    with pytest.raises(RuntimeError, match="boom"):
        with trace_span("failing_op"):
            time.sleep(0.01)
            raise RuntimeError("boom")
    assert metrics.get("span.failing_op.count") == 1.0
    assert metrics.get("span.failing_op.seconds") >= 0.01
    assert metrics.get("span.failing_op.errors") == 1.0
    assert metrics.histogram_stats("span.failing_op.duration_s")["count"] == 1
    # clean spans don't count errors
    with trace_span("clean_op"):
        pass
    assert metrics.get("span.clean_op.errors") == 0.0
    assert metrics.get("span.clean_op.count") == 1.0


# ---------------------------------------------------------------------------
# Flight recorder core.
# ---------------------------------------------------------------------------


def test_flightrec_ring_bounded_and_ordered():
    rec = flightrec.FlightRecorder(rank=0, capacity=8)
    for i in range(20):
        rec.record("tick", i=i)
    evs = rec.events()
    assert len(evs) == 8
    assert [e["i"] for e in evs] == list(range(12, 20))
    assert evs[-1]["seq"] == 20  # seq counts all-time, ring holds the tail


def test_flightrec_events_carry_both_clocks():
    # ISSUE 3 satellite: t_mono (perf_counter) rides alongside wall ts so
    # the cross-rank merger can align ranks without trusting wall clocks.
    rec = flightrec.FlightRecorder(rank=0)
    t0 = time.perf_counter()
    rec.record("collective", op="allreduce", seq=1)
    t1 = time.perf_counter()
    ev = rec.events()[-1]
    assert t0 <= ev["t_mono"] <= t1 + 1e-6
    assert abs(ev["ts"] - time.time()) < 60.0  # wall clock, roughly now


def test_flightrec_dump_header_has_t_mono_and_report_prints_it(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    flightrec.set_rank(0)
    flightrec.record(
        "failure", error="BridgeTimeoutError", message="timed out",
        op="allreduce", key="k",
    )
    path = flightrec.dump("unit")
    header = json.loads(open(path).readline())
    assert "t_mono" in header and "ts" in header
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    # the failure line shows both clocks
    assert "ts=" in proc.stdout and "t_mono=" in proc.stdout


def test_flightrec_dump_without_dir_is_noop(tmp_path):
    rec = flightrec.FlightRecorder(rank=0)
    rec.record("tick")
    assert rec.dump("test") is None  # CGX_METRICS_DIR unset
    # explicit path works regardless
    p = rec.dump("test", path=str(tmp_path / "explicit.jsonl"))
    assert p and os.path.exists(p)


def test_flightrec_dump_format(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    metrics.add("cgx.something", 3.0)
    flightrec.set_rank(5)
    flightrec.record("collective", op="allreduce", seq=1)
    path = flightrec.dump("unit")
    assert path.endswith("flightrec-rank5.jsonl")
    lines = [json.loads(l) for l in open(path)]
    header, events = lines[0], lines[1:]
    assert header["kind"] == "dump" and header["reason"] == "unit"
    assert header["rank"] == 5 and header["events"] == 1
    assert header["metrics"]["cgx.something"] == 3.0
    assert events[0]["kind"] == "collective" and events[0]["op"] == "allreduce"
    assert metrics.get("cgx.flightrec.dumps") == 1.0


# ---------------------------------------------------------------------------
# Dump-on-failure through the real shm channel (CGX_FAULTS injection).
# ---------------------------------------------------------------------------


def _dump_files(d):
    return sorted(glob.glob(os.path.join(str(d), "flightrec-rank*.jsonl")))


def test_corrupt_wire_leaves_flight_dump(tmp_path, monkeypatch):
    mdir = tmp_path / "m"
    monkeypatch.setenv("CGX_FAULTS", "corrupt_wire:step=0")
    monkeypatch.setenv("CGX_METRICS_DIR", str(mdir))
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("payload-key", np.ones(4096, np.uint8).tobytes())
        with pytest.raises(WireCorruptionError):
            reader.take("payload-key")
    finally:
        writer.close()
        reader.close()
    files = _dump_files(mdir)
    assert files, "corruption produced no flight-recorder dump"
    lines = [json.loads(l) for l in open(files[-1])]
    header = lines[0]
    assert header["kind"] == "dump" and header["reason"] == "WireCorruptionError"
    assert header["metrics"]["cgx.wire_corrupt"] == 1.0
    failures = [e for e in lines[1:] if e["kind"] == "failure"]
    assert failures, "no failure event in the dump"
    f = failures[-1]
    assert f["error"] == "WireCorruptionError"
    assert f["op"] == "shm.take" and f["key"] == "payload-key"
    # the injected fault that caused it is in the ring too
    assert any(
        e["kind"] == "fault" and e["mode"] == "corrupt_wire"
        for e in lines[1:]
    )


def test_take_timeout_leaves_flight_dump(tmp_path, monkeypatch):
    mdir = tmp_path / "m"
    monkeypatch.setenv("CGX_BRIDGE_TIMEOUT_MS", "200")
    monkeypatch.setenv("CGX_METRICS_DIR", str(mdir))
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        with pytest.raises(BridgeTimeoutError):
            reader.take("never-posted")
    finally:
        writer.close()
        reader.close()
    files = _dump_files(mdir)
    assert files
    lines = [json.loads(l) for l in open(files[-1])]
    failures = [e for e in lines[1:] if e["kind"] == "failure"]
    assert failures and failures[-1]["error"] == "BridgeTimeoutError"
    assert "never-posted" in failures[-1]["key"]


def test_shm_put_take_timing_instrumented(tmp_path):
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("k", np.ones(100_000, np.uint8).tobytes())
        reader.take("k")
    finally:
        writer.close()
        reader.close()
    assert metrics.histogram_stats("cgx.shm.put_s")["count"] == 1
    assert metrics.histogram_stats("cgx.shm.take_wait_s")["count"] == 1
    assert metrics.histogram_stats("cgx.shm.take_copy_s")["count"] == 1
    assert metrics.get("cgx.shm.put_bytes") >= 100_000
    kinds = [e["kind"] for e in flightrec.get_recorder().events()]
    assert "shm_put" in kinds and "shm_take" in kinds


# ---------------------------------------------------------------------------
# Exporter + cross-rank aggregation.
# ---------------------------------------------------------------------------


def test_exporter_periodic_flush(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    monkeypatch.setenv("CGX_METRICS_FLUSH_S", "0.05")
    metrics.add("cgx.steps", 3.0)
    metrics.observe("cgx.lat", 0.01)
    ex = obs_exporter.start_exporter(rank=2)
    assert ex is not None
    assert obs_exporter.start_exporter(rank=2) is ex  # idempotent
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if os.path.exists(ex.path) and len(open(ex.path).readlines()) >= 2:
            break
        time.sleep(0.02)
    obs_exporter.stop_exporter()
    lines = [json.loads(l) for l in open(ex.path)]
    assert len(lines) >= 2
    rec = lines[-1]
    assert rec["rank"] == 2
    assert rec["counters"]["cgx.steps"] == 3.0
    assert rec["histograms"]["cgx.lat"]["count"] == 1


def test_exporter_inert_without_dir():
    assert obs_exporter.start_exporter(rank=0) is None


_SIGTERM_CHILD = r"""
import os, signal, sys, time
sys.path.insert(0, {repo!r})
os.environ["CGX_METRICS_DIR"] = {mdir!r}
os.environ["CGX_METRICS_FLUSH_S"] = "3600"  # no periodic flush
from torch_cgx_tpu.observability import exporter, timeline
from torch_cgx_tpu.utils.logging import metrics

metrics.add("cgx.steps", 7.0)
timeline.set_rank(0)
with timeline.span("allreduce", timeline.CAT_COLLECTIVE, seq=1):
    pass
exporter.start_exporter(rank=0)
print("READY", flush=True)
time.sleep(60)
"""


def test_exporter_sigterm_flush_leaves_snapshot(tmp_path):
    # ISSUE 3 satellite: a rank torn down between periodic flushes
    # (SIGTERM from a launcher) still leaves its last metrics snapshot
    # AND its buffered timeline spans on disk.
    import signal

    mdir = str(tmp_path / "m")
    child = subprocess.Popen(
        [sys.executable, "-c",
         _SIGTERM_CHILD.format(repo=_REPO, mdir=mdir)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=_REPO,
    )
    try:
        line = child.stdout.readline()
        assert "READY" in line, child.stderr.read()
        child.send_signal(signal.SIGTERM)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
    assert child.returncode != 0  # SIGTERM still terminates the process
    mpath = os.path.join(mdir, "metrics-rank0.jsonl")
    assert os.path.exists(mpath), os.listdir(mdir)
    lines = [json.loads(l) for l in open(mpath)]
    assert lines and lines[-1]["counters"]["cgx.steps"] == 7.0
    spath = os.path.join(mdir, "spans-rank0.jsonl")
    assert os.path.exists(spath), os.listdir(mdir)
    spans = [json.loads(l) for l in open(spath)]
    assert any(
        e.get("kind") == "span" and e["name"] == "allreduce" for e in spans
    )


def test_aggregate_over_store_merges_and_names_missing(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_METRICS_DIR", str(tmp_path))
    store = FakeStore()
    # rank 1 publishes its snapshot (no report on non-leaders)
    metrics.add("cgx.wire_bytes", 100.0)
    metrics.observe("cgx.lat", 0.5)
    assert (
        obs_exporter.aggregate_over_store(store, 1, 3, timeout_s=0.2) is None
    )
    # rank 0 (here: same process, fresh registry) merges; rank 2 never
    # publishes -> named missing, not a hang
    metrics.reset()
    metrics.add("cgx.wire_bytes", 50.0)
    metrics.observe("cgx.lat", 0.1)
    t0 = time.monotonic()
    report = obs_exporter.aggregate_over_store(store, 0, 3, timeout_s=0.3)
    assert time.monotonic() - t0 < 5.0
    assert report["missing_ranks"] == [2]
    assert report["ranks_reporting"] == [0, 1]
    assert report["counters"]["cgx.wire_bytes"] == 150.0
    h = report["histograms"]["cgx.lat"]
    assert h["count"] == 2 and h["min"] == 0.1 and h["max"] == 0.5
    # leader also wrote the cluster report file
    lines = [json.loads(l) for l in open(tmp_path / "cluster-report.jsonl")]
    assert lines[-1]["counters"]["cgx.wire_bytes"] == 150.0


# ---------------------------------------------------------------------------
# JAX-path counters: SRA and Ring allreduce instrumentation (satellite).
# ---------------------------------------------------------------------------


def _run_allreduce_tree():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from torch_cgx_tpu.parallel.allreduce import allreduce_tree
    from torch_cgx_tpu.utils.compat import shard_map

    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("dp",))
    g = jnp.asarray(
        np.random.default_rng(0).normal(size=(16, 32)), jnp.float32
    )
    fn = jax.jit(
        shard_map(
            lambda x: allreduce_tree({"w": x}, mesh=mesh)["w"],
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False,
        )
    )
    jax.block_until_ready(fn(g))
    return g


def test_sra_allreduce_counters_and_events(monkeypatch):
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_INNER_REDUCTION_TYPE", "SRA")
    g = _run_allreduce_tree()
    assert metrics.get("cgx.trace.allreduce.compressed_elems") == g.size
    groups = [
        e for e in flightrec.get_recorder().events()
        if e["kind"] == "allreduce_group"
    ]
    assert groups and groups[-1]["algo"] == "SRA"
    assert groups[-1]["bits"] == 4 and groups[-1]["elems"] == g.size
    assert groups[-1]["wire_ratio"] > 1.0  # 4-bit wire beats fp32


def test_ring_allreduce_counters_and_events(monkeypatch):
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_INNER_REDUCTION_TYPE", "RING")
    g = _run_allreduce_tree()
    assert metrics.get("cgx.trace.allreduce.compressed_elems") == g.size
    groups = [
        e for e in flightrec.get_recorder().events()
        if e["kind"] == "allreduce_group"
    ]
    assert groups and groups[-1]["algo"] == "RING"


def test_qerr_stats_env_gated(monkeypatch):
    import jax

    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_QERR_STATS", "1")
    _run_allreduce_tree()
    jax.effects_barrier()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if metrics.snapshot("cgx.qerr."):
            break
        time.sleep(0.05)
    qerr = metrics.snapshot("cgx.qerr.")
    assert qerr, "CGX_QERR_STATS=1 produced no qerr observations"
    # 4-bit max-min error on gaussian data: small but nonzero
    means = [v for k, v in qerr.items() if k.endswith(".mean")]
    assert means and all(0.0 < m < 0.5 for m in means)
    qerr_events = [
        e for e in flightrec.get_recorder().events() if e["kind"] == "qerr"
    ]
    assert qerr_events and qerr_events[-1]["rel_l2"] > 0.0


# ---------------------------------------------------------------------------
# The acceptance chaos run (kill_rank + CGX_METRICS_DIR -> dump naming the
# failed collective and suspected dead rank, rendered by cgx_report) lives
# in tests/test_faults.py::test_kill_rank_produces_named_timeout — it
# rides the existing 2-rank kill run instead of spawning a second one
# (tier-1 wall-clock is budget-bound).
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# Report tool edge cases.
# ---------------------------------------------------------------------------


def test_cgx_report_empty_dir(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0
    assert "no events recorded" in proc.stdout


def test_cgx_report_rejects_missing_dir(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"),
         str(tmp_path / "nope")],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 2


def test_cgx_report_tolerates_torn_tail(tmp_path):
    # A killed writer can leave a torn last line; the reader must not care.
    p = tmp_path / "flightrec-rank0.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"kind": "dump", "reason": "x", "rank": 0,
                            "events": 1, "metrics": {}}) + "\n")
        f.write(json.dumps({"kind": "collective", "op": "allreduce",
                            "seq": 1, "seconds": 0.01, "ts": 0,
                            "ok": True}) + "\n")
        f.write('{"kind": "fail')  # torn mid-write
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0
    assert "allreduce" in proc.stdout
