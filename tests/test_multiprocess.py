"""TRUE multi-process JAX-path integration: the multi-host story end-to-end.

SURVEY §5.8's distributed backend on the JAX side is
``jax.distributed.initialize`` (``mesh.init_distributed``) + XLA
collectives across processes. The rest of the suite simulates multi-chip
with a single-process virtual mesh; this file spawns REAL processes (one
CPU device each, Gloo cross-process collectives) and drives the
bootstrap, the quantized allreduce, ``shard_batch``'s
local-slice-to-global-array path, and a full ``make_train_step`` —
the closest a CPU host gets to the reference's ``mpirun`` launches.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import socket
import sys
import traceback

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _proc_main(rank: int, ws: int, port: int, q) -> None:
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        sys.path.insert(0, _REPO)
        os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
        os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = "64"
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import numpy as np
        import optax
        from torch_cgx_tpu.utils.compat import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from torch_cgx_tpu.config import CompressionConfig
        from torch_cgx_tpu.parallel import (
            make_train_step,
            replicate,
            shard_batch,
        )
        from torch_cgx_tpu.parallel.mesh import init_distributed
        from torch_cgx_tpu.parallel.reducers import quantized_allreduce

        assert init_distributed(f"localhost:{port}", ws, rank)
        assert jax.process_count() == ws and jax.device_count() == ws
        mesh = Mesh(np.asarray(jax.devices()), ("dp",))
        cc = CompressionConfig(bits=4, bucket_size=64)

        # 1) quantized SRA across PROCESSES: constant-exactness oracle.
        x = jnp.full((256,), float(rank + 1), jnp.float32)
        garr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("dp")), np.asarray(x)[None]
        )
        fn = jax.jit(
            shard_map(
                lambda v: quantized_allreduce(v[0], "dp", ws, cc, "SRA")[None],
                mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                check_vma=False,
            )
        )
        local = np.asarray(fn(garr).addressable_shards[0].data)
        expect = ws * (ws + 1) // 2
        assert (local == expect).all(), (rank, local[0, :4], expect)

        # 2) full train step: per-process local batch slices via
        # shard_batch (make_array_from_process_local_data), quantized
        # gradient sync, replicated update.
        rng = np.random.default_rng(0)  # same data plan on every process
        Wt = rng.normal(size=(16, 4)).astype(np.float32)
        X = rng.normal(size=(64, 16)).astype(np.float32)
        Y = X @ Wt
        n_local = X.shape[0] // ws
        Xl = X[rank * n_local : (rank + 1) * n_local]
        Yl = Y[rank * n_local : (rank + 1) * n_local]

        def loss_fn(p, b):
            return jnp.mean((b[0] @ p["w"] - b[1]) ** 2)

        params = {"w": jnp.zeros((16, 4), jnp.float32)}
        opt = optax.sgd(5e-2)
        step = make_train_step(loss_fn, opt, mesh, donate=False)
        p = replicate(params, mesh)
        s = replicate(opt.init(params), mesh)
        losses = []
        for i in range(15):
            b = shard_batch((Xl, Yl), mesh)  # LOCAL slice in, global out
            p, s, loss = step(p, s, b, jnp.int32(i))
            losses.append(float(loss))
        assert losses[-1] < 0.5 * losses[0], losses
        # the local replica equals every process's (loss already proves the
        # sync ran; check the param bytes round-trip a psum unchanged)
        w = np.asarray(p["w"].addressable_shards[0].data)
        mx = jax.jit(
            shard_map(lambda v: jax.lax.pmax(v, "dp"), mesh=mesh,
                      in_specs=P(), out_specs=P(), check_vma=False)
        )(p["w"])
        np.testing.assert_array_equal(
            w, np.asarray(mx.addressable_shards[0].data)
        )
        q.put((rank, None))
    except Exception:
        q.put((rank, traceback.format_exc()))


def _hier_main(rank: int, ws: int, port: int, q) -> None:
    """Two processes x two local devices: the (cross, intra) hierarchy with
    the cross axis spanning REAL process boundaries — the traffic shape the
    reference's two-level topology exists for (intra = node-local SHM,
    cross = inter-node MPI; here intra = in-process, cross = Gloo)."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        sys.path.insert(0, _REPO)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from torch_cgx_tpu.utils.compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torch_cgx_tpu.config import CompressionConfig, TopologyConfig
        from torch_cgx_tpu.parallel.mesh import (
            hierarchical_mesh,
            init_distributed,
        )
        from torch_cgx_tpu.parallel.reducers import hierarchical_allreduce

        assert init_distributed(f"localhost:{port}", ws, rank)
        assert jax.device_count() == 2 * ws
        mesh = hierarchical_mesh(intra_size=2)  # (cross=ws, intra=2)
        assert mesh.shape["cross"] == ws and mesh.shape["intra"] == 2
        cc = CompressionConfig(bits=4, bucket_size=64)
        topo = TopologyConfig()  # leader scheme on

        # per-DEVICE values rank*2+local+1 -> exact sum 1+2+...+2ws
        local = np.stack([
            np.full((256,), rank * 2 + d + 1, np.float32) for d in range(2)
        ])
        garr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(("cross", "intra"))),
            local.reshape(2, 256),
        )

        def body(v):
            return hierarchical_allreduce(
                v[0], intra_axis="intra", cross_axis="cross",
                ws_intra=2, ws_cross=ws, cc=cc, topology=topo,
            )[None]

        fn = jax.jit(
            shard_map(body, mesh=mesh, in_specs=P(("cross", "intra")),
                      out_specs=P(("cross", "intra")), check_vma=False)
        )
        out = fn(garr)
        n_dev = 2 * ws
        expect = n_dev * (n_dev + 1) // 2
        for sh in out.addressable_shards:
            vals = np.asarray(sh.data)
            assert (vals == expect).all(), (rank, vals[0, :4], expect)
        q.put((rank, None))
    except Exception:
        q.put((rank, traceback.format_exc()))


def _run_once(ws: int, target=_proc_main):
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = _free_port()
    procs = [
        ctx.Process(target=target, args=(r, ws, port, q), daemon=True)
        for r in range(ws)
    ]
    for p in procs:
        p.start()
    errors = []
    try:
        for _ in range(ws):
            rank, err = q.get(timeout=240)
            if err is not None:
                errors.append(f"rank {rank}:\n{err}")
    except Exception:
        errors.append("timed out waiting for ranks")
    finally:
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
    return errors


def _launch(ws: int, target=_proc_main):
    def _retryable(errs):
        # The bind race manifests as an in-use/bind failure on the
        # coordinator rank while the OTHER ranks time out waiting for the
        # coordinator that never came up — both shapes retry.
        bindish = [e for e in errs
                   if "in use" in e or "bind" in e.lower()]
        rest_ok = all(
            ("in use" in e) or ("bind" in e.lower()) or ("timed out" in e)
            for e in errs
        )
        return bool(bindish) and rest_ok

    errors = _run_once(ws, target)
    if errors and _retryable(errors):
        errors = _run_once(ws, target)  # fresh port
    assert not errors, "\n".join(errors)


@pytest.mark.torch_bridge  # same spawn-cost class as the bridge tests
def test_two_process_jax_distributed():
    _launch(2)


@pytest.mark.torch_bridge
def test_two_process_hierarchical_cross_boundary():
    _launch(2, _hier_main)
