"""Adaptive per-layer bit allocation (parallel/adaptive.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from torch_cgx_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.parallel import (
    adapt_bits,
    allreduce_tree,
    flat_mesh,
    measure_layer_stats,
    solve_bit_allocation,
)
from torch_cgx_tpu.parallel.adaptive import LayerStat, apply_bit_allocation


def test_measure_skips_ineligible_layers(monkeypatch):
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    grads = {
        "kernel": jnp.ones((64, 32), jnp.float32),
        "bias": jnp.ones((64,), jnp.float32),  # dim<=1: uncompressed
        "tiny": jnp.ones((2, 2), jnp.float32),  # < minimal: uncompressed
    }
    stats = measure_layer_stats(grads)
    assert set(stats) == {"kernel"}
    assert stats["kernel"].numel == 64 * 32


def test_solver_respects_budget_and_prefers_noisy_layers():
    n = 10_000
    stats = {
        "noisy": LayerStat(numel=n, mean_sq_range=100.0),
        "quiet": LayerStat(numel=n, mean_sq_range=0.01),
    }
    alloc = solve_bit_allocation(stats, avg_bits=4.0, bits_range=(2, 8))
    total_bits = sum(stats[k].numel * b for k, b in alloc.items())
    assert total_bits <= 4.0 * 2 * n + 1e-9
    assert alloc["noisy"] > alloc["quiet"], alloc
    assert 2 <= alloc["quiet"] and alloc["noisy"] <= 8

    # budget at the floor: everyone gets the minimum
    alloc_lo = solve_bit_allocation(stats, avg_bits=2.0, bits_range=(2, 8))
    assert alloc_lo == {"noisy": 2, "quiet": 2}

    # unlimited budget: everyone maxes out
    alloc_hi = solve_bit_allocation(stats, avg_bits=8.0, bits_range=(2, 8))
    assert alloc_hi == {"noisy": 8, "quiet": 8}


def test_solver_validates_bits_range():
    with pytest.raises(ValueError, match="bits_range"):
        solve_bit_allocation({}, 4.0, bits_range=(0, 8))


def test_adaptive_beats_uniform_at_same_budget(monkeypatch):
    """Two layers, one with 100x the bucket range: the adaptive split at an
    average of 4 bits must reduce end-to-end allreduce error vs uniform
    4-bit on the same gradients."""
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, "64")
    mesh = flat_mesh()
    rng = np.random.default_rng(0)
    grads = {
        "wild": jnp.asarray(rng.normal(size=(64, 64)) * 100, jnp.float32),
        "tame": jnp.asarray(rng.normal(size=(64, 64)) * 1, jnp.float32),
    }

    def reduced_error():
        def fn(g):
            return allreduce_tree(g, mesh=mesh, average=True)

        out = jax.jit(
            shard_map(fn, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
        )(jax.device_put(grads, NamedSharding(mesh, P())))
        return sum(
            float(jnp.sum((a - b) ** 2))
            for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(grads))
        )

    err_uniform = reduced_error()
    alloc = adapt_bits(grads, avg_bits=4.0, bucket_size=64)
    assert alloc["wild"] > alloc["tame"], alloc
    # budget respected
    n = 64 * 64
    assert alloc["wild"] * n + alloc["tame"] * n <= 4.0 * 2 * n
    err_adaptive = reduced_error()
    assert err_adaptive < err_uniform * 0.9, (err_adaptive, err_uniform)


def test_adapt_takes_effect_through_train_step_cache(monkeypatch):
    """adapt_bits must invalidate make_train_step's cached trace (registry
    version in the build key): starting from a compression-OFF default env,
    post-adaptation steps must actually compress (trajectory diverges from
    the exact-f32 twin), and pre-adaptation steps must not."""
    monkeypatch.delenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, raising=False)
    import optax

    from torch_cgx_tpu.parallel import make_train_step, replicate, shard_batch

    mesh = flat_mesh()
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=(32, 32)) * 0.3, jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b[0] @ p["w"] - b[1]) ** 2)

    opt = optax.sgd(0.1)
    xs = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    ys = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)

    def run(adapt_at):
        step = make_train_step(loss_fn, opt, mesh, donate=False)
        p = replicate(params, mesh)
        s = replicate(opt.init(params), mesh)
        snaps = []
        for i in range(4):
            if i == adapt_at:
                g = {"w": np.asarray(p["w"])}
                alloc = adapt_bits(g, avg_bits=2.0, bucket_size=32)
                assert alloc == {"w": 2}, alloc
            b = shard_batch((xs, ys), mesh)
            p, s, _ = step(p, s, b, jnp.int32(i))
            snaps.append(np.asarray(p["w"]))
        return snaps

    plain = run(adapt_at=99)  # never adapts: exact f32 sync throughout
    adapted = run(adapt_at=2)
    cgx_config.clear_registry()
    # identical before adaptation...
    np.testing.assert_array_equal(plain[0], adapted[0])
    np.testing.assert_array_equal(plain[1], adapted[1])
    # ...and 2-bit-compressed after: the stale-cache bug would keep these
    # equal forever.
    assert not np.array_equal(plain[2], adapted[2]), (
        "adaptation never took effect (stale train-step cache)")


def test_apply_allocation_with_bare_layerstats(monkeypatch):
    """LayerStats constructed without a measured config (cc=None — the
    solver-test pattern) must fall back to the env defaults instead of
    raising (advisor r3)."""
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, "256")
    stats = {
        "layer": LayerStat(numel=4096, mean_sq_range=1.0),
    }
    apply_bit_allocation({"layer": 3}, stats)
    cc = cgx_config.resolve_pattern_config("layer")
    assert cc is not None and cc.bits == 3 and cc.bucket_size == 256
