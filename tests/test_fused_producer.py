"""Producer-fused gradient quantization: knob-off jaxpr/value inertness,
fused-kernel wire-byte parity vs the compose path, consumption plumbing
bit-equality through the staged allreduce (monolithic and pipelined),
and the fallback ladder (guard/EF/misaligned shapes never consume)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
import flax.linen as nn
from jax.sharding import Mesh

from torch_cgx_tpu.config import CompressionConfig
from torch_cgx_tpu.models.layers import CgxDense
from torch_cgx_tpu.ops import dispatch, fused_producer as fp
from torch_cgx_tpu.parallel import grad_sync, reducers
from torch_cgx_tpu.utils.logging import metrics


@pytest.fixture(autouse=True)
def _deconfigure():
    fp.deconfigure()
    yield
    fp.deconfigure()


def _mesh(ws=2):
    return Mesh(np.array(jax.devices()[:ws]).reshape(ws), ("dp",))


# ---------------------------------------------------------------------------
# Knob-off inertness.
# ---------------------------------------------------------------------------


def test_knob_off_matmul_jaxpr_is_plain_dot(monkeypatch):
    """CGX_PRODUCER_FUSE unset on CPU (auto => off): the wrapper lowers to
    exactly the cast + dot_general an unwrapped dense layer stages."""
    x = jnp.zeros((4, 8, 16), jnp.bfloat16)
    w = jnp.zeros((16, 32), jnp.float32)

    def wrapped(x, w):
        return fp.matmul(x, w, name="t/kernel", compute_dtype=jnp.bfloat16)

    def plain(x, w):
        return jax.lax.dot_general(
            x, w.astype(jnp.bfloat16), (((2,), (0,)), ((), ()))
        )

    assert str(jax.make_jaxpr(wrapped)(x, w)) == str(
        jax.make_jaxpr(plain)(x, w)
    )


def test_engaged_backward_stages_payload(monkeypatch):
    """With the knob on, inside the configured sync axis's shard_map, the
    backward stashes the layer's wire payload (one entry per layer)."""
    from jax.sharding import PartitionSpec as P

    from torch_cgx_tpu.utils.compat import shard_map

    monkeypatch.setenv("CGX_PRODUCER_FUSE", "on")
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_STANDALONE_LAYER_ELEMS", "32768")
    mesh = _mesh(2)
    fp.configure(mesh, ("dp",), divisor=2, active=True)
    x = jnp.zeros((4, 256), jnp.float32)
    w = jnp.zeros((256, 512), jnp.float32)

    def body(x, w):
        fp.begin_step()
        return jax.grad(
            lambda w: jnp.sum(
                fp.matmul(x, w, name="big/kernel",
                          compute_dtype=jnp.float32)
            )
        )(w)

    jax.make_jaxpr(
        shard_map(body, mesh=mesh, in_specs=(P("dp"), P()),
                  out_specs=P(), check_vma=False)
    )(x, w)
    assert fp.stash_size() == 1


def test_grad_outside_shard_map_falls_back(monkeypatch):
    """A bare jax.grad over a wrapped layer (no sync axis bound) must
    produce the plain cotangent, not crash on axis_index."""
    monkeypatch.setenv("CGX_PRODUCER_FUSE", "on")
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_STANDALONE_LAYER_ELEMS", "32768")
    fp.configure(_mesh(2), ("dp",), divisor=2, active=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 256))
    w = jax.random.normal(jax.random.PRNGKey(1), (256, 512))
    g = jax.grad(
        lambda w: jnp.sum(
            fp.matmul(x, w, name="big/kernel", compute_dtype=jnp.float32)
        )
    )(w)
    ref = jax.grad(lambda w: jnp.sum(x @ w))(w)
    assert bool(jnp.allclose(g, ref, atol=1e-5))


def test_cgx_dense_matches_nn_dense_values_and_grads():
    """CgxDense is a bit-exact nn.Dense twin with the knob off — same
    params, same outputs, same gradients."""
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 16)).astype(
        jnp.bfloat16
    )
    cgx = CgxDense(8, dtype=jnp.bfloat16)
    ref = nn.Dense(8, dtype=jnp.bfloat16)
    params = cgx.init(jax.random.PRNGKey(1), x)
    out_c = cgx.apply(params, x)
    out_r = ref.apply(params, x)  # identical param structure by design
    assert bool(jnp.array_equal(out_c, out_r))

    def loss_c(p):
        return jnp.sum(cgx.apply(p, x).astype(jnp.float32) ** 2)

    def loss_r(p):
        return jnp.sum(ref.apply(p, x).astype(jnp.float32) ** 2)

    g_c = jax.grad(loss_c)(params)
    g_r = jax.grad(loss_r)(params)
    for a, b in zip(jax.tree.leaves(g_c), jax.tree.leaves(g_r)):
        assert bool(jnp.array_equal(a, b))


# ---------------------------------------------------------------------------
# The fused matmul+quantize kernel.
# ---------------------------------------------------------------------------


def test_kernel_geometry_gates():
    cc = CompressionConfig(bits=4, bucket_size=512)
    # aligned: 256x512 over ws=2 -> chunk 65536, whole chunks, o%128==0
    assert fp._kernel_geometry(64, 256, 512, 2, 65536, cc) is not None
    # misaligned lane width
    assert fp._kernel_geometry(64, 256, 96, 2, 24576, cc) is None
    # bucket not lane-aligned
    cc2 = CompressionConfig(bits=4, bucket_size=96)
    assert fp._kernel_geometry(64, 256, 512, 2, 65536, cc2) is None


def test_kernel_bytes_match_compose_reference():
    """The fused matmul+quantize kernel's wire bytes equal a quantize of
    the same dw values (decode-exact contract on agreeing matmuls)."""
    cc = CompressionConfig(bits=4, bucket_size=512)
    K, din, o, ws = 64, 256, 512, 2
    rng = np.random.default_rng(0)
    x2 = jnp.asarray(rng.standard_normal((K, din)), jnp.float32)
    g2 = jnp.asarray(rng.standard_normal((K, o)), jnp.float32)
    chunk = din * o // ws
    tm, tk = fp._kernel_geometry(K, din, o, ws, chunk, cc)
    q_k = fp._matmul_quantize_q(
        x2, g2, cc, ws=ws, chunk=chunk, div=ws, tm=tm, tk=tk, interpret=True
    )
    dw = (
        jax.lax.dot_general(x2, g2, (((0,), (0,)), ((), ()))) / ws
    ).reshape(ws, chunk)
    q_ref = reducers._quantize_rows(dw, cc, None)
    assert bool(jnp.array_equal(q_k.packed, q_ref.packed))
    # meta rides the wire in the tensor dtype; envelope parity on decode
    d_k = dispatch.dequantize_batch(q_k)
    d_r = dispatch.dequantize_batch(q_ref)
    assert bool(jnp.array_equal(d_k, d_r))


# ---------------------------------------------------------------------------
# End-to-end consumption through the staged allreduce.
# ---------------------------------------------------------------------------


class _OneDense(nn.Module):
    @nn.compact
    def __call__(self, x):
        return CgxDense(512, dtype=jnp.float32, name="big")(x)


def _train(monkeypatch, fuse, steps=2, guard=None, **env):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    monkeypatch.setenv("CGX_PRODUCER_FUSE", fuse)
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_STANDALONE_LAYER_ELEMS", "32768")
    mesh = _mesh(2)
    model = _OneDense()
    xb = jax.random.normal(jax.random.PRNGKey(1), (8, 256))
    yb = jax.random.normal(jax.random.PRNGKey(2), (8, 512))
    params = model.init(jax.random.PRNGKey(0), xb)

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((model.apply(p, x) - y) ** 2)

    step = grad_sync.make_train_step(
        loss_fn, optax.sgd(0.1), mesh, axes=("dp",), nonfinite_guard=guard
    )
    p = grad_sync.replicate(jax.tree.map(jnp.array, params), mesh)
    s = grad_sync.replicate(optax.sgd(0.1).init(p), mesh)
    for i in range(steps):
        batch = grad_sync.shard_batch((xb, yb), mesh, axes=("dp",))
        p, s, loss = step(p, s, batch, i)
    return jax.tree.map(np.asarray, p)


def _consumed():
    return metrics.get("cgx.codec.producer_consumed_slices") or 0.0


def test_consumed_payload_bit_equal_monolithic(monkeypatch):
    p_off = _train(monkeypatch, "off")
    before = _consumed()
    p_on = _train(monkeypatch, "on")
    assert _consumed() > before, "producer payload was not consumed"
    for a, b in zip(jax.tree.leaves(p_off), jax.tree.leaves(p_on)):
        assert bool(np.array_equal(a, b))


def test_consumed_payload_bit_equal_pipelined(monkeypatch):
    env = dict(CGX_SCHEDULE="on", CGX_SCHED_CHUNKS="2",
               CGX_XLA_ALLREDUCE="on")
    p_off = _train(monkeypatch, "off", **env)
    before = _consumed()
    p_on = _train(monkeypatch, "on", **env)
    assert _consumed() > before
    for a, b in zip(jax.tree.leaves(p_off), jax.tree.leaves(p_on)):
        assert bool(np.array_equal(a, b))


def test_guard_disables_consumption_but_not_training(monkeypatch):
    """The nonfinite guard rewrites the gradient tree (where-selects), so
    the cotangent-identity match must fail closed: no consumption, and
    results equal the unfused guarded run bit for bit."""
    before = _consumed()
    p_on = _train(monkeypatch, "on", guard="skip")
    assert _consumed() == before
    p_off = _train(monkeypatch, "off", guard="skip")
    for a, b in zip(jax.tree.leaves(p_off), jax.tree.leaves(p_on)):
        assert bool(np.array_equal(a, b))


def test_error_feedback_never_consumes(monkeypatch):
    """EF adds residuals before the sync — identity match fails closed."""
    monkeypatch.setenv("CGX_PRODUCER_FUSE", "on")
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_STANDALONE_LAYER_ELEMS", "32768")
    mesh = _mesh(2)
    model = _OneDense()
    xb = jax.random.normal(jax.random.PRNGKey(1), (8, 256))
    yb = jax.random.normal(jax.random.PRNGKey(2), (8, 512))
    params = model.init(jax.random.PRNGKey(0), xb)

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((model.apply(p, x) - y) ** 2)

    step = grad_sync.make_train_step(
        loss_fn, optax.sgd(0.1), mesh, axes=("dp",), error_feedback=True
    )
    p = grad_sync.replicate(jax.tree.map(jnp.array, params), mesh)
    s = grad_sync.replicate(optax.sgd(0.1).init(p), mesh)
    ef = grad_sync.init_error_feedback(p, mesh, axes=("dp",))
    before = _consumed()
    batch = grad_sync.shard_batch((xb, yb), mesh, axes=("dp",))
    p, s, ef, loss = step(p, s, ef, batch, 0)
    assert np.isfinite(float(loss))
    assert _consumed() == before


def test_stash_epoch_and_claim():
    """lookup() honors identity + epoch; claim() prevents double-spend."""
    fp.configure(_mesh(2), ("dp",), divisor=2, active=True)
    leaf = jnp.zeros((4,))
    ent = fp.Produced(
        cotangent=leaf, q=None, q_blocks=None, table=None,
        raw_row=jnp.zeros((2,)), cc=CompressionConfig(bits=4),
        ws=2, n=4, divisor=2, epoch=fp._CFG["epoch"], name="t",
    )
    fp._STASH[id(leaf)] = ent
    assert fp.lookup(leaf) is ent
    assert fp.lookup(jnp.zeros((4,))) is None  # identity, not equality
    fp.claim(leaf)
    assert fp.lookup(leaf) is None
    fp._STASH[id(leaf)] = ent
    fp.begin_step()  # stale epoch entries unclaimable
    assert fp.lookup(leaf) is None


@pytest.mark.tpu  # compiled Mosaic lowering of the producer kernel
def test_kernel_bytes_match_compose_tpu():
    """Hardware validation of `_matmul_quantize_impl` (the hw_session runs
    `pytest -m tpu`): compiled-kernel wire bytes vs the compose reference
    on the real chip — envelope on decode (matmul association may differ
    between the MXU grid and XLA's lowering), bit-equal when it doesn't."""
    cc = CompressionConfig(bits=4, bucket_size=512)
    K, din, o, ws = 256, 1024, 1024, 4
    rng = np.random.default_rng(5)
    x2 = jnp.asarray(rng.standard_normal((K, din)), jnp.float32)
    g2 = jnp.asarray(rng.standard_normal((K, o)), jnp.float32)
    chunk = din * o // ws
    tm, tk = fp._kernel_geometry(K, din, o, ws, chunk, cc)
    q_k = fp._matmul_quantize_q(
        x2, g2, cc, ws=ws, chunk=chunk, div=ws, tm=tm, tk=tk,
        interpret=False,
    )
    dw = (
        jax.lax.dot_general(x2, g2, (((0,), (0,)), ((), ()))) / ws
    ).reshape(ws, chunk)
    q_ref = reducers._quantize_rows(dw, cc, None)
    d_k = np.asarray(dispatch.dequantize_batch(q_k))
    d_r = np.asarray(dispatch.dequantize_batch(q_ref))
    unit = np.abs(np.asarray(dw)).max() / ((1 << cc.bits) - 1)
    assert np.max(np.abs(d_k - d_r)) <= 2 * unit + 1e-6
