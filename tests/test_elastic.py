"""Elastic membership suite (ISSUE 16 tentpole).

Unit layers run single-process over an in-memory store: the preempt /
corrupt_join_page fault grammar, the snapshot pager (raw and quantized
round-trips, multi-donor striping, corruption re-request, deadline
abort), the join trigger claim/adoption, the decision's rank and donor
assignment, both abort paths (vote timeout, joiner-never-acks) leaving
survivors unharmed, a full commit round with a hand-rolled protocol
joiner proving received-state bit-identity, and the store-key hygiene
reaper across generation bumps.

The chaos soak spawns four real torch-bridge ranks, preempts rank 1
mid-training (SIGKILL-shaped death with a comeback notice and a
detached respawner), and asserts the ISSUE 16 acceptance: the respawned
rank rejoins at a bumped generation with zero checkpoint files on disk,
survivors never stall past the join bound, and every era of the run is
bit-identical to fault-free control replays — then rank 1 leaves again
(shrink -> grow -> shrink) and the final survivor era is verified the
same way.
"""

from __future__ import annotations

import glob
import json
import multiprocessing as mp
import os
import sys
import tempfile
import threading
import time
import traceback
import zlib

import numpy as np
import pytest

from torch_cgx_tpu import config as cfg
from torch_cgx_tpu.observability import health as health_mod
from torch_cgx_tpu.robustness import (
    JoinAbortedError,
    elastic,
    faults,
    rendezvous as rdz,
)
from torch_cgx_tpu.utils.logging import metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _fresh():
    faults.reset_injectors()
    metrics.reset()
    cfg.clear_registry()
    health_mod.stop()
    yield
    faults.reset_injectors()
    cfg.clear_registry()
    health_mod.stop()


class FakeStore:
    """Minimal c10d-Store look-alike (same shape as test_supervisor's)."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def set(self, k, v):
        with self._lock:
            self._d[k] = v if isinstance(v, bytes) else bytes(v)

    def get(self, k):
        with self._lock:
            if k not in self._d:
                raise KeyError(k)
            return self._d[k]

    def add(self, k, v):
        with self._lock:
            cur = int(self._d.get(k, b"0")) + int(v)
            self._d[k] = str(cur).encode()
            return cur

    def delete_key(self, k):
        # c10d's deleteKey returns whether a key was removed; the reap
        # counters depend on it.
        with self._lock:
            return self._d.pop(k, None) is not None

    def keys(self):
        with self._lock:
            return list(self._d)


class _StubGroup:
    """Just enough group surface for the survivor-side coordinator."""

    def __init__(self, global_rank, global_ranks, generation=0):
        self.global_rank = global_rank
        self.global_ranks = list(global_ranks)
        self.generation = generation
        self._shm = None
        self.reconfigures = []

    def reconfigure(self, members, generation, *, joiner_info=None):
        self.reconfigures.append((list(members), generation, joiner_info))
        self.global_ranks = list(members)
        self.generation = generation

    def degrade_to_store(self):  # pragma: no cover - consensus no-op path
        raise AssertionError("degrade must not fire with _shm is None")


class _StubSup:
    """Supervisor surface the coordinator binds to."""

    def __init__(self, store, group):
        self._store = store
        self.group = group
        self._elastic = None

    def attach_elastic(self, coordinator):
        self._elastic = coordinator

    @property
    def generation(self):
        return self.group.generation

    @property
    def survivors(self):
        return list(self.group.global_ranks)


def _tree(big_numel=3 * (1 << 19), seed=7):
    """A state tree with a multi-page float leaf, an int leaf and a
    scalar — exercises striping, raw int passthrough and 0-d arrays."""
    rng = np.random.default_rng(seed)
    return {
        "w": rng.normal(size=big_numel).astype(np.float32),
        "i": np.arange(17, dtype=np.int64),
        "s": np.float32(3.25),
    }


def _skeleton_like(state):
    import jax

    return jax.tree_util.tree_map(np.zeros_like, state)


def _tree_equal(a, b):
    import jax

    la, _ = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


# ---------------------------------------------------------------------------
# Fault grammar.
# ---------------------------------------------------------------------------


def test_preempt_spec_parses_and_requires_duration():
    (s,) = faults.parse_faults("preempt:1500ms@rank=1@step=5")
    assert (s.mode, s.rank, s.step, s.delay_ms) == ("preempt", 1, 5, 1500.0)
    (s2,) = faults.parse_faults("preempt:2s@rank=3")
    assert (s2.mode, s2.rank, s2.delay_ms) == ("preempt", 3, 2000.0)
    with pytest.raises(ValueError):
        faults.parse_faults("preempt:rank=1@step=5")


def test_corrupt_join_payload_gates_on_page_ordinal(monkeypatch):
    monkeypatch.setenv("CGX_FAULTS", "corrupt_join_page:step=2")
    faults.reset_injectors()
    inj = faults.get_injector(0)
    payload = bytes(range(64))
    assert inj.corrupt_join_payload(payload, 0) == payload
    assert inj.corrupt_join_payload(payload, 1) == payload
    hit = inj.corrupt_join_payload(payload, 2)
    assert hit != payload
    assert sum(a != b for a, b in zip(hit, payload)) == 1
    assert inj.corrupt_join_payload(payload, 3) == payload


# ---------------------------------------------------------------------------
# Snapshot pager: encode -> donor stripes -> receiver -> decode.
# ---------------------------------------------------------------------------


def _ship_and_receive(store, state, bits, bucket, n_donors,
                      injector=None, timeout=30.0):
    wires, descs = elastic._encode_state(state, bits, bucket)
    meta = {
        "leaves": descs, "step": 7, "generation": 3, "registry": {},
        "bits": bits, "bucket": bucket, "n_donors": n_donors,
    }
    deadline = time.monotonic() + timeout
    streams = [elastic._stream_name(3, 9, di) for di in range(n_donors)]
    donors = [
        elastic._SnapshotDonor(
            store, streams[di], wires, descs,
            meta=meta if di == 0 else None, donor_idx=di,
            n_donors=n_donors, bits=bits, bucket=bucket,
            deadline=deadline, injector=injector if di == 0 else None,
        )
        for di in range(n_donors)
    ]
    for d in donors:
        d.start()
    meta_rx, bufs = elastic._SnapshotReceiver(
        store, streams, deadline).receive()
    out, step = elastic._decode_into_skeleton(
        _skeleton_like(state), meta_rx, bufs)
    for d in donors:
        d.join(10)
        assert d.done()
    return out, step


def test_snapshot_pager_raw_roundtrip_two_donors():
    store = FakeStore()
    state = _tree()  # 6 MiB leaf -> 6 pages, striped across 2 donors
    out, step = _ship_and_receive(store, state, 0, 0, n_donors=2)
    assert step == 7
    assert _tree_equal(out, state)
    assert metrics.get("cgx.elastic.pages_shipped") >= 7
    assert metrics.get("cgx.elastic.pages_received") >= 7


def test_snapshot_pager_quantized_roundtrip_matches_grid_snap():
    store = FakeStore()
    state = _tree(seed=11)
    out, step = _ship_and_receive(store, state, 8, 128, n_donors=2)
    assert step == 7
    # The lossy contract: both sides land on dequant(quant(original)) —
    # exactly what snap_state_to_grid produces from the original state.
    expected = elastic.snap_state_to_grid(state, 8, 128)
    assert _tree_equal(out, expected)
    # Non-float leaves ship raw even under a quantized edge config.
    assert np.array_equal(out["i"], state["i"])


def test_snapshot_page_corruption_is_rerequested(monkeypatch):
    monkeypatch.setenv("CGX_FAULTS", "corrupt_join_page:step=1")
    faults.reset_injectors()
    store = FakeStore()
    state = {"w": np.random.default_rng(3).normal(
        size=3 * (1 << 18)).astype(np.float32)}  # 3 MiB -> 3 pages
    out, _ = _ship_and_receive(
        store, state, 0, 0, n_donors=1, injector=faults.get_injector(0))
    assert _tree_equal(out, state)
    assert metrics.get("cgx.elastic.page_rereqs") >= 1
    assert metrics.get("cgx.elastic.page_reships") >= 1


def test_receiver_deadline_aborts_cleanly():
    store = FakeStore()
    rx = elastic._SnapshotReceiver(
        store, [elastic._stream_name(1, 5, 0)], time.monotonic() + 0.4)
    with pytest.raises(JoinAbortedError):
        rx.receive()
    assert metrics.get("cgx.elastic.join_aborts") >= 1


# ---------------------------------------------------------------------------
# Comeback notices.
# ---------------------------------------------------------------------------


def test_comeback_notice_roundtrip_and_expiry(monkeypatch):
    store = FakeStore()
    assert elastic.fresh_comeback(store, 2) is None
    elastic.publish_comeback(store, 2, 1.5)
    rec = elastic.fresh_comeback(store, 2)
    assert rec is not None and rec["rank"] == 2
    assert metrics.get("cgx.elastic.comebacks") == 1
    # Age the record past delay + grace: no longer fresh.
    stale = json.loads(rdz._read(store, elastic._comeback_key(2)))
    stale["ts"] = time.time() - (1.5 + elastic.REJOIN_GRACE_S + 1.0)
    rdz._publish(store, elastic._comeback_key(2),
                 json.dumps(stale, sort_keys=True))
    assert elastic.fresh_comeback(store, 2) is None


# ---------------------------------------------------------------------------
# Trigger claim / adoption and the decision.
# ---------------------------------------------------------------------------


def _coordinator(store, rank, ranks, generation=0):
    sup = _StubSup(store, _StubGroup(rank, ranks, generation))
    return elastic.ElasticCoordinator(store, sup), sup


def test_trigger_claimed_once_and_adopted(monkeypatch):
    monkeypatch.setenv("CGX_ELASTIC", "1")
    cfg.clear_registry()
    store = FakeStore()
    ca, _ = _coordinator(store, 0, [0, 1])
    cb, _ = _coordinator(store, 1, [0, 1])
    elastic.announce_join(store, global_rank=7, host="otherhost|9")
    s = np.zeros(4, np.float32)
    ca.on_step_boundary(s, 0)
    cb.on_step_boundary(s, 0)
    cb.on_step_boundary(s, 1)  # adopter picks the record up one step late
    assert ca._trigger is not None and cb._trigger is not None
    assert ca._trigger == cb._trigger
    assert ca._trigger["join_step"] == 2
    assert ca._trigger["generation"] == 1
    assert metrics.get("cgx.elastic.triggers") == 1


def test_elastic_disabled_is_inert(monkeypatch):
    monkeypatch.delenv("CGX_ELASTIC", raising=False)
    cfg.clear_registry()
    store = FakeStore()
    c, _ = _coordinator(store, 0, [0, 1])
    elastic.announce_join(store, global_rank=7, host="otherhost|9")
    s = np.zeros(4, np.float32)
    for step in range(4):
        assert c.on_step_boundary(s, step) is s
    assert c._trigger is None
    assert metrics.get("cgx.elastic.triggers") == 0


def test_decide_preserves_wanted_rank_and_ranks_donors(monkeypatch):
    monkeypatch.setenv("CGX_ELASTIC", "1")
    monkeypatch.setenv("CGX_JOIN_DONORS", "2")
    cfg.clear_registry()
    store = FakeStore()
    c, _ = _coordinator(store, 0, [0, 2, 3])
    k1 = elastic.announce_join(store, global_rank=1, host="ha|1")
    k2 = elastic.announce_join(store, global_rank=2, host="hb|2")  # taken
    trig = {"join_step": 12, "generation": 1, "n": k2,
            "key": elastic._trigger_key(0, 1)}
    votes = {
        0: {"load": 5.0, "host": "h0|10", "step": 10},
        2: {"load": 1.0, "host": "h2|12", "step": 10},
        3: {"load": 3.0, "host": "h3|13", "step": 10},
    }
    d = c._decide(10, trig, votes)
    assert d.generation == 1 and d.step == 10
    assert d.survivors == (0, 2, 3)
    # Wanted rank 1 is free -> preserved; wanted rank 2 is taken -> the
    # next free global rank past the survivors.
    assert d.joiners == (1, 4)
    assert d.intents == {1: k1, 4: k2}
    assert d.members == (0, 1, 2, 3, 4)
    # Donors: the two lowest-load survivors, lowest first (donor 0
    # ships the META frame).
    assert d.donors == (2, 3)
    assert d.hosts[1] == "ha|1" and d.hosts[4] == "hb|2"
    # Disagreeing votes can never admit: step -1 tells everyone to
    # consume the intents and move on.
    votes[3]["step"] = 9
    d2 = c._decide(10, trig, votes)
    assert d2.step == -1 and d2.joiners == ()


# ---------------------------------------------------------------------------
# Abort paths: survivors stay unharmed.
# ---------------------------------------------------------------------------


def test_vote_timeout_aborts_grow(monkeypatch):
    monkeypatch.setenv("CGX_ELASTIC", "1")
    monkeypatch.setenv("CGX_JOIN_TIMEOUT_MS", "500")
    cfg.clear_registry()
    store = FakeStore()
    c, sup = _coordinator(store, 0, [0, 1])  # rank 1 will never vote
    elastic.announce_join(store, global_rank=5, host="hx|5")
    s = np.arange(8, dtype=np.float32)
    c.on_step_boundary(s, 0)
    c.on_step_boundary(s, 1)
    out = c.on_step_boundary(s, 2)  # join step: admit runs, times out
    assert np.array_equal(out, s)
    assert rdz._read(store, "cgxjoin/g1/outcome") == "abort"
    assert sup.group.reconfigures == []
    assert c.consumed == 1
    assert metrics.get("cgx.elastic.join_aborts") >= 1
    # The consumed watermark holds: later boundaries never re-trigger.
    c.on_step_boundary(s, 3)
    assert c._trigger is None


def test_joiner_never_acks_aborts_and_survivors_carry_on(monkeypatch):
    monkeypatch.setenv("CGX_ELASTIC", "1")
    monkeypatch.setenv("CGX_JOIN_TIMEOUT_MS", "700")
    cfg.clear_registry()
    store = FakeStore()
    coords = {r: _coordinator(store, r, [0, 1]) for r in (0, 1)}
    elastic.announce_join(store, global_rank=4, host="hx|4")
    barrier = threading.Barrier(2, timeout=30)
    errs = {}

    def survivor(rank):
        try:
            c, _ = coords[rank]
            s = np.zeros(4, np.float32)
            for step in range(4):
                barrier.wait()
                c.on_step_boundary(s, step)
        except Exception:  # pragma: no cover - surfaced via errs
            errs[rank] = traceback.format_exc()

    ts = [threading.Thread(target=survivor, args=(r,)) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
        assert not t.is_alive()
    assert errs == {}, errs
    assert rdz._read(store, "cgxjoin/g1/outcome") == "abort"
    for r in (0, 1):
        c, sup = coords[r]
        assert sup.group.reconfigures == []
        assert sup.generation == 0
        assert c.consumed == 1
    assert metrics.get("cgx.elastic.join_aborts") >= 1
    assert metrics.get("cgx.elastic.triggers") == 1  # no re-trigger


# ---------------------------------------------------------------------------
# Full commit round: hand-rolled protocol joiner, bit-identity, reaping.
# ---------------------------------------------------------------------------


def _grad(step):
    return np.float32(0.5) * np.arange(64, dtype=np.float32) + np.float32(step)


def test_full_join_round_is_bit_identical_and_reaps(monkeypatch):
    monkeypatch.setenv("CGX_ELASTIC", "1")
    monkeypatch.setenv("CGX_JOIN_TIMEOUT_MS", "20000")
    cfg.clear_registry()
    store = FakeStore()
    coords = {r: _coordinator(store, r, [0, 1]) for r in (0, 1)}
    barrier = threading.Barrier(2, timeout=30)
    n_steps, errs, finals = 6, {}, {}

    def survivor(rank):
        try:
            c, _ = coords[rank]
            state = np.arange(64, dtype=np.float32)
            for step in range(n_steps):
                barrier.wait()
                state = c.on_step_boundary(state, step)
                state = state + _grad(step)
            finals[rank] = state
        except Exception:  # pragma: no cover
            errs[rank] = traceback.format_exc()

    def joiner():
        try:
            k = elastic.announce_join(store, global_rank=2,
                                      host="joinerhost|99")
            akey = elastic._admit_key(k)
            deadline = time.monotonic() + 20
            while not rdz._flag_set(store, akey):
                assert time.monotonic() < deadline, "never admitted"
                time.sleep(0.01)
            admit = json.loads(rdz._read(store, akey))
            decision = elastic.JoinDecision.from_json(json.dumps(admit))
            me = int(admit["you"])
            jbase = f"{elastic.JOIN_PREFIX}/g{decision.generation}"
            store.add(f"{jbase}/jack", 1)
            while not rdz._flag_set(store, f"{jbase}/outcome"):
                assert time.monotonic() < deadline, "no outcome"
                time.sleep(0.01)
            assert rdz._read(store, f"{jbase}/outcome") == "commit"
            streams = [
                elastic._stream_name(decision.generation, me, di)
                for di in range(len(decision.donors))
            ]
            meta, bufs = elastic._SnapshotReceiver(
                store, streams, deadline).receive()
            state, step = elastic._decode_into_skeleton(
                np.zeros(64, np.float32), meta, bufs)
            rdz._publish(store, f"{jbase}/shmok{me}", "1")
            store.add(f"{jbase}/ready", 1)
            while int(store.add(f"{jbase}/ready", 0)) < len(decision.members):
                assert time.monotonic() < deadline, "ready barrier"
                time.sleep(0.01)
            for idx in range(step, n_steps):
                state = state + _grad(idx)
            finals["joiner"] = state
            finals["join_step"] = step
            finals["me"] = me
        except Exception:  # pragma: no cover
            errs["joiner"] = traceback.format_exc()

    ts = [threading.Thread(target=survivor, args=(r,)) for r in (0, 1)]
    ts.append(threading.Thread(target=joiner))
    for t in ts:
        t.start()
    for t in ts:
        t.join(40)
        assert not t.is_alive()
    assert errs == {}, errs
    assert finals["me"] == 2
    # Post-join state is bit-identical on every rank to a rank that was
    # never gone.
    assert np.array_equal(finals[0], finals[1])
    assert np.array_equal(finals["joiner"], finals[0])
    for r in (0, 1):
        c, sup = coords[r]
        assert sup.generation == 1
        assert sup.group.global_ranks == [0, 1, 2]
        (members, gen, joiner_info) = sup.group.reconfigures[0]
        assert (members, gen) == ([0, 1, 2], 1)
        assert joiner_info == {2: "joinerhost|99"}
    assert metrics.get("cgx.elastic.grows") >= 1
    assert metrics.get("cgx.elastic.joins") == 0  # hand-rolled joiner
    # Store-key hygiene: the NEXT generation bump retires every g1 join
    # key and the consumed intent/admit records.
    assert any(k.startswith("cgxjoin/g1/") for k in store.keys())
    rdz.reap_all(store, 1)
    leftovers = [
        k for k in store.keys()
        if k.startswith("cgxjoin/g1/")
        or k.startswith("cgxelastic/intents/1")
        or k.startswith("cgxelastic/admit/")
        or k.startswith("cgxelastic/trig/")
    ]
    assert leftovers == [], leftovers
    assert metrics.get("cgx.elastic.keys_reaped") > 0


def test_rendezvous_bumps_reap_join_keys_across_generations():
    """Satellite (b): counting keys across three generation bumps — the
    claim winner's reap cascades into the join namespace via the
    registered reaper."""
    store = FakeStore()
    # Plant a finished generation-0 join round.
    d = elastic.JoinDecision(
        generation=0, members=(0, 1), survivors=(0,), joiners=(1,),
        donors=(0,), hosts={0: "h|1", 1: "h|2"}, intents={1: 1},
        intents_n=1, step=4, bits=0, bucket=0,
        trigger_key=elastic._trigger_key(0, 0),
    )
    rdz._publish(store, "cgxjoin/g0/decision", d.to_json())
    rdz._publish(store, elastic._intent_key(1), "{}")
    rdz._publish(store, elastic._admit_key(1), "{}")
    rdz._publish(store, d.trigger_key, "{}")
    rdz._publish(store, "cgxjoin/g0/v0", "{}")
    store.add("cgxjoin/g0/jack", 1)
    for g in (1, 2, 3):
        rdz.negotiate(store, generation=g, me=0, participants=[0],
                      timeout_s=5.0, poll_s=0.01)
        stale = [
            k for k in store.keys()
            if k.startswith(f"cgxrdz/g{g - 1}/")
            or k.startswith(f"cgxjoin/g{g - 1}/")
        ]
        assert stale == [], (g, stale)
    assert not any(k.startswith("cgxelastic/intents/1") for k in store.keys())
    assert not any(k.startswith("cgxelastic/admit/") for k in store.keys())
    # Only the current generation's rendezvous keys remain.
    old = [k for k in store.keys()
           if k.startswith(("cgxrdz/g0/", "cgxrdz/g1/", "cgxrdz/g2/"))]
    assert old == []


# ---------------------------------------------------------------------------
# Chaos soak: 4 bridge ranks, preempt + rejoin + leave again.
# ---------------------------------------------------------------------------

_EL_WS = 4
_EL_NUMEL = 4096
# Preempt OFF the snapshot cadence (snapshots at even steps) so the
# shrink rollback has real distance, exactly like the ISSUE 5 soak.
_EL_KILL_STEP = 5
_EL_RESPAWN_S = 1.5
_EL_TAIL = 12       # steps everyone runs past the join step
_EL_PHASE_B = 10    # steps the survivors run after rank 1 leaves again
_EL_STEP_SLEEP = 0.2
_EL_MAX_STEPS = 200


def _el_grad(global_rank: int, step: int) -> np.ndarray:
    rng = np.random.default_rng(1000 * (global_rank + 1) + step)
    return rng.normal(size=_EL_NUMEL).astype(np.float32)


def _el_step_fn(states, gens, sleep_s):
    import torch

    def step_fn(group, state, idx):
        states[idx] = state.copy()
        gens[idx] = group.generation
        t = torch.from_numpy(_el_grad(group.global_rank, idx).copy())
        group.allreduce([t]).wait()
        if sleep_s:
            time.sleep(sleep_s)
        return state - 0.01 * t.numpy()

    return step_fn


def _el_env(mdir):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["CGX_BRIDGE_TIMEOUT_MS"] = "2500"
    os.environ["CGX_RECOVERY_RETRIES"] = "1"
    os.environ["CGX_RECOVERY_BACKOFF_MS"] = "50"
    os.environ["CGX_SNAPSHOT_EVERY"] = "2"
    os.environ["CGX_METRICS_DIR"] = mdir
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    os.environ["CGX_ELASTIC"] = "1"
    os.environ["CGX_JOIN_TIMEOUT_MS"] = "20000"
    # The soak runs ~100 steps of collectives; the default 512-event
    # ring would age the mid-run grow/rejoin events out of the dump.
    os.environ["CGX_FLIGHTREC_CAP"] = "8192"


def _el_wait_crcs(store, tag, ranks, timeout_s=120.0):
    vals = {}
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        for r in ranks:
            if r not in vals:
                try:
                    vals[r] = int(store.get(f"cgxtest/{tag}/{r}").decode())
                except Exception:
                    pass
        if len(vals) == len(ranks):
            return vals
        time.sleep(0.05)
    raise RuntimeError(f"crc exchange {tag}: only {sorted(vals)} of {ranks}")


def _el_main(rank: int, initfile: str, mdir: str, outfile: str, q) -> None:
    try:
        sys.path.insert(0, _REPO)
        _el_env(mdir)
        if rank == 1:
            os.environ["CGX_FAULTS"] = (
                f"preempt:{_EL_RESPAWN_S}s@rank=1@step={_EL_KILL_STEP}"
            )
            os.environ[
                "CGX_PREEMPT_RESPAWN"
            ] = (f"{sys.executable} {os.path.abspath(__file__)} "
                 f"--joiner-child {initfile} {outfile} {mdir}")
            # The detached respawner re-runs this file as a script whose
            # module-level imports need the repo on the path.
            os.environ["PYTHONPATH"] = os.pathsep.join(
                [_REPO] + [p for p in
                           os.environ.get("PYTHONPATH", "").split(os.pathsep)
                           if p]
            )
        import datetime

        import torch.distributed as dist

        from torch_cgx_tpu.robustness import elastic as el
        from torch_cgx_tpu.robustness import faults as faults_mod
        from torch_cgx_tpu.robustness.supervisor import RecoverySupervisor
        from torch_cgx_tpu.torch_backend.backend import ProcessGroupCGX
        from torch_cgx_tpu.utils.logging import metrics as m

        store = dist.FileStore(initfile, _EL_WS)
        pg = ProcessGroupCGX(
            store, rank, _EL_WS, datetime.timedelta(seconds=60)
        )
        sup = RecoverySupervisor(store, pg)
        el.ElasticCoordinator(store, sup)
        states: dict = {}
        gens: dict = {}
        fn = _el_step_fn(states, gens, _EL_STEP_SLEEP)
        state = np.zeros(_EL_NUMEL, np.float32)
        step, end, max_wall = 0, None, 0.0
        while True:
            t0 = time.monotonic()
            state = sup.run_steps(state, 1, fn, start_step=step)
            max_wall = max(max_wall, time.monotonic() - t0)
            step += 1
            if end is None and sup.generation >= 2:
                js = min(i for i, g in gens.items() if g >= 2)
                end = js + _EL_TAIL
            if end is not None and step >= end:
                break
            if step >= _EL_MAX_STEPS:
                raise RuntimeError(
                    f"rank {rank}: the joiner never arrived within "
                    f"{_EL_MAX_STEPS} steps (generation {sup.generation})"
                )
        problems = []
        js = min(i for i, g in gens.items() if g >= 2)
        rb1 = min(i for i, g in gens.items() if g == 1)
        if sup.generation != 2:
            problems.append(f"generation {sup.generation} != 2 after grow")
        if sorted(sup.survivors) != [0, 1, 2, 3]:
            problems.append(f"survivors {sup.survivors} != [0,1,2,3]")
        if rb1 > _EL_KILL_STEP:
            problems.append(f"rollback step {rb1} > kill step")
        if m.get("cgx.elastic.grows") < 1:
            problems.append("no grow counted")
        if m.get("cgx.recovery.rejoin_rungs") < 1:
            problems.append("rejoin rung never preferred for the suspect")
        # Survivors never stall longer than the join bound: the worst
        # single step covers one bridge timeout + the grow rendezvous,
        # both far under CGX_JOIN_TIMEOUT_MS.
        if max_wall > 15.0:
            problems.append(f"a step stalled {max_wall:.1f}s")
        endA = end
        if rank == 0:
            store.set("cgxtest/bounds", json.dumps(
                {"rb1": rb1, "js": js, "endA": endA}))
        store.set(f"cgxtest/crcA/{rank}", str(zlib.crc32(state.tobytes())))
        crcs = _el_wait_crcs(store, "crcA", [0, 1, 2, 3])
        if len(set(crcs.values())) != 1:
            problems.append(f"post-join state diverged across ranks: {crcs}")
        # -- control replays: fault-free era-by-era reruns chained on
        # the rolled-back anchor state. Gradients are state-independent,
        # so the joiner (whose history starts at the join step) can
        # participate in the ws-4 era's collectives from its own anchor;
        # every era starts at a reconfigure (fresh error feedback),
        # matching the fresh control groups.
        os.environ.pop("CGX_FAULTS", None)
        faults_mod.reset_injectors()
        cfn = _el_step_fn({}, {}, 0.0)
        # Only ranks 0/2/3 reach this point: rank 1 died at the preempt
        # and its respawn runs _joiner_child_main instead.
        pgA = ProcessGroupCGX(
            store, [0, 2, 3].index(rank), 3,
            datetime.timedelta(seconds=120),
            generation=600, global_ranks=[0, 2, 3],
        )
        control = states[rb1].copy()
        for idx in range(rb1, js):
            control = cfn(pgA, control, idx)
        pgB = ProcessGroupCGX(
            store, rank, _EL_WS, datetime.timedelta(seconds=120),
            generation=601, global_ranks=[0, 1, 2, 3],
        )
        for idx in range(js, endA):
            control = cfn(pgB, control, idx)
        if not np.array_equal(state, control):
            problems.append(
                "phase A state differs from fault-free control replay "
                f"(max abs diff {np.abs(state - control).max()})"
            )
        pgA.shutdown()
        pgB.shutdown()
        # -- phase B: rank 1 leaves again (its process exits after the
        # control); the survivors shrink back and finish.
        stateB = sup.run_steps(state, _EL_PHASE_B, fn, start_step=endA)
        if sup.generation != 3:
            problems.append(f"generation {sup.generation} != 3 after "
                            "second shrink")
        if sorted(sup.survivors) != [0, 2, 3]:
            problems.append(f"final survivors {sup.survivors} != [0,2,3]")
        rb3 = min(i for i, g in gens.items() if g == 3)
        pgC = ProcessGroupCGX(
            store, [0, 2, 3].index(rank), 3,
            datetime.timedelta(seconds=120),
            generation=602, global_ranks=[0, 2, 3],
        )
        controlB = states[rb3].copy()
        for idx in range(rb3, endA + _EL_PHASE_B):
            controlB = cfn(pgC, controlB, idx)
        if not np.array_equal(stateB, controlB):
            problems.append(
                "phase B state differs from fault-free control replay "
                f"(max abs diff {np.abs(stateB - controlB).max()})"
            )
        store.set(f"cgxtest/crcB/{rank}",
                  str(zlib.crc32(stateB.tobytes())))
        crcsB = _el_wait_crcs(store, "crcB", [0, 2, 3])
        if len(set(crcsB.values())) != 1:
            problems.append(f"final state diverged: {crcsB}")
        # Zero checkpoint files on disk: the whole lifecycle ran from
        # memory — nothing checkpoint-shaped may exist anywhere the run
        # writes.
        ckpt_files = [
            p for p in glob.glob(os.path.join(mdir, "**", "*"),
                                 recursive=True)
            if "ckpt" in os.path.basename(p).lower()
            or "checkpoint" in os.path.basename(p).lower()
        ]
        if ckpt_files:
            problems.append(f"checkpoint files on disk: {ckpt_files}")
        pgC.shutdown()
        pg.shutdown()
        q.put((rank, "; ".join(problems) or None))
    except Exception:
        q.put((rank, traceback.format_exc()))


def _joiner_child_main(initfile: str, outfile: str, mdir: str) -> None:
    """Entry point for the respawned rank 1 (CGX_PREEMPT_RESPAWN runs
    this file as a script). Reports through ``outfile`` — the detached
    process has no queue to the pytest parent."""
    report = {"problems": []}
    try:
        sys.path.insert(0, _REPO)
        os.environ.pop("CGX_FAULTS", None)
        os.environ.pop("CGX_PREEMPT_RESPAWN", None)
        _el_env(mdir)
        import datetime

        import torch.distributed as dist

        from torch_cgx_tpu.robustness import elastic as el
        from torch_cgx_tpu.robustness.supervisor import RecoverySupervisor
        from torch_cgx_tpu.torch_backend.backend import ProcessGroupCGX
        from torch_cgx_tpu.utils.logging import metrics as m

        store = dist.FileStore(initfile, _EL_WS)
        t0 = time.perf_counter()
        res = el.join(store, np.zeros(_EL_NUMEL, np.float32), global_rank=1)
        join_ms = (time.perf_counter() - t0) * 1000.0
        problems = report["problems"]
        if res.generation != 2:
            problems.append(f"joined at generation {res.generation} != 2")
        if res.members != [0, 1, 2, 3]:
            problems.append(f"members {res.members}")
        sup = RecoverySupervisor(store, res.group)
        el.ElasticCoordinator(store, sup,
                              consumed=res.decision.intents_n)
        states: dict = {}
        gens: dict = {}
        fn = _el_step_fn(states, gens, _EL_STEP_SLEEP)
        endA = res.step + _EL_TAIL
        final = sup.run_steps(res.state.copy(), endA - res.step, fn,
                              start_step=res.step)
        store.set("cgxtest/crcA/1", str(zlib.crc32(final.tobytes())))
        crcs = _el_wait_crcs(store, "crcA", [0, 1, 2, 3])
        if len(set(crcs.values())) != 1:
            problems.append(f"joiner diverged from survivors: {crcs}")
        bounds = json.loads(store.get("cgxtest/bounds").decode())
        if bounds["js"] != res.step:
            problems.append(
                f"survivors saw join step {bounds['js']}, joiner "
                f"resumed at {res.step}"
            )
        # The joiner's control: a fault-free replay of the ws-4 era from
        # its received state must reproduce its final state bit-for-bit
        # — the snapshot pages handed it exactly the state a rank that
        # was never gone would hold.
        pgB = ProcessGroupCGX(
            store, 1, _EL_WS, datetime.timedelta(seconds=120),
            generation=601, global_ranks=[0, 1, 2, 3],
        )
        cfn = _el_step_fn({}, {}, 0.0)
        control = res.state.copy()
        for idx in range(res.step, endA):
            control = cfn(pgB, control, idx)
        if not np.array_equal(final, control):
            problems.append(
                "joiner state differs from fault-free control "
                f"(max abs diff {np.abs(final - control).max()})"
            )
        if m.get("cgx.elastic.joins") < 1:
            problems.append("join counter not bumped")
        report.update(
            generation=res.generation, step=res.step, join_ms=join_ms,
            crc=crcs.get(1),
        )
        pgB.shutdown()
        # Leave WITHOUT ceremony: this exit IS the soak's second shrink.
    except Exception:
        report["problems"].append(traceback.format_exc())
    tmp = outfile + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f)
    os.rename(tmp, outfile)
    os._exit(1 if report["problems"] else 0)


# Slow tier: ~45 s of real-process soak on a 1-core box — the unit
# tests above cover every protocol leg in-process; run via -m faults
# or the full (unfiltered) sweep.
@pytest.mark.slow
@pytest.mark.torch_bridge
def test_chaos_soak_preempt_rejoin_shrink(tmp_path):
    """ISSUE 16 chaos acceptance: 4-rank bridge run, rank 1 SIGKILLed
    mid-training by ``preempt`` and respawned by the detached respawner
    — it rejoins at a bumped generation with zero checkpoint files on
    disk, survivors never stall past the join bound, every era is
    bit-identical to fault-free control replays, and when the rejoined
    rank leaves again the survivors shrink back and finish clean."""
    mdir = str(tmp_path / "metrics")
    outfile = str(tmp_path / "joiner.json")
    initfile = tempfile.mktemp(prefix="cgx_elastic_store_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_el_main, args=(r, initfile, mdir, outfile, q))
        for r in range(_EL_WS)
    ]
    for p in procs:
        p.start()
    results = {}
    for _ in range(3):  # rank 1 preempts; its respawn reports via file
        rank, err = q.get(timeout=300)
        results[rank] = err
    for p in procs:
        p.join(timeout=60)
        if p.is_alive():
            p.terminate()
    assert sorted(results) == [0, 2, 3], results
    for rank, err in sorted(results.items()):
        assert err is None, f"rank {rank}: {err}"
    from torch_cgx_tpu.robustness.faults import KILL_EXIT_CODE

    assert procs[1].exitcode == KILL_EXIT_CODE, procs[1].exitcode
    # The detached joiner's report.
    deadline = time.monotonic() + 120
    while not os.path.exists(outfile) and time.monotonic() < deadline:
        time.sleep(0.2)
    assert os.path.exists(outfile), "the respawned joiner never reported"
    joiner = json.load(open(outfile))
    assert joiner["problems"] == [], joiner["problems"]
    assert joiner["generation"] == 2
    assert joiner["join_ms"] > 0
    if os.path.exists(initfile):
        os.unlink(initfile)
    # -- flight recorder: the whole membership story is audited --
    path = os.path.join(mdir, "flightrec-rank0.jsonl")
    assert os.path.exists(path), (
        os.listdir(mdir) if os.path.isdir(mdir) else "no metrics dir"
    )
    events = [json.loads(line) for line in open(path)]
    el_ev = [e for e in events if e.get("kind") == "elastic"]
    assert any(e.get("phase") == "grow" for e in el_ev), el_ev
    rec = [e for e in events if e.get("kind") == "recovery"]
    assert any(e.get("phase") == "rejoin_rung" for e in rec), \
        [e.get("phase") for e in rec]
    assert any(
        e.get("phase") == "evicted_peers" and e.get("evicted") == [1]
        for e in rec
    )
    # -- report CLI renders the membership section --
    import subprocess as sp

    proc = sp.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"),
         mdir, "--json"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    js = json.loads(proc.stdout)
    assert js.get("membership"), js.keys()
    assert js["membership"]["grows"] >= 1
    assert js["membership"]["joiners"], js["membership"]
    text = sp.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"), mdir],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert text.returncode == 0
    assert "== membership" in text.stdout


if __name__ == "__main__":
    if len(sys.argv) >= 5 and sys.argv[1] == "--joiner-child":
        _joiner_child_main(sys.argv[2], sys.argv[3], sys.argv[4])
    else:  # pragma: no cover
        sys.exit(f"usage: {sys.argv[0]} --joiner-child "
                 "<initfile> <outfile> <mdir>")
