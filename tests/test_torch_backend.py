"""Multi-process integration tests of the ``"cgx"`` torch.distributed
backend — the rebuild of the reference's test strategy (SURVEY.md §4,
/root/reference/test/test_cgx.py): real multi-process launches, the
bit-exactness oracle on constant buckets, the analytic ∞-norm error
envelope on varying data, and the uncompressed fallback — plus what the
reference lacks: DDP comm-hook coverage and both reduction algorithms.

The reference launches via ``mpirun``; here each test spawns fresh Python
processes rendezvousing over a file store (no MPI on TPU hosts).
"""

from __future__ import annotations

import atexit
import multiprocessing as mp
import os
import sys
import tempfile
import traceback

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pool_worker(rank: int, ws: int, task_q, result_q) -> None:
    """Persistent rank process: imports once, then runs one worker body per
    task with a fresh process group (the reference's setUp/tearDown cycle,
    test_cgx.py:53-67) — spawning + torch import per test was ~80% of the
    suite's wall time."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS", None)
    sys.path.insert(0, _REPO)
    # Debug hook (name deliberately NOT CGX_-prefixed: the conftest env
    # isolation fixture strips that prefix before every test): periodic
    # all-thread stack dumps + a task-receipt trace, per pid, for
    # diagnosing hung/deadlocked rank pools.
    trace = None
    if os.environ.get("CGXTEST_DUMP_STACKS"):
        import faulthandler

        dump_file = open(f"/tmp/cgx_stacks_r{rank}_{os.getpid()}.txt", "w")
        faulthandler.dump_traceback_later(
            int(os.environ["CGXTEST_DUMP_STACKS"]), repeat=True,
            file=dump_file,
        )

        def trace(msg):  # noqa: F811
            with open("/tmp/cgx_pool_trace.log", "a") as f:
                f.write(f"{os.getpid()} r{rank} ws{ws} {msg}\n")

    import torch.distributed as dist
    import torch_cgx_tpu.torch_backend  # noqa: F401 — registers "cgx"
    from torch_cgx_tpu import config as cgx_config

    while True:
        item = task_q.get()
        if item is None:
            return
        target_name, initfile = item
        if trace is not None:
            trace(f"GOT {target_name}")
        env_before = {
            k: v for k, v in os.environ.items() if k.startswith("CGX_")
        }
        err = "task did not complete"  # overwritten by success/except
        try:
            cgx_config.clear_registry()
            dist.init_process_group(
                "cgx", init_method=f"file://{initfile}", rank=rank,
                world_size=ws,
            )
            globals()[target_name](rank, ws)
            dist.barrier()
            err = None
            if trace is not None:
                trace(f"OK {target_name}")
        except Exception:
            err = traceback.format_exc()
            if trace is not None:
                trace(f"ERR {target_name}")
        finally:
            # Destroy BEFORE reporting: the harness unlinks the store's
            # backing file as soon as both results arrive, and a FileStore
            # op on a deleted file spins for the full store timeout — the
            # report must therefore be the LAST thing a task does.
            try:
                dist.destroy_process_group()
            except Exception:
                pass
            if trace is not None:
                trace(f"DESTROYED {target_name}")
            for k in [k for k in os.environ if k.startswith("CGX_")]:
                if k not in env_before:
                    os.environ.pop(k)
            os.environ.update(env_before)
            result_q.put((rank, err))


class _RankPool:
    def __init__(self, ws: int):
        self.ws = ws
        ctx = mp.get_context("spawn")
        self.task_qs = [ctx.Queue() for _ in range(ws)]
        self.result_q = ctx.Queue()
        self.procs = [
            ctx.Process(
                target=_pool_worker,
                args=(r, ws, self.task_qs[r], self.result_q),
                daemon=True,
            )
            for r in range(ws)
        ]
        for p in self.procs:
            p.start()

    def alive(self) -> bool:
        return all(p.is_alive() for p in self.procs)

    def run(self, target_name: str, timeout: float):
        import time as _time

        initfile = tempfile.mktemp(prefix="cgx_test_store_")
        for q in self.task_qs:
            q.put((target_name, initfile))
        errors = []
        timed_out = False
        deadline = _time.monotonic() + timeout
        received: set = set()

        def take(rank, err):
            received.add(rank)
            if err is not None:
                errors.append(f"rank {rank}:\n{err}")

        while len(received) < self.ws:
            try:
                take(*self.result_q.get(timeout=2.0))
            except Exception:
                if not self.alive():
                    # Drain results that arrived concurrently with the death
                    # so surviving ranks' tracebacks aren't discarded.
                    while True:
                        try:
                            take(*self.result_q.get_nowait())
                        except Exception:
                            break
                    dead = [
                        r for r, p in enumerate(self.procs)
                        if not p.is_alive() and r not in received
                    ]
                    if dead:
                        errors.append(f"rank(s) {dead} died without a result")
                    timed_out = True
                    break
                if _time.monotonic() >= deadline:
                    errors.append(
                        "timeout waiting for a rank (possible deadlock)"
                    )
                    timed_out = True
                    break
        if os.path.exists(initfile):
            os.unlink(initfile)
        return errors, timed_out

    def shutdown(self) -> None:
        for q in self.task_qs:
            try:
                q.put(None)
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()


_POOLS: dict = {}


def _shutdown_pools() -> None:
    for pool in _POOLS.values():
        pool.shutdown()
    _POOLS.clear()


atexit.register(_shutdown_pools)


def _launch(target, ws: int, timeout: float = 240.0) -> None:
    pool = _POOLS.get(ws)
    if pool is None or not pool.alive():
        if pool is not None:
            pool.shutdown()
        pool = _RankPool(ws)
        _POOLS[ws] = pool
    errors, timed_out = pool.run(target.__name__, timeout)
    if timed_out or not pool.alive():
        # A hung or dead rank poisons the pool — tear it down so the next
        # test gets a fresh one.
        pool.shutdown()
        _POOLS.pop(ws, None)
    assert not errors, "\n".join(errors)


# ---------------------------------------------------------------------------
# Worker bodies (run inside spawned ranks).
# ---------------------------------------------------------------------------


def _sum_expect(ws: int) -> float:
    return float(sum(r + 1 for r in range(ws)))


def _check_exact(ws: int, rank: int, algo: str) -> None:
    """Constant buckets quantize exactly at any bits — allreduce must be
    bit-exact (reference test_compressed_exact, test_cgx.py:69-78)."""
    import torch
    import torch.distributed as dist

    os.environ["CGX_INNER_REDUCTION_TYPE"] = algo
    for bits in (2, 4, 8):
        os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = str(bits)
        for n in (1, 17, 500, 1000, 100_000):
            for dtype in (torch.float32, torch.bfloat16):
                t = torch.full((n,), float(rank + 1), dtype=dtype)
                dist.all_reduce(t)
                want = torch.full((n,), _sum_expect(ws), dtype=dtype)
                assert torch.equal(t, want), (algo, bits, n, dtype, t[:4])
    # int32 WITH bits set: ints bypass compression and stay bit-exact —
    # the reference's exactness sweep includes int32 (test_cgx.py:9-19).
    ti = torch.full((1000,), rank + 1, dtype=torch.int32)
    dist.all_reduce(ti)
    assert torch.equal(
        ti, torch.full((1000,), int(_sum_expect(ws)), dtype=torch.int32)
    )
    os.environ.pop("CGX_INNER_REDUCTION_TYPE")
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS")


def _check_envelope(ws: int, rank: int, algo: str) -> None:
    """Varying data honors the analytic error envelope
    (reference test_compressed_non_exact, test_cgx.py:80-93)."""
    import torch
    import torch.distributed as dist

    os.environ["CGX_INNER_REDUCTION_TYPE"] = algo
    for bits in (2, 4, 8):
        for bucket in (64, 512, 2048):
            os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = str(bits)
            os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = str(bucket)
            for n in (128, 4096, 100_000):
                x = torch.arange(n, dtype=torch.float32) / n * (rank + 1)
                exact = (
                    torch.arange(n, dtype=torch.float32) / n * _sum_expect(ws)
                )
                t = x.clone()
                dist.all_reduce(t)
                err = (t - exact).abs().max().item()
                # Per-rank bucket range <= (rank+1)*min(bucket,n)/n; one
                # quantization per contribution plus the requant step gives
                # the reference's ws*(ws+1)-shaped envelope, scaled to this
                # data's magnitude.
                bound = (
                    2 * min(bucket, n) / (2**bits - 1) * ws * (ws + 1) / n
                )
                assert err < bound, (algo, bits, bucket, n, err, bound)
    for k in (
        "CGX_INNER_REDUCTION_TYPE",
        "CGX_COMPRESSION_QUANTIZATION_BITS",
        "CGX_COMPRESSION_BUCKET_SIZE",
    ):
        os.environ.pop(k)


def _worker_collectives(rank: int, ws: int) -> None:
    import numpy as np
    import torch
    import torch.distributed as dist

    _check_exact(ws, rank, "SRA")
    _check_exact(ws, rank, "RING")
    _check_envelope(ws, rank, "SRA")
    _check_envelope(ws, rank, "RING")

    # Debug all-to-all reduction (CGX_DEBUG_ALL_TO_ALL_REDUCTION analogue).
    os.environ["CGX_DEBUG_ALL_TO_ALL_REDUCTION"] = "1"
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    t = torch.full((5000,), float(rank + 1))
    dist.all_reduce(t)
    assert torch.equal(t, torch.full((5000,), _sum_expect(ws)))
    os.environ.pop("CGX_DEBUG_ALL_TO_ALL_REDUCTION")

    # Dummy (pass-through) compression must be exact on any data.
    os.environ["CGX_DEBUG_DUMMY_COMPRESSION"] = "1"
    x = torch.linspace(-3, 7, 4096) * (rank + 1)
    exact = torch.linspace(-3, 7, 4096) * _sum_expect(ws)
    t = x.clone()
    dist.all_reduce(t)
    assert torch.allclose(t, exact, atol=1e-4), (t - exact).abs().max()
    os.environ.pop("CGX_DEBUG_DUMMY_COMPRESSION")
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS")

    # Uncompressed fallback: bits=32 (default) floats stay exact, ints sum.
    x = torch.linspace(-1, 1, 1000) * (rank + 1)
    t = x.clone()
    dist.all_reduce(t)
    assert torch.allclose(t, torch.linspace(-1, 1, 1000) * _sum_expect(ws))
    ti = torch.full((64,), rank + 1, dtype=torch.int64)
    dist.all_reduce(ti)
    assert ti[0].item() == int(_sum_expect(ws))

    # MIN / MAX / PRODUCT ops take the plain path.
    t = torch.full((10,), float(rank + 1))
    dist.all_reduce(t, op=dist.ReduceOp.MAX)
    assert t[0].item() == ws
    t = torch.full((10,), float(rank + 1))
    dist.all_reduce(t, op=dist.ReduceOp.MIN)
    assert t[0].item() == 1.0

    # broadcast / allgather / gather / scatter / alltoall / send-recv.
    tb = torch.arange(8, dtype=torch.float32) if rank == 0 else torch.zeros(8)
    dist.broadcast(tb, src=0)
    assert torch.equal(tb, torch.arange(8, dtype=torch.float32))

    gathered = [torch.zeros(4) for _ in range(ws)]
    dist.all_gather(gathered, torch.full((4,), float(rank)))
    for j in range(ws):
        assert torch.equal(gathered[j], torch.full((4,), float(j)))

    gl = [torch.zeros(3) for _ in range(ws)] if rank == 0 else None
    dist.gather(torch.full((3,), float(rank + 10)), gl, dst=0)
    if rank == 0:
        for j in range(ws):
            assert torch.equal(gl[j], torch.full((3,), float(j + 10)))

    out = torch.zeros(2)
    sl = [torch.full((2,), float(j)) for j in range(ws)] if rank == 0 else None
    dist.scatter(out, sl, src=0)
    assert torch.equal(out, torch.full((2,), float(rank)))

    outs = [torch.zeros(2) for _ in range(ws)]
    ins = [torch.full((2,), float(rank * ws + j)) for j in range(ws)]
    dist.all_to_all(outs, ins)
    for j in range(ws):
        assert torch.equal(outs[j], torch.full((2,), float(j * ws + rank)))

    if ws >= 2:
        if rank == 0:
            dist.send(torch.arange(5, dtype=torch.float32), dst=1)
        elif rank == 1:
            r = torch.zeros(5)
            dist.recv(r, src=0)
            assert torch.equal(r, torch.arange(5, dtype=torch.float32))
    dist.barrier()

    # reduce to root.
    t = torch.full((6,), float(rank + 1))
    dist.reduce(t, dst=0)
    if rank == 0:
        assert torch.equal(t, torch.full((6,), _sum_expect(ws)))

    # Stochastic rounding stays within the envelope and remains exact on
    # constants-in-expectation is not testable cheaply; check envelope only.
    os.environ["CGX_STOCHASTIC_ROUNDING"] = "1"
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    n, bucket = 4096, 512
    x = torch.arange(n, dtype=torch.float32) / n * (rank + 1)
    exact = torch.arange(n, dtype=torch.float32) / n * _sum_expect(ws)
    t = x.clone()
    dist.all_reduce(t)
    err = (t - exact).abs().max().item()
    bound = 2 * min(bucket, n) / (2**4 - 1) * ws * (ws + 1) / n
    assert 0 < err < bound, (err, bound)
    os.environ.pop("CGX_STOCHASTIC_ROUNDING")
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS")

    # Per-layer registry: one bucket, two layers with different configs —
    # the framed wire applies each layer's own bits.
    from torch_cgx_tpu import config as cfg

    cfg.clear_registry()
    cfg.register_layer(0, 0, 3000, 2, 256)
    cfg.register_layer(0, 1, 1096, 32, 0)  # uncompressed layer
    x = torch.cat(
        [
            torch.full((3000,), float(rank + 1)),
            torch.linspace(-1, 1, 1096) * (rank + 1),
        ]
    )
    t = x.clone()
    dist.all_reduce(t)
    assert torch.equal(t[:3000], torch.full((3000,), _sum_expect(ws)))
    assert torch.allclose(
        t[3000:], torch.linspace(-1, 1, 1096) * _sum_expect(ws), atol=1e-5
    ), "uncompressed layer must be exact"
    cfg.clear_registry()


def _worker_alltoall_base(rank: int, ws: int) -> None:
    """dist.all_to_all_single — even split (MPI_Alltoall analogue) and
    uneven splits (MPI_Alltoallv), ProcessGroupCGX.cc:638-705."""
    import torch
    import torch.distributed as dist

    # Even split: rank r sends slice j the values r*ws + j.
    inp = torch.arange(ws * 3, dtype=torch.float32) + rank * ws * 3
    out = torch.empty(ws * 3, dtype=torch.float32)
    dist.all_to_all_single(out, inp)
    want = torch.cat(
        [torch.arange(3, dtype=torch.float32) + j * ws * 3 + rank * 3
         for j in range(ws)]
    )
    assert torch.equal(out, want), (rank, out, want)

    # Non-contiguous output (stride-2 column view): results must land in
    # the caller's tensor, not a detached reshape copy.
    big = torch.zeros(ws * 3, 2)
    outc = big[:, 0]
    dist.all_to_all_single(outc, inp)
    assert torch.equal(big[:, 0], want), (rank, big[:, 0], want)
    assert torch.equal(big[:, 1], torch.zeros(ws * 3))

    # Even split, 2-D rows (dim-0 divides; trailing dims ride along).
    inp2 = torch.arange(ws * 2 * 4, dtype=torch.float32).reshape(ws * 2, 4) + rank * 1000
    out2 = torch.empty_like(inp2)
    dist.all_to_all_single(out2, inp2)
    for j in range(ws):
        want_j = (
            torch.arange(2 * 4, dtype=torch.float32).reshape(2, 4)
            + rank * 2 * 4 + j * 1000
        )
        assert torch.equal(out2[j * 2 : (j + 1) * 2], want_j)

    # Uneven splits (alltoallv): rank r sends j a block of (j + 1) rows;
    # rank r receives (r + 1) rows from every peer.
    in_splits = [j + 1 for j in range(ws)]
    out_splits = [rank + 1] * ws
    inp3 = torch.cat(
        [torch.full((j + 1,), float(rank * 100 + j)) for j in range(ws)]
    )
    out3 = torch.empty(sum(out_splits), dtype=torch.float32)
    dist.all_to_all_single(
        out3, inp3, output_split_sizes=out_splits, input_split_sizes=in_splits
    )
    want3 = torch.cat(
        [torch.full((rank + 1,), float(j * 100 + rank)) for j in range(ws)]
    )
    assert torch.equal(out3, want3), (rank, out3, want3)

    # Uneven with zero-sized splits and int64 payloads.
    in_splits = [0 if j % 2 else 2 for j in range(ws)]
    out_splits = [0 if rank % 2 else 2 for _ in range(ws)]
    inp4 = torch.arange(sum(in_splits), dtype=torch.int64) + rank * 10
    out4 = torch.empty(sum(out_splits), dtype=torch.int64)
    dist.all_to_all_single(
        out4, inp4, output_split_sizes=out_splits, input_split_sizes=in_splits
    )
    if rank % 2 == 0:
        want4 = torch.cat(
            [torch.arange(2, dtype=torch.int64)
             + j * 10 + sum(in_splits[:rank]) for j in range(ws)]
        )
        assert torch.equal(out4, want4), (rank, out4, want4)
    else:
        assert out4.numel() == 0

    # Mismatched split-size validation raises on the calling thread.
    try:
        dist.all_to_all_single(
            torch.empty(4), torch.empty(5),
            output_split_sizes=[], input_split_sizes=[],
        )
    except Exception:
        pass
    else:
        raise AssertionError("uneven dim-0 with even split did not raise")


def _worker_ddp(rank: int, ws: int) -> None:
    import torch
    import torch.distributed as dist
    import torch.nn as nn
    import torch_cgx_tpu.torch_backend as tb
    from torch_cgx_tpu import config as cfg

    torch.manual_seed(1234)
    model = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 10))
    ddp = nn.parallel.DistributedDataParallel(model)
    state = tb.CGXState(
        None,
        compression_params={"bits": 4, "bucket_size": 512},
        layer_min_size=64,
    )
    ddp.register_comm_hook(state, tb.cgx_hook)
    opt = torch.optim.SGD(ddp.parameters(), lr=0.05)
    loss_fn = nn.CrossEntropyLoss()
    torch.manual_seed(100 + rank)  # rank-local data
    for _ in range(8):
        x = torch.randn(16, 32)
        y = torch.randint(0, 10, (16,))
        opt.zero_grad()
        loss_fn(ddp(x), y).backward()
        opt.step()

    # Registration happened at step 2 with the stabilized bucket layout.
    assert cfg.registered_buckets(), "no layers registered by cgx_hook"
    n_layers = sum(
        len(cfg.registered_layer_sizes(b)) for b in cfg.registered_buckets()
    )
    assert n_layers == 4, n_layers  # 2 weights + 2 biases
    bits = sorted(
        cfg.get_layer_config((b, i)).bits
        for b in cfg.registered_buckets()
        for i in range(len(cfg.registered_layer_sizes(b)))
    )
    assert bits[0] == 4 and bits[-1] == 32, bits  # weights 4-bit, biases raw

    # Replicas must stay bit-identical (quantized allreduce is symmetric).
    for p in ddp.parameters():
        buf = [torch.zeros_like(p) for _ in range(ws)]
        dist.all_gather(buf, p.detach())
        for b in buf[1:]:
            assert torch.equal(b, buf[0]), "replicas diverged"


def _worker_unsupported(rank: int, ws: int) -> None:
    import torch
    import torch.distributed as dist

    # allreduce_coalesced keeps the reference's NotImplementedError
    # (ProcessGroupCGX.cc:422-428); reduce_scatter/_allgather_base are now
    # implemented (FSDP needs them) — covered by _worker_sharded_collectives.
    try:
        dist.all_reduce_coalesced([torch.ones(4), torch.ones(8)])
        raise AssertionError("allreduce_coalesced should be unsupported")
    except (NotImplementedError, RuntimeError):
        pass


def _worker_sharded_collectives(rank: int, ws: int) -> None:
    import os

    import torch
    import torch.distributed as dist

    n = 512
    # all_gather_into_tensor (FSDP param gather)
    inp = torch.full((n,), float(rank + 1))
    out = torch.zeros(ws * n)
    dist.all_gather_into_tensor(out, inp)
    for j in range(ws):
        assert torch.equal(out[j * n : (j + 1) * n], torch.full((n,), float(j + 1)))

    # reduce_scatter_tensor, uncompressed (bits default 32): exact sums
    flat = torch.arange(ws * n, dtype=torch.float32) * (rank + 1)
    mine = torch.zeros(n)
    dist.reduce_scatter_tensor(mine, flat)
    want = torch.arange(rank * n, (rank + 1) * n, dtype=torch.float32) * sum(
        r + 1 for r in range(ws)
    )
    assert torch.allclose(mine, want), (mine[:4], want[:4])

    # reduce_scatter_tensor, compressed 4-bit: constant chunks exact
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    flat = torch.full((ws * n,), float(rank + 1))
    mine = torch.zeros(n)
    dist.reduce_scatter_tensor(mine, flat)
    assert torch.equal(mine, torch.full((n,), float(sum(r + 1 for r in range(ws)))))

    # compressed varying data honors the envelope
    bits, bucket = 4, 512
    os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = str(bucket)
    base = torch.arange(ws * n, dtype=torch.float32) / n
    flat = base * (rank + 1)
    mine = torch.zeros(n)
    dist.reduce_scatter_tensor(mine, flat)
    exact = base[rank * n : (rank + 1) * n] * sum(r + 1 for r in range(ws))
    err = (mine - exact).abs().max().item()
    bound = 2 * min(bucket, n) / (2**bits - 1) * ws * (ws + 1) / n
    assert err < bound, (err, bound)
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS")
    os.environ.pop("CGX_COMPRESSION_BUCKET_SIZE")

    # int dtype + MAX op takes the raw path
    flat = torch.arange(ws * n, dtype=torch.int64) * (rank + 1)
    mine = torch.zeros(n, dtype=torch.int64)
    dist.reduce_scatter_tensor(mine, flat, op=dist.ReduceOp.MAX)
    want = torch.arange(rank * n, (rank + 1) * n, dtype=torch.int64) * ws
    assert torch.equal(mine, want)

    # list-form reduce_scatter
    ins = [torch.full((64,), float(rank + 1 + j)) for j in range(ws)]
    mine = torch.zeros(64)
    dist.reduce_scatter(mine, ins)
    assert torch.equal(
        mine, torch.full((64,), float(sum(r + 1 + rank for r in range(ws))))
    )
    dist.barrier()


def _worker_ddp_torch_powersgd(rank: int, ws: int) -> None:
    """torch's BUILT-IN PowerSGD DDP comm hook over the cgx process group:
    the hook allreduces low-rank factor tensors through our backend, so
    this exercises plain-float allreduce + the hook protocol end-to-end
    (interop the reference never demonstrates)."""
    import torch
    import torch.distributed as dist
    import torch.nn as nn
    from torch.distributed.algorithms.ddp_comm_hooks import (
        powerSGD_hook as psgd,
    )

    torch.manual_seed(7)
    model = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 10))
    ddp = nn.parallel.DistributedDataParallel(model)
    state = psgd.PowerSGDState(
        process_group=None, matrix_approximation_rank=2,
        start_powerSGD_iter=2,
    )
    ddp.register_comm_hook(state, psgd.powerSGD_hook)
    opt = torch.optim.SGD(ddp.parameters(), lr=0.05)
    loss_fn = nn.CrossEntropyLoss()
    torch.manual_seed(100 + rank)
    losses = []
    for _ in range(10):
        x = torch.randn(16, 32)
        y = torch.randint(0, 10, (16,))
        opt.zero_grad()
        loss = loss_fn(ddp(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    for p in ddp.parameters():
        buf = [torch.zeros_like(p) for _ in range(ws)]
        dist.all_gather(buf, p.detach())
        for b in buf[1:]:
            assert torch.equal(b, buf[0]), "replicas diverged"


def _worker_fsdp(rank: int, ws: int) -> None:
    """Fully-sharded (ZeRO-3 style) training through the cgx backend: each
    rank owns a 1/ws shard of the flat parameters, all_gather_into_tensor
    materializes them for compute, reduce_scatter_tensor averages gradient
    shards — exactly the two collectives torch FSDP is built from (the
    reference throws on both, so FSDP can never run on it; torch's FSDP
    *wrapper* additionally refuses CPU-only hosts, hence the manual loop —
    the collective workflow is identical). VERDICT r2 missing #4."""
    import os

    import torch
    import torch.distributed as dist

    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "8"
    torch.manual_seed(0)
    d_in, d_out = 32, 8
    w = torch.randn(d_in, d_out) * 0.1  # same init on every rank
    flat = w.reshape(-1)
    n = flat.numel()
    shard_n = -(-n // ws)
    padded = torch.cat([flat, torch.zeros(shard_n * ws - n)])
    my_shard = padded[rank * shard_n : (rank + 1) * shard_n].clone()

    torch.manual_seed(17)  # same data on every rank; shard batches by rank
    x_all = torch.randn(ws * 16, d_in)
    y_all = x_all @ torch.randn(d_in, d_out)
    x = x_all[rank * 16 : (rank + 1) * 16]
    y = y_all[rank * 16 : (rank + 1) * 16]

    lr = 0.05
    losses = []
    for _ in range(50):
        # gather full params from shards (FSDP forward gather)
        full = torch.zeros(shard_n * ws)
        dist.all_gather_into_tensor(full, my_shard)
        wt = full[:n].reshape(d_in, d_out).detach().requires_grad_(True)
        loss = ((x @ wt - y) ** 2).mean()
        loss.backward()
        # reduce-scatter gradient shards (FSDP backward reduce), averaged
        g = torch.cat([wt.grad.reshape(-1), torch.zeros(shard_n * ws - n)])
        gshard = torch.zeros(shard_n)
        dist.reduce_scatter_tensor(gshard, g, op=dist.ReduceOp.AVG)
        my_shard = my_shard - lr * gshard
        losses.append(float(loss))
    del os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"]
    assert losses[-1] < 0.25 * losses[0], losses
    dist.barrier()


def _worker_fsdp_quantized_allgather(rank: int, ws: int) -> None:
    """CGX_FSDP_ALLGATHER_BITS: the parameter all-gather (the half of
    ZeRO-3's traffic reduce_scatter_tensor leaves raw) rides an 8-bit
    max-min wire — decoded identically on every rank, within the bucket
    envelope, and the full quantized-both-ways workflow still trains."""
    import os

    import torch
    import torch.distributed as dist

    n = 640
    base = torch.linspace(-1, 1, n)
    shard = base * (rank + 1)

    # Default (bits=0): raw exact gather.
    full = torch.zeros(n * ws)
    dist.all_gather_into_tensor(full, shard)
    for j in range(ws):
        assert torch.equal(full[j * n : (j + 1) * n], base * (j + 1))

    # 8-bit wire: per-bucket envelope + nonzero error (it really quantized).
    os.environ["CGX_FSDP_ALLGATHER_BITS"] = "8"
    os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = "128"
    full_q = torch.zeros(n * ws)
    dist.all_gather_into_tensor(full_q, shard)
    for j in range(ws):
        seg = full_q[j * n : (j + 1) * n]
        ref = base * (j + 1)
        err = (seg - ref).abs().max().item()
        bucket_range = (j + 1) * 2 * 127 / (n - 1)
        bound = bucket_range / (2**8 - 1) / 2 + 1e-6
        assert 0 < err <= bound, (j, err, bound)

    # Error symmetry: every rank decoded identical bytes.
    mx, mn = full_q.clone(), full_q.clone()
    dist.all_reduce(mx, op=dist.ReduceOp.MAX)
    dist.all_reduce(mn, op=dist.ReduceOp.MIN)
    assert torch.equal(mx, mn), "gathered params differ across ranks"

    # ZeRO-3 loop with BOTH directions compressed still trains.
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "8"
    torch.manual_seed(0)
    d_in, d_out = 32, 8
    w = torch.randn(d_in, d_out) * 0.1
    flat = w.reshape(-1)
    pn = flat.numel()
    shard_n = -(-pn // ws)
    padded = torch.cat([flat, torch.zeros(shard_n * ws - pn)])
    my_shard = padded[rank * shard_n : (rank + 1) * shard_n].clone()
    torch.manual_seed(17)
    x_all = torch.randn(ws * 16, d_in)
    y_all = x_all @ torch.randn(d_in, d_out)
    x = x_all[rank * 16 : (rank + 1) * 16]
    y = y_all[rank * 16 : (rank + 1) * 16]
    losses = []
    for _ in range(50):
        fullp = torch.zeros(shard_n * ws)
        dist.all_gather_into_tensor(fullp, my_shard)
        wt = fullp[:pn].reshape(d_in, d_out).detach().requires_grad_(True)
        loss = ((x @ wt - y) ** 2).mean()
        loss.backward()
        g = torch.cat([wt.grad.reshape(-1), torch.zeros(shard_n * ws - pn)])
        gshard = torch.zeros(shard_n)
        dist.reduce_scatter_tensor(gshard, g, op=dist.ReduceOp.AVG)
        my_shard = my_shard - 0.05 * gshard
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], losses
    dist.barrier()


# ---------------------------------------------------------------------------
# Tests.
# ---------------------------------------------------------------------------


@pytest.mark.torch_bridge
def test_collectives_ws2():
    _launch(_worker_collectives, ws=2)


@pytest.mark.torch_bridge
def test_collectives_ws4():
    _launch(_worker_collectives, ws=4, timeout=360.0)


@pytest.mark.torch_bridge
def test_alltoall_base_ws2():
    _launch(_worker_alltoall_base, ws=2)


@pytest.mark.torch_bridge
def test_alltoall_base_ws4():
    _launch(_worker_alltoall_base, ws=4)


@pytest.mark.torch_bridge
def test_ddp_training_ws2():
    _launch(_worker_ddp, ws=2)


@pytest.mark.torch_bridge
def test_ddp_torch_powersgd_hook_ws2():
    _launch(_worker_ddp_torch_powersgd, ws=2)


@pytest.mark.torch_bridge
def test_unsupported_ops_ws2():
    _launch(_worker_unsupported, ws=2)


@pytest.mark.torch_bridge
def test_sharded_collectives_ws2():
    _launch(_worker_sharded_collectives, ws=2)


@pytest.mark.torch_bridge
def test_sharded_collectives_ws4():
    _launch(_worker_sharded_collectives, ws=4)


@pytest.mark.torch_bridge
def test_fsdp_training_ws2():
    _launch(_worker_fsdp, ws=2)


@pytest.mark.torch_bridge
def test_fsdp_quantized_allgather_ws2():
    _launch(_worker_fsdp_quantized_allgather, ws=2)


@pytest.mark.torch_bridge
def test_fsdp_quantized_allgather_ws4():
    _launch(_worker_fsdp_quantized_allgather, ws=4)


def _worker_sched_pipelined(rank: int, ws: int) -> None:
    """CGX_SCHEDULE=on bridge pipeline (ISSUE 9): the double-buffered
    in-flight window must produce BIT-EQUAL results to the monolithic
    path on a bucket-aligned payload (the schedule compiler's contract,
    parallel/schedule.py), bump the ``cgx.sched.*`` bridge counters, and
    record a live overlap ratio. The knob is re-read per collective, so
    one group runs both forms back to back."""
    import torch
    import torch.distributed as dist

    from torch_cgx_tpu.utils.logging import metrics

    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    os.environ["CGX_SCHED_CHUNKS"] = "4"
    n = ws * 512 * 32  # ceil(n/ws) divides the bucket: aligned payload
    x = (rank + 1) * (torch.arange(n, dtype=torch.float32) / n - 0.5)

    os.environ.pop("CGX_SCHEDULE", None)
    mono = x.clone()
    dist.all_reduce(mono)
    assert metrics.get("cgx.sched.bridge_collectives") == 0.0

    os.environ["CGX_SCHEDULE"] = "on"
    pipe = x.clone()
    dist.all_reduce(pipe)
    assert torch.equal(mono, pipe), (
        "pipelined bridge result diverges from monolithic",
        (mono - pipe).abs().max(),
    )
    assert metrics.get("cgx.sched.bridge_collectives") == 1.0
    assert metrics.get("cgx.sched.wall_s") > 0.0
    assert metrics.get("cgx.sched.overlap_s") > 0.0

    # Sub-bucket payload: the plan degrades to one chunk -> the
    # monolithic body runs even with the knob on (no per-chunk keys).
    tiny = torch.full((256,), float(rank + 1))
    dist.all_reduce(tiny)
    assert torch.allclose(
        tiny, torch.full((256,), _sum_expect(ws))
    )
    assert metrics.get("cgx.sched.bridge_collectives") == 1.0
    os.environ.pop("CGX_SCHEDULE", None)
    os.environ.pop("CGX_SCHED_CHUNKS", None)
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS", None)


@pytest.mark.torch_bridge
def test_sched_pipelined_bridge_ws2():
    _launch(_worker_sched_pipelined, ws=2)


@pytest.mark.torch_bridge
def test_sched_pipelined_bridge_ws4():
    _launch(_worker_sched_pipelined, ws=4, timeout=360.0)


def _worker_subgroup(rank: int, ws: int) -> None:
    import torch
    import torch.distributed as dist

    # Quantized allreduce on a 2-rank subgroup of the world — the reference
    # pins everything to MPI_COMM_WORLD and subgroups don't work there
    # (SURVEY.md §8.11); the store-transport bridge supports them.
    sub = dist.new_group(ranks=[0, 1])
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    if rank in (0, 1):
        t = torch.full((5000,), float(rank + 1))
        dist.all_reduce(t, group=sub)
        assert torch.equal(t, torch.full((5000,), 3.0)), t[:4]
    else:
        # ranks outside the subgroup must not participate or deadlock
        pass
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS")


def _worker_failed_future(rank: int, ws: int) -> None:
    import torch
    import torch.distributed as dist

    # A worker-thread failure must surface as a failed Work future (the
    # finishWorkMPIError path, ProcessGroupCGX.cc:312-317), not a hang:
    # an invalid env config is only discovered inside the worker's run().
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = "-7"
    t = torch.full((5000,), float(rank + 1))
    try:
        dist.all_reduce(t)
        raise AssertionError("expected the failed future to raise on wait()")
    except (RuntimeError, ValueError):
        pass
    os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = "512"
    # The group must still be usable afterwards.
    ok = torch.full((8,), float(rank + 1))
    dist.all_reduce(ok)
    assert ok[0].item() == sum(r + 1 for r in range(ws))
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS")
    os.environ.pop("CGX_COMPRESSION_BUCKET_SIZE")


@pytest.mark.torch_bridge
def test_subgroup_ws3():
    _launch(_worker_subgroup, ws=3)


@pytest.mark.torch_bridge
def test_failed_work_recovers_ws2():
    _launch(_worker_failed_future, ws=2)


def _worker_bucket_disambiguation(rank: int, ws: int) -> None:
    import torch
    import torch.distributed as dist
    from torch_cgx_tpu import config as cfg

    # Two registered buckets share the same TOTAL numel but have different
    # layer layouts/configs. The hook-style tag must select the right one;
    # an untagged allreduce of that size is ambiguous and must raise
    # (reference extractLayers errors on mismatch,
    # mpi_allreduce_operations.cc:278-284).
    cfg.clear_registry()
    cfg.register_layer("bucketA", 0, 4096, 2, 64)    # aggressive 2-bit
    cfg.register_layer("bucketA", 1, 1000, 2, 64)
    cfg.register_layer("bucketB", 0, 1000, 32, 0)    # fully raw
    cfg.register_layer("bucketB", 1, 4096, 32, 0)
    n = 5096
    x = torch.linspace(-1, 1, n) * (rank + 1)
    exact = torch.linspace(-1, 1, n) * _sum_expect(ws)

    # Tagged as the raw bucket: exact result.
    t = x.clone()
    cfg.set_current_bucket("bucketB")
    dist.all_reduce(t)
    assert torch.allclose(t, exact, atol=1e-5), "bucketB must be exact"

    # Tagged as the 2-bit bucket: quantization error must appear.
    t = x.clone()
    cfg.set_current_bucket("bucketA")
    dist.all_reduce(t)
    assert not torch.allclose(t, exact, atol=1e-6), "bucketA must quantize"

    # Untagged + ambiguous total: the Work future fails.
    t = x.clone()
    try:
        dist.all_reduce(t)
        raise AssertionError("ambiguous untagged allreduce should raise")
    except RuntimeError as e:
        assert "matches 2 registered buckets" in str(e), e

    # Tagged with a stale/mismatched registration: loud error, not silence.
    cfg.set_current_bucket("bucketA")
    t = torch.zeros(77)
    try:
        dist.all_reduce(t)
        raise AssertionError("size-mismatched tag should raise")
    except RuntimeError as e:
        assert "registered layer sizes" in str(e), e
    cfg.clear_registry()
    dist.barrier()


def _worker_async_p2p(rank: int, ws: int) -> None:
    import time

    import torch
    import torch.distributed as dist

    # recv must return a live Work immediately (AsyncWork model) and the
    # collective worker must stay unblocked while the recv is pending.
    if rank == 1:
        r = torch.zeros(1000)
        work = dist.irecv(r, src=0)
        assert not work.is_completed(), "recv completed before the send"
        # Collectives progress while the recv is parked.
        t = torch.full((256,), float(rank + 1))
        dist.all_reduce(t)
        assert t[0].item() == _sum_expect(ws)
        work.wait()
        assert torch.equal(r, torch.arange(1000, dtype=torch.float32))
    else:
        time.sleep(0.5)  # ensure the recv is posted and parked first
        t = torch.full((256,), float(rank + 1))
        dist.all_reduce(t)
        if rank == 0:
            dist.isend(torch.arange(1000, dtype=torch.float32), dst=1).wait()
    dist.barrier()


@pytest.mark.torch_bridge
def test_bucket_disambiguation_ws2():
    _launch(_worker_bucket_disambiguation, ws=2)


def _worker_fake_ratio(rank: int, ws: int) -> None:
    import os

    import numpy as np
    import torch
    import torch.distributed as dist

    # CGX_COMPRESSION_FAKE_RATIO: only the leading fraction of the
    # compressed slice travels; the tail stays stale (debug traffic
    # shaping, mpi_allreduce_operations.cc:130-144). The bridge's
    # span-based implementation must reduce exactly the leading budget
    # and leave the rest untouched.
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "8"
    os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = "64"
    os.environ["CGX_COMPRESSION_FAKE_RATIO"] = "0.5"
    n = 4096
    t = torch.full((n,), float(rank + 1)).reshape(64, 64)
    dist.all_reduce(t)
    flat = t.reshape(-1)
    total = float(sum(range(1, ws + 1)))
    lead = np.asarray(flat[: n // 2])
    tail = np.asarray(flat[n // 2 :])
    # constant buckets quantize exactly: leading half allreduced...
    np.testing.assert_allclose(lead, total, rtol=1e-6)
    # ...tail untouched (still this rank's own values)
    np.testing.assert_allclose(tail, float(rank + 1), rtol=1e-6)
    del os.environ["CGX_COMPRESSION_FAKE_RATIO"]
    dist.barrier()


@pytest.mark.torch_bridge
def test_fake_ratio_bridge_ws2():
    _launch(_worker_fake_ratio, ws=2)


@pytest.mark.torch_bridge
def test_async_p2p_ws2():
    _launch(_worker_async_p2p, ws=2)


def _worker_wait_timeout(rank: int, ws: int) -> None:
    import datetime

    import torch
    import torch.distributed as dist

    if rank == 0:
        # Rank 1 never posts its chunk within the window: wait(timeout)
        # must raise, not hang (c10d timeout contract).
        t = torch.full((64,), 1.0)
        work = dist.all_reduce(t, async_op=True)
        try:
            work.wait(timeout=datetime.timedelta(seconds=2))
            raise AssertionError("expected timeout")
        except RuntimeError as e:
            assert "timed out" in str(e), e
    # rank 1 deliberately skips the collective; both just exit (the
    # _bootstrap barrier is skipped via a store flag below).


@pytest.mark.torch_bridge
def test_wait_timeout_ws2():
    # A custom launch without the trailing barrier (rank 1 never joins the
    # collective, so a barrier would deadlock).
    import multiprocessing as mp
    import tempfile

    initfile = tempfile.mktemp(prefix="cgx_test_store_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(
            target=_bootstrap_no_barrier,
            args=(r, 2, initfile, "_worker_wait_timeout", q),
        )
        for r in range(2)
    ]
    for p in procs:
        p.start()
    errors = []
    for _ in range(2):
        rank, err = q.get(timeout=120)
        if err is not None:
            errors.append(f"rank {rank}:\n{err}")
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    if os.path.exists(initfile):
        os.unlink(initfile)
    assert not errors, "\n".join(errors)


def _bootstrap_no_barrier(rank, ws, initfile, target_name, q):
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, _REPO)
        import torch.distributed as dist
        import torch_cgx_tpu.torch_backend  # noqa: F401

        dist.init_process_group(
            "cgx", init_method=f"file://{initfile}", rank=rank, world_size=ws
        )
        globals()[target_name](rank, ws)
        q.put((rank, None))
    except Exception:
        import traceback

        q.put((rank, traceback.format_exc()))
        raise


# ---------------------------------------------------------------------------
# Wire framing units (single-process; no process group needed).
# ---------------------------------------------------------------------------


def test_bf16_wire_meta_halves():
    """bf16 buckets frame with bf16 meta: meta (and total) wire bytes drop
    by half vs f32 framing for the same segment — the reference's
    store-meta-in-input-dtype economics (compressor.cc:401-419)."""
    import ml_dtypes
    import numpy as np

    from torch_cgx_tpu.ops import codec_host as hcodec
    from torch_cgx_tpu.torch_backend.backend import (
        _Segment,
        _compress_frames,
        _decompress_frames,
    )

    bf16 = np.dtype(ml_dtypes.bfloat16)
    n, bits, bucket = 4096, 4, 512
    rng = np.random.default_rng(0)
    fused = rng.normal(size=n).astype(np.float32)
    segs = [_Segment(0, n, bits, bucket)]

    wire_f32 = _compress_frames(fused, segs, False, None)
    wire_bf16 = _compress_frames(fused, segs, False, None, bf16)
    meta_f32, packed_b, _, total_f32 = hcodec.wire_layout(n, bits, bucket, np.float32)
    meta_bf16 = hcodec.wire_layout(n, bits, bucket, bf16)[0]
    assert len(wire_f32) == total_f32
    assert meta_bf16 * 2 == meta_f32
    assert len(wire_f32) - len(wire_bf16) == meta_f32 - meta_bf16

    # Round trip through the bf16 frame stays within the quantization
    # envelope (meta rounding to bf16 adds <= 2^-8 relative).
    out = np.zeros_like(fused)
    _decompress_frames(
        np.frombuffer(wire_bf16, np.uint8), segs, out, False, False, bf16
    )
    xb = fused.reshape(-1, bucket)
    unit = (xb.max(1) - xb.min(1)) / ((1 << bits) - 1)
    err = np.abs(out - fused).reshape(-1, bucket).max(1)
    assert (err <= unit * 1.01 + 1e-6).all()


def test_f16_tensors_stay_f32_framed():
    """fp16 wire framing must NOT narrow the fused f32 accumulator: partial
    sums can exceed the fp16 range mid-reduction (review finding r3); the
    bridge only enables 16-bit framing for bf16, whose exponent range
    matches f32. Drives the bridge's actual dtype dispatch (_wire_dtype),
    not a test-local choice, then proves the f32 framing survives
    above-fp16-range partial sums."""
    import ml_dtypes
    import numpy as np
    import torch

    from torch_cgx_tpu.torch_backend.backend import (
        _Segment,
        _compress_frames,
        _decompress_frames,
        _wire_dtype,
    )

    # The dispatch itself: fp16 -> f32 frames, bf16 -> bf16, f32 -> f32.
    assert _wire_dtype(torch.float16) == np.float32
    assert _wire_dtype(torch.float32) == np.float32
    assert _wire_dtype(torch.bfloat16) == np.dtype(ml_dtypes.bfloat16)

    n, bits, bucket = 1024, 4, 512
    # f32 partial sums far above fp16 max (65504): must survive framing
    # with the dtype the bridge actually selects for fp16 tensors.
    wdt = _wire_dtype(torch.float16)
    fused = np.full(n, 9.0e4, np.float32)
    fused[::7] = -1.2e5
    segs = [_Segment(0, n, bits, bucket)]
    wire = _compress_frames(fused, segs, False, None, wdt)
    out = np.zeros_like(fused)
    _decompress_frames(
        np.frombuffer(wire, np.uint8), segs, out, False, False, wdt
    )
    assert np.isfinite(out).all()
    xb = fused.reshape(-1, bucket)
    unit = (xb.max(1) - xb.min(1)) / ((1 << bits) - 1)
    err = np.abs(out - fused).reshape(-1, bucket).max(1)
    assert (err <= unit * 1.01).all()


# ---------------------------------------------------------------------------
# SHM data plane, hierarchical two-level reduction, abort (round 5 —
# shm_communicator.cc:116-177, mpi_allreduce_operations.cc:139-185,
# ProcessGroupCGX.cc:295-298).
# ---------------------------------------------------------------------------


def _backend_of(group=None):
    """The ProcessGroupCGX instance behind a dist group (our creator fn
    returns the backend as the group itself)."""
    import torch.distributed as dist

    from torch_cgx_tpu.torch_backend.backend import ProcessGroupCGX

    pg = (
        group
        if group is not None
        else dist.distributed_c10d._get_default_group()
    )
    assert isinstance(pg, ProcessGroupCGX), type(pg)
    return pg


def _worker_shm_plane(rank: int, ws: int) -> None:
    import torch
    import torch.distributed as dist

    be = _backend_of()
    assert be._shm is not None, "shm plane inactive on a single host"
    assert be._all_local, be._host_by_rank
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    # Large enough that the compressed frames + an uncompressed broadcast
    # force the 8 MB arena ring to wrap AND grow generations.
    n = 3_000_000
    t = torch.full((n,), float(rank + 1))
    dist.all_reduce(t)
    assert torch.equal(t, torch.full((n,), _sum_expect(ws)))
    big = torch.full((4_000_000,), float(rank))
    dist.broadcast(big, src=0)
    assert torch.equal(big, torch.zeros(4_000_000))
    # Transport equivalence: the deterministic codec makes results
    # byte-identical whichever plane carried them.
    x = torch.linspace(-3, 7, 100_000) * (rank + 1)
    via_shm = x.clone()
    dist.all_reduce(via_shm)
    os.environ["CGX_SHM"] = "0"
    store_group = dist.new_group(ranks=list(range(ws)))
    os.environ.pop("CGX_SHM")
    assert _backend_of(store_group)._shm is None
    via_store = x.clone()
    dist.all_reduce(via_store, group=store_group)
    assert torch.equal(via_shm, via_store)
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS")


def _check_hier_group(rank: int, ws: int, hosts: int) -> None:
    """Build a subgroup whose rendezvous sees a simulated multi-host
    topology (CGX_SHM_HOST_ID override) and verify the two-level leader
    path end to end: exactness, envelope, global bit-identity."""
    import torch
    import torch.distributed as dist
    from torch_cgx_tpu import config as cgx_cfg

    per_host = -(-ws // hosts)
    os.environ["CGX_SHM_HOST_ID"] = f"testhost{rank // per_host}"
    sub = dist.new_group(ranks=list(range(ws)))
    be = _backend_of(sub)
    assert len(set(be._host_by_rank)) == hosts, be._host_by_rank
    assert be._use_hierarchy(cgx_cfg.topology_from_env()), be._host_by_rank
    assert not be._all_local
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    # Bit-exactness on constant buckets through both levels.
    t = torch.full((10_000,), float(rank + 1))
    dist.all_reduce(t, group=sub)
    assert torch.equal(t, torch.full((10_000,), _sum_expect(ws))), t[:4]
    # Envelope + global symmetry on varying data.
    n, bits, bucket = 50_000, 4, 512
    x = torch.arange(n, dtype=torch.float32) / n * (rank + 1)
    exact = torch.arange(n, dtype=torch.float32) / n * _sum_expect(ws)
    r = x.clone()
    dist.all_reduce(r, group=sub)
    # Two quantized levels + requant stages: double the flat bound.
    bound = 4 * min(bucket, n) / (2**bits - 1) * ws * (ws + 1) / n
    assert (r - exact).abs().max().item() < bound
    gathered = [torch.empty_like(r) for _ in range(ws)]
    dist.all_gather(gathered, r, group=sub)
    for g in gathered:
        assert torch.equal(g, gathered[0]), "cross-host bit-identity broken"
    # Raw intra stages (CGX_INTRA_COMPRESS=0): exact intra, quantized cross.
    os.environ["CGX_INTRA_COMPRESS"] = "0"
    t = torch.full((7_000,), float(rank + 1))
    dist.all_reduce(t, group=sub)
    assert torch.equal(t, torch.full((7_000,), _sum_expect(ws)))
    for k in (
        "CGX_INTRA_COMPRESS",
        "CGX_COMPRESSION_QUANTIZATION_BITS",
        "CGX_SHM_HOST_ID",
    ):
        os.environ.pop(k)


def _worker_hier_2x2(rank: int, ws: int) -> None:
    _check_hier_group(rank, ws, hosts=2)


def _worker_hier_asym(rank: int, ws: int) -> None:
    # hosts = {0,1} and {2}: the single-rank host is its own leader — every
    # rank must still take the hierarchical branch (group-global predicate;
    # a per-rank gate deadlocks exactly this topology).
    _check_hier_group(rank, ws, hosts=2)


def _worker_abort(rank: int, ws: int) -> None:
    import time as _time

    import torch
    import torch.distributed as dist

    # Scoped to a subgroup so its poison key doesn't leak into the world
    # group the harness barriers on.
    sub = dist.new_group(ranks=list(range(ws)))
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    if rank == 0:
        t = torch.full((100_000,), 1.0)
        w = dist.all_reduce(t, group=sub, async_op=True)
        t0 = _time.monotonic()
        try:
            w.wait()
            raise AssertionError("expected abort to fail the collective")
        except RuntimeError as e:
            assert "abort" in str(e), e
        assert _time.monotonic() - t0 < 30, "peer unblocked too slowly"
    else:
        _time.sleep(0.5)  # let rank 0 park inside the collective
        _backend_of(sub).abort("deliberate test failure")
    os.environ.pop("CGX_COMPRESSION_QUANTIZATION_BITS")
    # The WORLD group stays healthy after the subgroup died.
    ok = torch.full((8,), float(rank + 1))
    dist.all_reduce(ok)
    assert ok[0].item() == _sum_expect(ws)


def _worker_shm_perf(rank: int, ws: int) -> None:
    import time as _time

    import torch
    import torch.distributed as dist

    n = 16 * 1024 * 1024  # 64 MB fp32 payload

    def bench(group) -> float:
        t = torch.ones(n)
        dist.broadcast(t, src=0, group=group)  # warm (arena growth etc.)
        dist.barrier(group=group)
        t0 = _time.perf_counter()
        for _ in range(3):
            dist.broadcast(t, src=0, group=group)
        dist.barrier(group=group)
        return (_time.perf_counter() - t0) / 3

    shm_group = dist.new_group(ranks=list(range(ws)))
    os.environ["CGX_SHM"] = "0"
    store_group = dist.new_group(ranks=list(range(ws)))
    os.environ.pop("CGX_SHM")
    assert _backend_of(shm_group)._shm is not None
    assert _backend_of(store_group)._shm is None
    # Capability gate over up to 3 attempts, judged on the RATIO OF
    # MINIMUMS: scheduling noise on a loaded single-core CI box only ever
    # ADDS time, so min() over attempts estimates each transport's true
    # floor — one noisy store attempt can't fake a pass (the store floor
    # stays honest), and a genuinely regressed shm plane can't hide (its
    # floor rises). Ranks agree on the attempt count via a consensus
    # broadcast so collective counts stay matched.
    t_shms, t_stores = [], []
    for _ in range(3):
        t_shms.append(bench(shm_group))
        t_stores.append(bench(store_group))
        ratio = min(t_stores) / max(min(t_shms), 1e-9)
        done = torch.tensor([1.0 if ratio > 5 else 0.0])
        dist.broadcast(done, src=ws - 1, group=shm_group)
        if done.item():
            break
    if rank == ws - 1:  # a receiver sees the transport cost end to end
        ratio = min(t_stores) / max(min(t_shms), 1e-9)
        assert ratio > 5, (
            f"shm 64MB broadcast floor only {ratio:.1f}x faster than "
            f"store floor ({min(t_shms) * 1e3:.1f} ms vs "
            f"{min(t_stores) * 1e3:.1f} ms over {len(t_shms)} attempts)"
        )


@pytest.mark.torch_bridge
def test_shm_plane_ws2():
    _launch(_worker_shm_plane, ws=2)


@pytest.mark.torch_bridge
def test_shm_plane_ws4():
    _launch(_worker_shm_plane, ws=4)


@pytest.mark.torch_bridge
def test_hierarchical_2x2_ws4():
    _launch(_worker_hier_2x2, ws=4)


@pytest.mark.torch_bridge
def test_hierarchical_asym_ws3():
    _launch(_worker_hier_asym, ws=3)


@pytest.mark.torch_bridge
def test_abort_unblocks_peers_ws2():
    _launch(_worker_abort, ws=2)


# Slow tier: a wall-clock performance assertion (~12 s) — timing
# comparisons belong in the unfiltered sweep, not the 1-core tier-1.
@pytest.mark.slow
@pytest.mark.torch_bridge
def test_shm_beats_store_64mb_ws2():
    _launch(_worker_shm_perf, ws=2, timeout=360.0)


def test_shm_arena_wrap_and_growth():
    """Single-process ShmArena unit test: ring wrap reuses reclaimed space;
    an oversized payload grows a generation; drained old generations are
    unlinked."""
    import tempfile

    import numpy as np

    from torch_cgx_tpu.torch_backend.shm import ShmArena

    acks: dict = {}
    dropped: list = []
    arena = ShmArena(
        tempfile.gettempdir(),
        f"cgxtest-{os.getpid()}",
        poll_ack=lambda k: acks.get(k, 0),
        drop_keys=dropped.extend,
        min_capacity=1 << 12,  # 4 KB ring
    )
    try:
        payload = bytes(range(256)) * 4  # 1 KB
        regions = []
        for i in range(3):
            regions.append(arena.write(payload, f"k{i}/ack", 1))
        assert all(g == 1 for g, _, _ in regions)
        # Nothing acked: a 4th+5th 1 KB write exceeds the ring -> growth.
        g4 = arena.write(payload, "k3/ack", 1)[0]
        g5 = arena.write(payload, "k4/ack", 1)[0]
        assert max(g4, g5) >= 2
        # Ack everything, then reclaim under pressure (reclaim only runs
        # when an allocation misses — per-put ack polling would be an RPC
        # storm): fill the current ring so the next write must reclaim.
        for i in range(5):
            acks[f"k{i}/ack"] = 1
        cap_now = arena._gens[arena._gen].capacity
        fills = cap_now // len(payload)
        gen_before = arena._gen
        for j in range(fills + 1):
            arena.write(payload, f"fill{j}/ack", 1)
            acks[f"fill{j}/ack"] = 1
        # A reclaim pass ran; gen-1 regions were acked long ago -> its file
        # is unlinked and its control keys dropped.
        assert not os.path.exists(arena.path_of(1))
        assert any(d.startswith("k0") for d in dropped)
        assert arena._gen == gen_before, "reclaim should beat growth here"
        # Payload round-trips bit-exactly through the mmap.
        gen, off, size = arena.write(payload, "k6/ack", 1)
        gf = arena._gens[gen]
        assert bytes(gf.mm[off : off + size]) == payload
    finally:
        arena.close()
    assert not os.path.exists(arena.path_of(arena._gen))


# ---------------------------------------------------------------------------
# Layer-aligned greedy chunk split (CGX_LAYER_ALIGNED_SPLIT,
# compressor.cc:265-299).
# ---------------------------------------------------------------------------


def _reference_sizes_and_offsets(num_elements, world_size, layer_numels, align):
    """Independent transcription of Quantizer::GetSizesAndOffsets's
    semantics (compressor.cc:265-299) used as the parity oracle: greedy
    per-rank targets of remaining/(ws-rank), whole layers preferred, cuts
    only inside oversized layers at align-rounded offsets."""
    sizes, offsets = [], []
    offset = 0
    li, n_elem = 0, min(layer_numels[0], num_elements)
    for rank in range(world_size):
        per_node = num_elements // (world_size - rank)
        cur = 0
        while cur < per_node:
            if n_elem <= per_node - cur:
                cur += n_elem
                li += 1
                if li == len(layer_numels):
                    break
                n_elem = min(layer_numels[li], num_elements)
            else:
                aligned = min(-(-(per_node - cur) // align) * align, n_elem)
                cur += aligned
                n_elem -= aligned
        num_elements -= cur
        sizes.append(cur)
        offsets.append(offset)
        offset += cur
    return sizes, offsets


@pytest.mark.parametrize(
    "layer_numels,ws",
    [
        ([100, 37, 5000, 11, 11, 2000], 4),          # mix of tiny + large
        ([64] * 40, 8),                              # all-whole layers
        ([1_000_003], 4),                            # one giant layer, cuts
        ([8, 8, 8, 8], 8),                           # more ranks than work
        ([513, 511, 1024, 3], 3),                    # odd sizes
    ],
)
def test_layer_aligned_split_matches_reference_formula(layer_numels, ws):
    from torch_cgx_tpu.torch_backend.backend import (
        _chunk_split_layer_aligned,
    )

    n = sum(layer_numels)
    sizes, offs = _chunk_split_layer_aligned(n, ws, list(layer_numels))
    want_sizes, want_offs = _reference_sizes_and_offsets(
        n, ws, list(layer_numels), align=32
    )
    assert sizes == want_sizes and offs == want_offs
    # Partition invariants.
    assert sum(sizes) == n and offs[0] == 0
    for i in range(1, ws):
        assert offs[i] == offs[i - 1] + sizes[i - 1]
    # The aligned property itself: any layer SMALLER than its rank's whole
    # chunk lies entirely inside one chunk (never straddles a boundary).
    bounds = set(offs[1:])
    lo = 0
    for numel in layer_numels:
        hi = lo + numel
        inside = [b for b in bounds if lo < b < hi]
        for b in inside:
            # a cut is legal only in a layer bigger than the chunk target
            r = offs.index(b) - 1
            assert numel > sizes[r] or numel >= 32, (
                f"small layer [{lo},{hi}) straddles chunk boundary {b}"
            )
        lo = hi


def _worker_layer_aligned(rank: int, ws: int) -> None:
    import torch
    import torch.distributed as dist
    from torch_cgx_tpu import config as cgx_cfg

    os.environ["CGX_LAYER_ALIGNED_SPLIT"] = "1"
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
    os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = "64"
    # Register a bucket with mixed layer sizes so the aligned split is
    # exercised through the real extract-layers path, both algorithms.
    sizes = [100, 37, 5000, 11, 11, 2000]
    cgx_cfg.register_layer("b0", 0, numel=sizes[0])
    for i, nl in enumerate(sizes[1:], 1):
        cgx_cfg.register_layer("b0", i, numel=nl)
    n = sum(sizes)
    for algo in ("SRA", "RING"):
        os.environ["CGX_INNER_REDUCTION_TYPE"] = algo
        t = torch.full((n,), float(rank + 1))
        cgx_cfg.set_current_bucket("b0")
        dist.all_reduce(t)
        assert torch.equal(t, torch.full((n,), _sum_expect(ws))), (algo, t[:4])
        x = torch.arange(n, dtype=torch.float32) / n * (rank + 1)
        exact = torch.arange(n, dtype=torch.float32) / n * _sum_expect(ws)
        r = x.clone()
        cgx_cfg.set_current_bucket("b0")
        dist.all_reduce(r)
        bound = 2 * 64 / (2**4 - 1) * ws * (ws + 1) / n
        assert (r - exact).abs().max().item() < bound, algo
    cgx_cfg.clear_registry()
    for k in (
        "CGX_LAYER_ALIGNED_SPLIT",
        "CGX_COMPRESSION_QUANTIZATION_BITS",
        "CGX_COMPRESSION_BUCKET_SIZE",
        "CGX_INNER_REDUCTION_TYPE",
    ):
        os.environ.pop(k)


@pytest.mark.torch_bridge
def test_layer_aligned_allreduce_ws4():
    _launch(_worker_layer_aligned, ws=4)


def _worker_p2p_mixed_routing(rank: int, ws: int) -> None:
    """Per-peer p2p channel routing in a mixed-host topology (simulated
    hosts h0={0,1}, h1={2}): a same-host send/recv rides the SHM plane,
    a cross-host one rides the store, and BOTH sides pick the same
    channel (a mismatch deadlocks). The lone rank has no channel at all
    yet interoperates."""
    import torch
    import torch.distributed as dist

    os.environ["CGX_SHM_HOST_ID"] = f"testhost{min(rank // 2, 1)}"
    sub = dist.new_group(ranks=list(range(ws)))
    be = _backend_of(sub)
    if rank in (0, 1):
        assert be._shm is not None and not be._all_local
    else:
        assert be._shm is None  # alone on its host
    n = 4096
    if rank == 0:
        dist.send(torch.full((n,), 1.0), dst=1, group=sub)
        dist.send(torch.full((n,), 2.0), dst=2, group=sub)
        # exactly ONE p2p payload took the shm plane (the local peer's)
        assert be._shm.n_puts == 1, be._shm.n_puts
    elif rank == 1:
        t = torch.zeros(n)
        dist.recv(t, src=0, group=sub)
        assert torch.equal(t, torch.full((n,), 1.0))
        assert be._shm.n_takes == 1, be._shm.n_takes
    else:
        t = torch.zeros(n)
        dist.recv(t, src=0, group=sub)
        assert torch.equal(t, torch.full((n,), 2.0))
    os.environ.pop("CGX_SHM_HOST_ID")


@pytest.mark.torch_bridge
def test_p2p_mixed_routing_ws3():
    _launch(_worker_p2p_mixed_routing, ws=3)


def test_dead_arena_reaping(tmp_path):
    """Arenas from a SIGKILLed writer (atexit never ran) are reaped by the
    next channel creation in the same directory. Ownership = a held flock
    (namespace-proof; kernel-released on any death): locked and young and
    untagged files are all spared."""
    import fcntl
    import time

    from torch_cgx_tpu.torch_backend import shm as shm_mod

    d = str(tmp_path)
    old = time.time() - 2 * shm_mod._REAP_GRACE_S
    dead = tmp_path / "cgx-abc123-p999999999-r0-g1"  # orphan, past grace
    young = tmp_path / "cgx-bbb999-p999999998-r0-g1"  # orphan, in grace
    live = tmp_path / f"cgx-def456-p{os.getpid()}-r1-g2"  # flock held
    legacy = tmp_path / "cgx-oldstyle-r0-g1"  # untagged: never touched
    for f in (dead, young, live, legacy):
        f.write_bytes(b"x")
    for f in (dead, live, legacy):
        os.utime(f, (old, old))
    fd = os.open(str(live), os.O_RDWR)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        shm_mod._reap_dead_arenas(d)
        assert not dead.exists()
        assert young.exists() and live.exists() and legacy.exists()
    finally:
        os.close(fd)


def _worker_shm_dir_override(rank: int, ws: int) -> None:
    """CGX_SHM_DIR relocates the arena files (containers where /dev/shm is
    tiny or not shared); the plane still engages and carries payloads."""
    import glob

    import torch
    import torch.distributed as dist

    d = os.path.join(tempfile.gettempdir(), f"cgx_shmdir_test_{ws}")
    os.makedirs(d, exist_ok=True)
    os.environ["CGX_SHM_DIR"] = d
    sub = dist.new_group(ranks=list(range(ws)))
    be = _backend_of(sub)
    assert be._shm is not None and be._shm._dir == d
    t = torch.full((65536,), float(rank + 1))
    dist.all_reduce(t, group=sub)
    assert t[0].item() == _sum_expect(ws)
    assert glob.glob(os.path.join(d, "cgx-*")), "no arena files in override dir"
    os.environ.pop("CGX_SHM_DIR")


@pytest.mark.torch_bridge
def test_shm_dir_override_ws2():
    _launch(_worker_shm_dir_override, ws=2)
