"""Chaos suite: drive every CGX_FAULTS injector mode through the hardened
data plane and assert the matching defense fires (ISSUE 1 tentpole).

Single-process tests exercise :class:`ShmChannel` directly over an
in-memory store; the kill test spawns real torch ranks (the
test_torch_backend custom-launch pattern — a pool would die with the
killed rank). The JAX tests drive ``make_train_step``'s non-finite guard
on the virtual 8-device mesh.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import sys
import tempfile
import threading
import time
import traceback

import numpy as np
import pytest

from torch_cgx_tpu.robustness import (
    BridgeTimeoutError,
    FaultSpec,
    WireCorruptionError,
    faults,
    heartbeat,
    parse_faults,
)
from torch_cgx_tpu.utils.logging import metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset_injectors()
    metrics.reset()
    yield
    faults.reset_injectors()


# ---------------------------------------------------------------------------
# Grammar + determinism.
# ---------------------------------------------------------------------------


def test_fault_grammar_full_spec():
    specs = parse_faults(
        "drop_put:0.1,delay_take:50ms,corrupt_wire:step=7,"
        "kill_rank:2@step=5,nan_grad:step=3,stall_ack:1.0"
    )
    by_mode = {s.mode: s for s in specs}
    assert by_mode["drop_put"].prob == pytest.approx(0.1)
    assert by_mode["delay_take"].delay_ms == pytest.approx(50.0)
    assert by_mode["corrupt_wire"].step == 7
    assert by_mode["kill_rank"] == FaultSpec(
        mode="kill_rank", rank=2, step=5
    )
    assert by_mode["nan_grad"].step == 3
    assert by_mode["stall_ack"].prob == 1.0
    # durations in seconds, explicit rank=
    (s,) = parse_faults("delay_take:2s@rank=1")
    assert s.delay_ms == 2000.0 and s.rank == 1


def test_fault_grammar_slow_rank_and_flap():
    # ISSUE 5 satellite: the retry rung's rehearsal faults — a straggler
    # (slow_rank) and a transient drop-then-recover (flap).
    specs = parse_faults("slow_rank:1@800ms,flap:120ms@step=2")
    by_mode = {s.mode: s for s in specs}
    assert by_mode["slow_rank"] == FaultSpec(
        mode="slow_rank", rank=1, delay_ms=800.0
    )
    assert by_mode["flap"].delay_ms == pytest.approx(120.0)
    assert by_mode["flap"].step == 2
    # bare-int rank shorthand works for slow_rank like kill_rank
    (s,) = parse_faults("slow_rank:3@250ms")
    assert s.rank == 3 and s.delay_ms == pytest.approx(250.0)
    # both modes ARE their delay: omitting the duration would inject
    # nothing, so the parser fails loud instead of going vacuously green
    with pytest.raises(ValueError):
        parse_faults("slow_rank:3")
    with pytest.raises(ValueError):
        parse_faults("flap:step=2")


def test_flap_delay_helper_fires_on_its_step():
    inj = faults.FaultInjector(
        parse_faults("flap:50ms@step=1"), seed=0, rank=0
    )
    assert inj.flap_delay() is None  # event 0: gated off
    assert inj.flap_delay() == pytest.approx(0.05)  # event 1 fires
    assert inj.flap_delay() is None  # event 2: gated off again
    assert metrics.get("cgx.faults.flap") == 1


def test_fault_grammar_leak_page():
    # ISSUE 18 satellite: the memory plane's chaos fault — a KV page
    # whose last reference drops never reaches the free list. Prob and
    # step gates both parse; no extra fields are required (the fault IS
    # the suppressed release).
    (s,) = parse_faults("leak_page:1.0")
    assert s.mode == "leak_page" and s.prob == 1.0
    (s,) = parse_faults("leak_page:step=4")
    assert s.step == 4
    inj = faults.FaultInjector(parse_faults("leak_page:1.0"), seed=0, rank=0)
    assert inj.fire("leak_page")
    assert metrics.get("cgx.faults.leak_page") == 1


def test_fault_grammar_rejects_junk():
    with pytest.raises(ValueError):
        parse_faults("explode_randomly:1.0")  # unknown mode
    with pytest.raises(ValueError):
        parse_faults("drop_put:bogus")  # unparseable token
    with pytest.raises(ValueError):
        parse_faults("drop_put:1.5")  # probability out of range


def test_injector_seeded_determinism():
    a = faults.FaultInjector(parse_faults("drop_put:0.5"), seed=7, rank=0)
    b = faults.FaultInjector(parse_faults("drop_put:0.5"), seed=7, rank=0)
    c = faults.FaultInjector(parse_faults("drop_put:0.5"), seed=8, rank=0)
    seq_a = [a.fire("drop_put") for _ in range(64)]
    seq_b = [b.fire("drop_put") for _ in range(64)]
    seq_c = [c.fire("drop_put") for _ in range(64)]
    assert seq_a == seq_b  # same seed replays exactly
    assert seq_a != seq_c  # different seed is a different schedule
    assert any(seq_a) and not all(seq_a)


def test_injector_step_and_rank_gates():
    inj = faults.FaultInjector(
        parse_faults("corrupt_wire:step=2"), seed=0, rank=0
    )
    assert [inj.fire("corrupt_wire") for _ in range(4)] == [
        False, False, True, False,
    ]
    other = faults.FaultInjector(
        parse_faults("kill_rank:1@step=0"), seed=0, rank=0
    )
    assert not other.fire("kill_rank")  # rank gate: not this rank


# ---------------------------------------------------------------------------
# ShmChannel over an in-memory store.
# ---------------------------------------------------------------------------


class FakeStore:
    """Minimal c10d-Store look-alike: set/get/add/delete_key, get raises
    when the key is missing (like TCPStore on timeout)."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def set(self, k, v):
        with self._lock:
            self._d[k] = bytes(v)

    def get(self, k):
        with self._lock:
            if k not in self._d:
                raise KeyError(k)
            return self._d[k]

    def add(self, k, v):
        with self._lock:
            cur = int(self._d.get(k, b"0")) + int(v)
            self._d[k] = str(cur).encode()
            return cur

    def delete_key(self, k):
        with self._lock:
            self._d.pop(k, None)


def _channel_pair(store, tmp_path):
    from torch_cgx_tpu.torch_backend.shm import ShmChannel

    writer = ShmChannel(store, rank=0, directory=str(tmp_path))
    reader = ShmChannel(store, rank=1, directory=str(tmp_path))
    return writer, reader


def test_checksum_roundtrip_clean(tmp_path, monkeypatch):
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        payload = np.arange(100_000, dtype=np.uint8).tobytes()
        writer.put("k", payload)
        out = reader.take("k")
        assert out.tobytes() == payload
        assert metrics.get("cgx.wire_corrupt") == 0
        # the header really carries a crc (5th field, non-negative)
        hdr = bytes(store.get("cgxshm/k")).decode()
        assert int(hdr.rsplit(":", 4)[4]) >= 0
    finally:
        writer.close()
        reader.close()


def test_corrupt_wire_raises_after_one_retry(tmp_path, monkeypatch):
    # Acceptance (b): corrupted payload -> WireCorruptionError after one
    # re-read, cgx.wire_corrupt incremented.
    monkeypatch.setenv("CGX_FAULTS", "corrupt_wire:step=0")
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("k", np.ones(4096, np.uint8).tobytes())
        with pytest.raises(WireCorruptionError, match="checksum mismatch"):
            reader.take("k")
        assert metrics.get("cgx.wire_corrupt") == 1
        assert metrics.get("cgx.faults.corrupt_wire") == 1
        assert metrics.get("cgx.wire_reread_ok") == 0
    finally:
        writer.close()
        reader.close()


def test_transient_corruption_heals_on_reread(tmp_path, monkeypatch):
    # A stale cached mapping (not arena damage) must be cured by the one
    # fresh re-read, counted under cgx.wire_reread_ok, and return clean
    # bytes.
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        payload = np.arange(4096, dtype=np.uint8).tobytes()
        writer.put("k", payload)
        real_read = reader._read
        flipped = {"done": False}

        def flaky_read(path, off, size, refresh=False):
            out = real_read(path, off, size, refresh=refresh)
            if not flipped["done"]:
                flipped["done"] = True
                out = out.copy()
                out[0] ^= 0xFF
            return out

        monkeypatch.setattr(reader, "_read", flaky_read)
        out = reader.take("k")
        assert out.tobytes() == payload
        assert metrics.get("cgx.wire_corrupt") == 1
        assert metrics.get("cgx.wire_reread_ok") == 1
    finally:
        writer.close()
        reader.close()


def test_take_timeout_bounded_and_named(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_BRIDGE_TIMEOUT_MS", "300")
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        t0 = time.monotonic()
        with pytest.raises(BridgeTimeoutError, match="never-posted") as ei:
            reader.take("never-posted")
        assert time.monotonic() - t0 < 5.0  # bounded, not a hang
        assert ei.value.key == "cgxshm/never-posted"
        assert metrics.get("cgx.bridge_timeout") == 1
    finally:
        writer.close()
        reader.close()


def test_drop_put_surfaces_as_reader_timeout(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_FAULTS", "drop_put:1.0")
    monkeypatch.setenv("CGX_BRIDGE_TIMEOUT_MS", "300")
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("k", b"x" * 1024)  # payload written, header dropped
        assert metrics.get("cgx.faults.drop_put") == 1
        with pytest.raises(BridgeTimeoutError):
            reader.take("k")
    finally:
        writer.close()
        reader.close()


def test_delay_take_injects_latency(tmp_path, monkeypatch):
    monkeypatch.setenv("CGX_FAULTS", "delay_take:80ms")
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("k", b"y" * 64)
        t0 = time.monotonic()
        out = reader.take("k")
        assert time.monotonic() - t0 >= 0.08
        assert out.tobytes() == b"y" * 64
        assert metrics.get("cgx.faults.delay_take") == 1
    finally:
        writer.close()
        reader.close()


def test_arena_pressure_bounded_not_unbounded_growth(tmp_path, monkeypatch):
    # A dead/stalled reader (stall_ack) + the CGX_SHM_MAX_MB cap: puts back
    # off, then fail with the stalled ack key named — instead of growing
    # tmpfs forever.
    monkeypatch.setenv("CGX_FAULTS", "stall_ack:1.0")
    monkeypatch.setenv("CGX_SHM_MAX_MB", "1")
    monkeypatch.setenv("CGX_BRIDGE_TIMEOUT_MS", "300")
    store = FakeStore()
    from torch_cgx_tpu.torch_backend.shm import ShmChannel

    writer = ShmChannel(store, rank=0, directory=str(tmp_path))
    try:
        chunk = b"z" * (512 * 1024)
        t0 = time.monotonic()
        with pytest.raises(BridgeTimeoutError, match="un-acked") as ei:
            for i in range(64):
                writer.put(f"k{i}", chunk)
        assert time.monotonic() - t0 < 10.0
        assert ei.value.key.endswith("/ack")
        assert metrics.get("cgx.arena_pressure_waits") > 0
    finally:
        writer.close()


def test_peer_death_reaped_arena_names_sender(tmp_path):
    # Satellite: a reaped writer arena (the crash-path hygiene deleted the
    # gen file) surfaces as the existing "sending rank died" RuntimeError —
    # immediately, not after a hang.
    store = FakeStore()
    writer, reader = _channel_pair(store, tmp_path)
    try:
        writer.put("k", b"q" * 4096)
        for gen in list(writer._arena._gens):
            os.unlink(writer._arena.path_of(gen))
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="sending rank died"):
            reader.take("k")
        assert time.monotonic() - t0 < 5.0
    finally:
        writer.close()
        reader.close()


# ---------------------------------------------------------------------------
# Heartbeat liveness.
# ---------------------------------------------------------------------------


def test_heartbeat_live_then_stale(tmp_path):
    me = os.getpid()
    hb = heartbeat.Heartbeat(str(tmp_path), me, interval_s=0.05).start()
    try:
        assert heartbeat.suspect_dead_pids(str(tmp_path), [me]) == []
        # a pid that never heartbeat is suspect
        assert heartbeat.suspect_dead_pids(str(tmp_path), [me, 999999]) == [
            999999
        ]
    finally:
        hb.stop(unlink=False)
    # age the file artificially: stale -> suspected
    old = time.time() - 60
    os.utime(hb.path, (old, old))
    assert heartbeat.suspect_dead_pids(str(tmp_path), [me]) == [me]


def test_heartbeat_process_singleton(tmp_path):
    a = heartbeat.ensure_heartbeat(str(tmp_path))
    b = heartbeat.ensure_heartbeat(str(tmp_path))
    assert a is b  # one thread/file per (process, directory)
    assert os.path.exists(a.path)
    assert heartbeat.suspect_dead_pids(str(tmp_path), [os.getpid()]) == []


# ---------------------------------------------------------------------------
# kill_rank through the real torch bridge (acceptance a).
# ---------------------------------------------------------------------------


def _kill_rank_main(rank: int, ws: int, initfile: str, mdir: str, q) -> None:
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, _REPO)
        os.environ["CGX_BRIDGE_TIMEOUT_MS"] = "6000"
        os.environ["CGX_FAULTS"] = "kill_rank:1@step=0"
        os.environ["CGX_METRICS_DIR"] = mdir  # acceptance: black-box dump
        os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = "4"
        import torch
        import torch.distributed as dist
        import torch_cgx_tpu.torch_backend  # noqa: F401 — registers "cgx"
        from torch_cgx_tpu.robustness import BridgeTimeoutError as BTE

        dist.init_process_group(
            "cgx", init_method=f"file://{initfile}", rank=rank,
            world_size=ws,
        )
        # rank 1 dies inside this collective (kill_rank fires on its first
        # dequeued work item — an os._exit, no abort, no atexit).
        t = torch.full((8192,), float(rank + 1))
        t0 = time.monotonic()
        try:
            dist.all_reduce(t)
            q.put((rank, "collective succeeded despite the killed peer"))
            return
        except BTE as e:
            elapsed = time.monotonic() - t0
            msg = str(e)
            problems = []
            if "timed out" not in msg:
                problems.append(f"no timeout wording: {msg!r}")
            if 1 not in e.suspects or "1" not in msg:
                problems.append(f"dead rank 1 not named: {msg!r}")
            if elapsed > 30:
                problems.append(f"took {elapsed:.1f}s (budget was 6s)")
            q.put((rank, "; ".join(problems) or None))
    except Exception:
        q.put((rank, traceback.format_exc()))


@pytest.mark.torch_bridge
def test_kill_rank_produces_named_timeout(tmp_path):
    """A SIGKILL-style peer death mid-collective surfaces on the survivor
    as BridgeTimeoutError naming rank 1, within CGX_BRIDGE_TIMEOUT_MS —
    and (ISSUE 2 acceptance) with CGX_METRICS_DIR set the survivor leaves
    a flight-recorder dump identifying the failed collective and the
    suspected dead rank, which tools/cgx_report.py renders."""
    import json
    import subprocess

    mdir = str(tmp_path / "metrics")
    initfile = tempfile.mktemp(prefix="cgx_faults_store_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_kill_rank_main, args=(r, 2, initfile, mdir, q))
        for r in range(2)
    ]
    for p in procs:
        p.start()
    # Only rank 0 reports; rank 1 dies by design.
    rank, err = q.get(timeout=180)
    assert rank == 0 and err is None, f"rank {rank}: {err}"
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    from torch_cgx_tpu.robustness.faults import KILL_EXIT_CODE

    assert procs[1].exitcode == KILL_EXIT_CODE, procs[1].exitcode
    if os.path.exists(initfile):
        os.unlink(initfile)
    # -- flight-recorder acceptance: the evidence survived the failure --
    path = os.path.join(mdir, "flightrec-rank0.jsonl")
    assert os.path.exists(path), (
        os.listdir(mdir) if os.path.isdir(mdir) else "no metrics dir"
    )
    lines = [json.loads(line) for line in open(path)]
    assert lines[0]["kind"] == "dump"
    failures = [e for e in lines[1:] if e["kind"] == "failure"]
    assert failures, "no failure event in the survivor's dump"
    assert any(f["error"] == "BridgeTimeoutError" for f in failures)
    # the failed collective is named...
    assert any(f.get("op") == "allreduce" for f in failures)
    # ...and so is the suspected dead peer
    assert any(1 in (f.get("suspects") or []) for f in failures)
    # the report CLI renders the chaos dir without error (text + json)
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"), mdir],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    # (kill_rank itself fired on the DEAD rank — an os._exit leaves no
    # dump, by design; the survivor's evidence is the named timeout.)
    assert "BridgeTimeoutError" in proc.stdout
    assert "suspected dead" in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "cgx_report.py"),
         mdir, "--json"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0
    js = json.loads(proc.stdout)
    assert js["failures"]
    assert any(f.get("op") == "allreduce" for f in js["failures"])
    assert any(1 in (f.get("suspects") or []) for f in js["failures"])


# ---------------------------------------------------------------------------
# nan_grad + the non-finite guard (acceptance c).
# ---------------------------------------------------------------------------


def _guard_harness():
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from torch_cgx_tpu.parallel import make_train_step, replicate, shard_batch

    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("dp",))
    rng = np.random.default_rng(0)
    Wt = rng.normal(size=(16, 4)).astype(np.float32)
    batches = []
    for _ in range(4):
        x = rng.normal(size=(32, 16)).astype(np.float32)
        batches.append((x, x @ Wt))

    def loss_fn(p, b):
        return jnp.mean((b[0] @ p["w"] - b[1]) ** 2)

    def run(batch_list, guard, faults_env=None, idxs=None):
        os.environ.pop("CGX_FAULTS", None)
        if faults_env:
            os.environ["CGX_FAULTS"] = faults_env
        faults.reset_injectors()
        try:
            params = {"w": jnp.zeros((16, 4), jnp.float32)}
            opt = optax.adam(1e-2)
            step = make_train_step(
                loss_fn, opt, mesh, donate=False, nonfinite_guard=guard
            )
            p = replicate(params, mesh)
            s = replicate(opt.init(params), mesh)
            for i, (x, y) in enumerate(batch_list):
                b = shard_batch((x, y), mesh)
                si = idxs[i] if idxs is not None else i
                p, s, _loss = step(p, s, b, jnp.int32(si))
            return np.asarray(p["w"])
        finally:
            os.environ.pop("CGX_FAULTS", None)

    return batches, run


def test_nan_grad_skip_resumes_bit_identically(monkeypatch):
    """Acceptance (c): under nan_grad injection with guard="skip", the
    poisoned step is dropped (cgx.nonfinite_steps == 1), parameters stay
    finite, and training from there is bit-identical to a run that never
    saw the poisoned batch."""
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_COMPRESSION_BUCKET_SIZE", "64")
    batches, run = _guard_harness()
    w_faulted = run(batches, "skip", faults_env="nan_grad:step=1")
    assert np.isfinite(w_faulted).all()
    assert metrics.get("cgx.nonfinite_steps") == 1
    # control: same schedule minus the poisoned batch (step idx preserved
    # so the trace-identical program runs on the same inputs)
    control = [batches[0], batches[2], batches[3]]
    w_control = run(control, "skip", idxs=[0, 2, 3])
    np.testing.assert_array_equal(w_faulted, w_control)


def test_nan_grad_unguarded_poisons_everything(monkeypatch):
    """The failure mode the guard exists for: with the guard off, one NaN
    gradient element destroys the max-min wire for every parameter."""
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_COMPRESSION_BUCKET_SIZE", "64")
    batches, run = _guard_harness()
    w = run(batches[:2], "off", faults_env="nan_grad:step=1")
    assert not np.isfinite(w).all()


def test_nan_grad_probabilistic(monkeypatch):
    """A ``nan_grad:<prob>`` spec poisons ~that fraction of steps (a
    per-step Bernoulli seeded by CGX_FAULTS_SEED — deterministic replay),
    not every step."""
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_COMPRESSION_BUCKET_SIZE", "64")
    monkeypatch.setenv("CGX_FAULTS_SEED", "3")
    batches, run = _guard_harness()
    # 12 steps at p=0.5: some but not all must fault (p(all-or-none) ~ 2^-11)
    sched = (batches * 3)[:12]
    w = run(sched, "skip", faults_env="nan_grad:0.5")
    n_bad = metrics.get("cgx.nonfinite_steps")
    assert 0 < n_bad < 12, n_bad
    assert np.isfinite(w).all()
    # deterministic replay: same seed -> same fault schedule
    metrics.reset()
    run(sched, "skip", faults_env="nan_grad:0.5")
    assert metrics.get("cgx.nonfinite_steps") == n_bad


def test_nan_grad_exact_fallback_applies_the_step(monkeypatch):
    """guard="exact": the poisoned step still applies an update — from the
    uncompressed psum of the sanitized gradients — and params stay finite;
    fault-free runs are bit-identical to guard="off"."""
    monkeypatch.setenv("CGX_COMPRESSION_QUANTIZATION_BITS", "4")
    monkeypatch.setenv("CGX_COMPRESSION_BUCKET_SIZE", "64")
    batches, run = _guard_harness()
    w_exact = run(batches, "exact", faults_env="nan_grad:step=1")
    assert np.isfinite(w_exact).all()
    assert metrics.get("cgx.nonfinite_steps") == 1
    w_skip = run(batches, "skip", faults_env="nan_grad:step=1")
    assert not np.array_equal(w_exact, w_skip)  # the step was applied
    # zero-overhead identity on clean runs
    w_off = run(batches, "off")
    w_exact_clean = run(batches, "exact")
    np.testing.assert_array_equal(w_off, w_exact_clean)
