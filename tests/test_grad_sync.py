"""Tree-level allreduce + training front-end tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

import torch_cgx_tpu
from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.config import CompressionConfig
from torch_cgx_tpu.parallel import (
    allreduce_tree,
    flat_mesh,
    gradient_sync,
    make_train_step,
    replicate,
    shard_batch,
)

WS = 8


def run_tree_allreduce(make_tree, mesh=None, **kwargs):
    """make_tree(rank) -> pytree of np arrays. Returns rank-0's reduced tree."""
    mesh = mesh or flat_mesh()

    def body(rank_arr):
        rank = rank_arr[0]
        del rank  # values are baked per-shard below instead
        return None

    # Build a stacked global tree: leaves get a leading ws dim.
    trees = [make_tree(r) for r in range(WS)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *trees)

    def fn(local):
        local = jax.tree.map(lambda l: l[0], local)
        return jax.tree.map(
            lambda l: l[None],
            allreduce_tree(local, mesh=mesh, **kwargs),
        )

    specs = jax.tree.map(lambda _: P("dp"), stacked)
    out = jax.jit(
        shard_map(fn, mesh=mesh, in_specs=(specs,), out_specs=specs)
    )(jax.device_put(stacked, NamedSharding(mesh, P("dp"))))
    return jax.tree.map(lambda l: np.asarray(l[0]), out)


def test_tree_allreduce_mixed_leaves(monkeypatch):
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")

    def make_tree(rank):
        v = np.float32(rank + 1)
        return {
            "kernel": np.full((64, 32), v, np.float32),  # compressed
            "bias": np.full((32,), v, np.float32),  # dim<=1 -> raw psum
            "tiny": np.full((4,), v, np.float32),  # < minimal -> raw psum
            "ints": np.full((10,), rank + 1, np.int32),  # int -> raw psum
        }

    out = run_tree_allreduce(make_tree)
    s = WS * (WS + 1) // 2
    np.testing.assert_array_equal(out["kernel"], np.full((64, 32), s, np.float32))
    np.testing.assert_array_equal(out["bias"], np.full((32,), s, np.float32))
    np.testing.assert_array_equal(out["tiny"], np.full((4,), s, np.float32))
    np.testing.assert_array_equal(out["ints"], np.full((10,), s, np.int32))


def test_tree_allreduce_pattern_config(monkeypatch):
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "8")
    torch_cgx_tpu.set_layer_pattern_config(
        r"special", CompressionConfig(bits=2, bucket_size=64)
    )

    def make_tree(rank):
        v = np.float32(rank + 1)
        return {
            "special": np.full((50, 10), v, np.float32),
            "normal": np.full((50, 10), v, np.float32),
        }

    out = run_tree_allreduce(make_tree)
    s = WS * (WS + 1) // 2
    np.testing.assert_array_equal(out["special"], np.full((50, 10), s, np.float32))
    np.testing.assert_array_equal(out["normal"], np.full((50, 10), s, np.float32))


def test_fusion_slicing_flushes_all(monkeypatch):
    # Tiny fusion cap -> multiple slices; reference bug §8.5 (dropped slices)
    # must not be reproduced.
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.FUSION_BUFFER_SIZE_MB, "0")  # floor: 2048 elems

    def make_tree(rank):
        return {"big": np.full((5000,), np.float32(rank + 1), np.float32).reshape(50, 100)}

    out = run_tree_allreduce(make_tree)
    s = WS * (WS + 1) // 2
    np.testing.assert_array_equal(out["big"], np.full((50, 100), s, np.float32))


def test_average_divides_before_reduce(monkeypatch):
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")

    def make_tree(rank):
        return {"w": np.full((32, 32), np.float32(rank + 1), np.float32)}

    out = run_tree_allreduce(make_tree, average=True)
    avg = (WS + 1) / 2.0
    np.testing.assert_allclose(out["w"], np.full((32, 32), avg, np.float32), rtol=1e-6)


def _toy_data(n=512, d=16, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(d, 1)).astype(np.float32)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=(n, 1)).astype(np.float32)
    return x, y


def _mlp_init(d=16, h=32, seed=1):
    rng = np.random.default_rng(seed)
    return {
        "w1": jnp.asarray(rng.normal(size=(d, h)) * 0.3, jnp.float32),
        "b1": jnp.zeros((h,), jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(h, 1)) * 0.3, jnp.float32),
        "b2": jnp.zeros((1,), jnp.float32),
    }


def _mlp_loss(params, batch):
    x, y = batch
    z = jnp.tanh(x @ params["w1"] + params["b1"])
    pred = z @ params["w2"] + params["b2"]
    return jnp.mean((pred - y) ** 2)


def _train(bits, steps=40, stochastic_seed=None):
    import os

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = str(bits)
    os.environ[cgx_config.COMPRESSION_BUCKET_SIZE] = "128"
    mesh = flat_mesh()
    params = replicate(_mlp_init(), mesh)
    opt = optax.adam(3e-3)
    opt_state = replicate(opt.init(params), mesh)
    step = make_train_step(
        _mlp_loss, opt, mesh, stochastic_seed=stochastic_seed, donate=False
    )
    x, y = _toy_data()
    losses = []
    for i in range(steps):
        batch = shard_batch((x, y), mesh)
        params, opt_state, loss = step(params, opt_state, batch, jnp.int32(i))
        losses.append(float(loss))
    return losses


def test_training_loss_decreases_compressed():
    losses = _train(bits=4)
    assert losses[-1] < 0.5 * losses[0], losses[:3] + losses[-3:]


def test_compressed_matches_uncompressed_training():
    l8 = _train(bits=8)
    l32 = _train(bits=32)
    # 8-bit gradient compression should track the fp32 trajectory closely.
    assert abs(l8[-1] - l32[-1]) < 0.1 * max(l32[0], 1e-3), (l8[-1], l32[-1])


def test_training_with_stochastic_rounding(monkeypatch):
    monkeypatch.setenv(cgx_config.STOCHASTIC_ROUNDING, "1")
    losses = _train(bits=4, stochastic_seed=123)
    assert losses[-1] < 0.5 * losses[0]


def test_gradient_sync_replicated_outputs():
    # All devices must hold bit-identical synced grads (error symmetry).
    mesh = flat_mesh()

    def make_tree(rank):
        rng = np.random.default_rng(rank)
        return {"w": rng.normal(size=(128, 8)).astype(np.float32)}

    import os

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = "4"
    trees = [make_tree(r) for r in range(WS)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *trees)
    specs = jax.tree.map(lambda _: P("dp"), stacked)

    def fn(local):
        local = jax.tree.map(lambda l: l[0], local)
        synced = gradient_sync(local, mesh=mesh, average=False)
        return jax.tree.map(lambda l: l[None], synced)

    out = jax.jit(shard_map(fn, mesh=mesh, in_specs=(specs,), out_specs=specs))(
        jax.device_put(stacked, NamedSharding(mesh, P("dp")))
    )
    w = np.asarray(out["w"])  # (ws, 128, 8) — every row identical
    for r in range(1, WS):
        np.testing.assert_array_equal(w[0], w[r])
