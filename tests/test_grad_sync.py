"""Tree-level allreduce + training front-end tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from torch_cgx_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

import torch_cgx_tpu
from torch_cgx_tpu import config as cgx_config
from torch_cgx_tpu.config import CompressionConfig
from torch_cgx_tpu.parallel import (
    allreduce_tree,
    flat_mesh,
    gradient_sync,
    make_train_step,
    replicate,
    shard_batch,
)

WS = 8


def run_tree_allreduce(make_tree, mesh=None, **kwargs):
    """make_tree(rank) -> pytree of np arrays. Returns rank-0's reduced tree."""
    mesh = mesh or flat_mesh()

    def body(rank_arr):
        rank = rank_arr[0]
        del rank  # values are baked per-shard below instead
        return None

    # Build a stacked global tree: leaves get a leading ws dim.
    trees = [make_tree(r) for r in range(WS)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *trees)

    def fn(local):
        local = jax.tree.map(lambda l: l[0], local)
        return jax.tree.map(
            lambda l: l[None],
            allreduce_tree(local, mesh=mesh, **kwargs),
        )

    specs = jax.tree.map(lambda _: P("dp"), stacked)
    out = jax.jit(
        shard_map(fn, mesh=mesh, in_specs=(specs,), out_specs=specs)
    )(jax.device_put(stacked, NamedSharding(mesh, P("dp"))))
    return jax.tree.map(lambda l: np.asarray(l[0]), out)


def test_tree_allreduce_mixed_leaves(monkeypatch):
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")

    def make_tree(rank):
        v = np.float32(rank + 1)
        return {
            "kernel": np.full((64, 32), v, np.float32),  # compressed
            "bias": np.full((32,), v, np.float32),  # dim<=1 -> raw psum
            "tiny": np.full((4,), v, np.float32),  # < minimal -> raw psum
            "ints": np.full((10,), rank + 1, np.int32),  # int -> raw psum
        }

    out = run_tree_allreduce(make_tree)
    s = WS * (WS + 1) // 2
    np.testing.assert_array_equal(out["kernel"], np.full((64, 32), s, np.float32))
    np.testing.assert_array_equal(out["bias"], np.full((32,), s, np.float32))
    np.testing.assert_array_equal(out["tiny"], np.full((4,), s, np.float32))
    np.testing.assert_array_equal(out["ints"], np.full((10,), s, np.int32))


def test_tree_allreduce_pattern_config(monkeypatch):
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "8")
    torch_cgx_tpu.set_layer_pattern_config(
        r"special", CompressionConfig(bits=2, bucket_size=64)
    )

    def make_tree(rank):
        v = np.float32(rank + 1)
        return {
            "special": np.full((50, 10), v, np.float32),
            "normal": np.full((50, 10), v, np.float32),
        }

    out = run_tree_allreduce(make_tree)
    s = WS * (WS + 1) // 2
    np.testing.assert_array_equal(out["special"], np.full((50, 10), s, np.float32))
    np.testing.assert_array_equal(out["normal"], np.full((50, 10), s, np.float32))


def test_fusion_slicing_flushes_all(monkeypatch):
    # Tiny fusion cap -> multiple slices; reference bug §8.5 (dropped slices)
    # must not be reproduced.
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.FUSION_BUFFER_SIZE_MB, "0")  # floor: 2048 elems

    def make_tree(rank):
        return {"big": np.full((5000,), np.float32(rank + 1), np.float32).reshape(50, 100)}

    out = run_tree_allreduce(make_tree)
    s = WS * (WS + 1) // 2
    np.testing.assert_array_equal(out["big"], np.full((50, 100), s, np.float32))


def test_average_divides_before_reduce(monkeypatch):
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")

    def make_tree(rank):
        return {"w": np.full((32, 32), np.float32(rank + 1), np.float32)}

    out = run_tree_allreduce(make_tree, average=True)
    avg = (WS + 1) / 2.0
    np.testing.assert_allclose(out["w"], np.full((32, 32), avg, np.float32), rtol=1e-6)


def _toy_data(n=512, d=16, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(d, 1)).astype(np.float32)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=(n, 1)).astype(np.float32)
    return x, y


def _mlp_init(d=16, h=32, seed=1):
    rng = np.random.default_rng(seed)
    return {
        "w1": jnp.asarray(rng.normal(size=(d, h)) * 0.3, jnp.float32),
        "b1": jnp.zeros((h,), jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(h, 1)) * 0.3, jnp.float32),
        "b2": jnp.zeros((1,), jnp.float32),
    }


def _mlp_loss(params, batch):
    x, y = batch
    z = jnp.tanh(x @ params["w1"] + params["b1"])
    pred = z @ params["w2"] + params["b2"]
    return jnp.mean((pred - y) ** 2)


def _train(bits, steps=40, stochastic_seed=None):
    import os

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = str(bits)
    os.environ[cgx_config.COMPRESSION_BUCKET_SIZE] = "128"
    mesh = flat_mesh()
    params = replicate(_mlp_init(), mesh)
    opt = optax.adam(3e-3)
    opt_state = replicate(opt.init(params), mesh)
    step = make_train_step(
        _mlp_loss, opt, mesh, stochastic_seed=stochastic_seed, donate=False
    )
    x, y = _toy_data()
    losses = []
    for i in range(steps):
        batch = shard_batch((x, y), mesh)
        params, opt_state, loss = step(params, opt_state, batch, jnp.int32(i))
        losses.append(float(loss))
    return losses


def test_training_loss_decreases_compressed():
    losses = _train(bits=4)
    assert losses[-1] < 0.5 * losses[0], losses[:3] + losses[-3:]


def test_compressed_matches_uncompressed_training():
    l8 = _train(bits=8)
    l32 = _train(bits=32)
    # 8-bit gradient compression should track the fp32 trajectory closely.
    assert abs(l8[-1] - l32[-1]) < 0.1 * max(l32[0], 1e-3), (l8[-1], l32[-1])


def test_training_with_stochastic_rounding(monkeypatch):
    monkeypatch.setenv(cgx_config.STOCHASTIC_ROUNDING, "1")
    losses = _train(bits=4, stochastic_seed=123)
    assert losses[-1] < 0.5 * losses[0]


def test_gradient_sync_replicated_outputs():
    # All devices must hold bit-identical synced grads (error symmetry).
    mesh = flat_mesh()

    def make_tree(rank):
        rng = np.random.default_rng(rank)
        return {"w": rng.normal(size=(128, 8)).astype(np.float32)}

    import os

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = "4"
    trees = [make_tree(r) for r in range(WS)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *trees)
    specs = jax.tree.map(lambda _: P("dp"), stacked)

    def fn(local):
        local = jax.tree.map(lambda l: l[0], local)
        synced = gradient_sync(local, mesh=mesh, average=False)
        return jax.tree.map(lambda l: l[None], synced)

    out = jax.jit(shard_map(fn, mesh=mesh, in_specs=(specs,), out_specs=specs))(
        jax.device_put(stacked, NamedSharding(mesh, P("dp")))
    )
    w = np.asarray(out["w"])  # (ws, 128, 8) — every row identical
    for r in range(1, WS):
        np.testing.assert_array_equal(w[0], w[r])


def test_large_leaves_form_standalone_groups(monkeypatch):
    """Leaves >= CGX_STANDALONE_LAYER_ELEMS skip the fuse-concat: their
    group is a singleton, so allreduce_tree takes the zero-copy reshape
    path (the dominant codec-adjacent cost in the single-chip proxy)."""
    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.parallel.allreduce import _group_leaves

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.STANDALONE_LAYER_ELEMS, "1000")
    big1 = jnp.zeros((64, 32))   # 2048 elems -> standalone
    big2 = jnp.zeros((2000,), jnp.float32)  # 1-D but big: still own group
    small = [jnp.zeros((10, 10)) for _ in range(3)]  # fuse together
    leaves = [("a/big1", big1), ("b/big2", big2)] + [
        (f"c/s{i}", s) for i, s in enumerate(small)
    ]
    groups = _group_leaves(leaves, compress_small=False)
    singleton = [g for g in groups if len(g.indices) == 1]
    fused = [g for g in groups if len(g.indices) > 1]
    assert {g.indices[0] for g in singleton} == {0, 1}
    assert len(fused) == 1 and set(fused[0].indices) == {2, 3, 4}


def test_force_codec_ws1(monkeypatch):
    """CGX_DEBUG_FORCE_CODEC on a 1-device axis runs the quantize +
    self-dequantize round trip (the per-rank SRA work), so results carry
    quantization error but stay within the envelope."""
    from jax.sharding import Mesh, PartitionSpec as P

    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.parallel import gradient_sync

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, "64")
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(64, 64)), jnp.float32)

    def sync(g):
        return gradient_sync(g, mesh=mesh, average=False)

    run = jax.jit(
        shard_map(sync, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
    )
    # Without the flag: ws==1 is the identity.
    y = run({"w": x})["w"]
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    # With it: codec round trip — not identical, within per-bucket envelope.
    # (config is read at trace time, so build a fresh jit for the new env)
    monkeypatch.setenv(cgx_config.DEBUG_FORCE_CODEC, "1")
    run2 = jax.jit(
        shard_map(sync, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
    )
    y = run2({"w": x})["w"]
    err = np.abs(np.asarray(y) - np.asarray(x))
    assert err.max() > 0
    xb = np.asarray(x).reshape(-1, 64)
    unit = (xb.max(1) - xb.min(1)) / 15
    assert (err.reshape(-1, 64).max(1) <= unit * 0.51).all()


def test_sp_batch_with_rank1_leaf(monkeypatch):
    """sp_axis shards only the sequence dim of rank>=2 leaves; a batch dict
    with a rank-1 leaf (per-sample weights) must shard it over dp alone and
    replicate it over sp instead of crashing (code-review r3 finding)."""
    from jax.sharding import Mesh

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    b, s, d = 4, 32, 16
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "sp"))
    rng = np.random.default_rng(0)
    batch = {
        "x": jnp.asarray(rng.normal(size=(b, s, d)), jnp.float32),
        "w": jnp.asarray(rng.uniform(0.5, 1.5, size=(b,)), jnp.float32),
    }
    params = {"proj": jnp.asarray(rng.normal(size=(d, 1)) * 0.3, jnp.float32)}

    def loss_fn(p, bt):
        # mean over the local sequence shard; sp_lm_loss-style weighting by
        # the replicated rank-1 leaf
        pred = bt["x"] @ p["proj"]
        return jnp.mean(bt["w"][:, None, None] * pred**2)

    import optax

    opt = optax.sgd(0.1)
    step = make_train_step(loss_fn, opt, mesh, axes=("dp",), sp_axis="sp",
                           donate=False)
    sharded = shard_batch(batch, mesh, ("dp",), sp_axis="sp")
    # rank-1 leaf must not carry the sp dim
    assert sharded["w"].sharding.spec == P(("dp",))
    p2, _, loss = step(
        replicate(params, mesh), replicate(opt.init(params), mesh),
        sharded, jnp.int32(0),
    )
    assert np.isfinite(float(loss))
    # params moved (gradient flowed through the weighted loss)
    assert float(jnp.abs(p2["proj"] - params["proj"]).max()) > 0


def _train_ef(bits, steps=60, error_feedback=True, lr=5e-2):
    import os

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = str(bits)
    os.environ[cgx_config.COMPRESSION_BUCKET_SIZE] = "128"
    from torch_cgx_tpu.parallel import init_error_feedback

    mesh = flat_mesh()
    params = _mlp_init()
    opt = optax.sgd(lr)
    step = make_train_step(_mlp_loss, opt, mesh, donate=False,
                           error_feedback=error_feedback)
    p = replicate(params, mesh)
    s = replicate(opt.init(params), mesh)
    ef = init_error_feedback(params, mesh) if error_feedback else None
    x, y = _toy_data()
    losses = []
    for i in range(steps):
        batch = shard_batch((x, y), mesh)
        if error_feedback:
            p, s, ef, loss = step(p, s, ef, batch, jnp.int32(i))
        else:
            p, s, loss = step(p, s, batch, jnp.int32(i))
        losses.append(float(loss))
    return losses, ef


def test_error_feedback_residual_mechanics(monkeypatch):
    """One EF sync of a KNOWN gradient: the residual must be nonzero, and
    bounded per element by half a quantization unit of the wire's actual
    bucket layout (ws-chunked rows, buckets restarting per chunk) — this
    pins the roundtrip to the transport's real stage-1 geometry."""
    from torch_cgx_tpu.parallel import compressed_allreduce_transform

    bits, bucket = 2, 64
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, str(bits))
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, str(bucket))
    mesh = flat_mesh()
    rng = np.random.default_rng(3)
    g = jnp.asarray(rng.normal(size=(16, 32)), jnp.float32)  # 512 elems
    tx = compressed_allreduce_transform(mesh=mesh, error_feedback=True)

    def run(gg):
        state = tx.init({"w": gg})
        _, state = tx.update({"w": gg}, state)
        return state.e["w"]

    e = np.asarray(
        jax.jit(
            shard_map(run, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
        )(g)
    )
    assert np.abs(e).max() > 0, "2-bit quantization left a zero residual"
    # wire layout: g_eff = g/8 flat 512 elems -> (ws=8, chunk=64) rows,
    # one 64-elem bucket per row; deterministic rounding error <= unit/2.
    rows = (np.asarray(g, np.float64).reshape(-1) / WS).reshape(8, 64)
    unit = (rows.max(axis=1) - rows.min(axis=1)) / (2**bits - 1)
    bound = unit[:, None] / 2 + 1e-6
    assert (np.abs(e.reshape(8, 64)) <= bound).all(), (
        np.abs(e.reshape(8, 64)).max(axis=1), bound[:, 0])


def test_error_feedback_zero_residual_on_exact_wire(monkeypatch):
    """PSUM reduction sends raw f32 — the wire is exact, so EF must carry a
    zero residual instead of injecting phantom corrections (code-review r3
    finding: the roundtrip must mirror the transport's decision tree)."""
    from torch_cgx_tpu.parallel import compressed_allreduce_transform

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "2")
    monkeypatch.setenv("CGX_INNER_REDUCTION_TYPE", "PSUM")
    mesh = flat_mesh()
    g = jnp.asarray(np.random.default_rng(4).normal(size=(16, 32)), jnp.float32)
    tx = compressed_allreduce_transform(mesh=mesh, error_feedback=True)

    def run(gg):
        state = tx.init({"w": gg})
        reduced, state = tx.update({"w": gg}, state)
        return reduced["w"], state.e["w"]

    red, e = jax.jit(
        shard_map(run, mesh=mesh, in_specs=P(), out_specs=P(),
                  check_vma=False)
    )(g)
    np.testing.assert_array_equal(np.asarray(e), 0.0)
    # and the reduction itself is the exact mean
    np.testing.assert_allclose(np.asarray(red), np.asarray(g), rtol=1e-6)


def test_error_feedback_improves_outlier_bucket_training():
    """The regime EF exists for: per-bucket outliers dominate the max-min
    range, so small-coordinate gradients quantize with a systematic bias
    that adam amplifies. With residual accumulation the bias cancels over
    steps — final loss with EF must beat no-EF (deterministic seeds; the
    reference stubs this hook but never wires it). 2 bits: with the r4
    exact-own-chunk SRA, 4-bit wire bias is too small to dominate this
    toy's optimization noise."""
    import os

    from jax.sharding import Mesh

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = "2"
    os.environ[cgx_config.COMPRESSION_BUCKET_SIZE] = "64"
    from torch_cgx_tpu.parallel import init_error_feedback

    d = 512
    rng = np.random.default_rng(0)
    scale = np.where(np.arange(d) % 8 == 0, 100.0, 1.0)
    xs = (rng.normal(size=(256, d)) * scale).astype(np.float32)
    w_true = (
        rng.normal(size=(d, 1)) / np.sqrt(d) / scale[:, None]
    ).astype(np.float32)
    ys = xs @ w_true

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    def train(error_feedback):
        mesh = flat_mesh()
        params = {"w": jnp.zeros((d, 1), jnp.float32)}
        opt = optax.adam(3e-3)
        step = make_train_step(loss_fn, opt, mesh, donate=False,
                               error_feedback=error_feedback)
        p = replicate(params, mesh)
        s = replicate(opt.init(params), mesh)
        ef = init_error_feedback(params, mesh) if error_feedback else None
        for i in range(80):
            b = shard_batch((jnp.asarray(xs), jnp.asarray(ys)), mesh)
            if error_feedback:
                p, s, ef, loss = step(p, s, ef, b, jnp.int32(i))
            else:
                p, s, loss = step(p, s, b, jnp.int32(i))
        return float(loss)

    l_ef, l_plain = train(True), train(False)
    assert l_ef < l_plain * 0.9, (l_ef, l_plain)


def test_error_feedback_replicas_stay_identical():
    """EF state varies per device, but params must remain bit-identical
    replicas (everyone decodes the same reduced wire)."""
    import os

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = "2"
    from torch_cgx_tpu.parallel import init_error_feedback

    mesh = flat_mesh()
    params = _mlp_init()
    opt = optax.sgd(1e-2)
    step = make_train_step(_mlp_loss, opt, mesh, donate=False,
                           error_feedback=True)
    p = replicate(params, mesh)
    s = replicate(opt.init(params), mesh)
    ef = init_error_feedback(params, mesh)
    x, y = _toy_data()
    for i in range(3):
        p, s, ef, _ = step(p, s, ef, shard_batch((x, y), mesh), jnp.int32(i))
    for leaf in jax.tree.leaves(p):
        shards = [np.asarray(sh.data) for sh in leaf.addressable_shards]
        for sh in shards[1:]:
            np.testing.assert_array_equal(shards[0], sh)


# ---------------------------------------------------------------------------
# EF roundtrip mirrors per transport (advisor r3: the roundtrip must measure
# the same layout AND stochastic draw as the wire, per algorithm).
# ---------------------------------------------------------------------------


def _per_device_roundtrip(g, mesh, *, key=None, topology=None, axes=("dp",)):
    """allreduce_tree(return_roundtrip=True), replicated input; returns each
    device's roundtrip stacked on a leading ws_total dim."""

    def run(gg):
        _, rt = allreduce_tree(
            {"w": gg}, mesh=mesh, axes=axes, topology=topology, key=key,
            average=False, return_roundtrip=True,
        )
        return rt["w"][None]

    spec = P(mesh.axis_names)
    out = jax.jit(
        shard_map(run, mesh=mesh, in_specs=P(), out_specs=spec,
                  check_vma=False)
    )(g)
    return np.asarray(out)


def test_ring_roundtrip_support_is_own_segment(monkeypatch):
    """RING's only per-device-attributable quantization of raw data is the
    step-0 hop of the own outgoing segment (row index = rank): the roundtrip
    must be exact on every other segment and bucket-bounded on the own one."""
    bits, bucket = 4, 64
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, str(bits))
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, str(bucket))
    monkeypatch.setenv(cgx_config.INNER_REDUCTION_TYPE, "RING")
    mesh = flat_mesh()
    g = jnp.asarray(np.random.default_rng(7).normal(size=(16, 32)), jnp.float32)
    rts = _per_device_roundtrip(g, mesh)  # (ws, 16, 32)
    rows32 = np.asarray(g).reshape(WS, 64)
    rows = rows32.astype(np.float64)
    for r in range(WS):
        rt = rts[r].reshape(WS, 64)
        mask = np.ones(WS, bool)
        mask[r] = False
        np.testing.assert_array_equal(rt[mask], rows32[mask])
        err = np.abs(rt[r] - rows[r])
        assert err.max() > 0, "own segment left unquantized in the roundtrip"
        unit = (rows[r].max() - rows[r].min()) / (2**bits - 1)
        assert err.max() <= unit / 2 + 1e-6


def test_ring_roundtrip_matches_wire_key(monkeypatch):
    """Stochastic RING: the own-segment roundtrip must reproduce
    ring_allreduce's step-0 draw, keyed fold_in(fold_in(piece_key, 0), rank)
    — any other derivation measures a different random field (advisor r3)."""
    from torch_cgx_tpu.ops import dispatch

    bits, bucket = 4, 64
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, str(bits))
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, str(bucket))
    monkeypatch.setenv(cgx_config.STOCHASTIC_ROUNDING, "1")
    monkeypatch.setenv(cgx_config.INNER_REDUCTION_TYPE, "RING")
    mesh = flat_mesh()
    key = jax.random.key(11)
    g = jnp.asarray(np.random.default_rng(8).normal(size=(16, 32)), jnp.float32)
    rts = _per_device_roundtrip(g, mesh, key=key)
    cc = cgx_config.default_compression_config()
    assert cc.stochastic and cc.bits == bits
    # piece key: fold_in(group 0) then fold_in(slice offset 0)
    piece_key = jax.random.fold_in(jax.random.fold_in(key, 0), 0)
    rows = jnp.asarray(np.asarray(g).reshape(WS, 64))

    def oracle(r, k_r):
        q = dispatch.quantize_batch(rows[r][None], cc, k_r)
        return np.asarray(dispatch.dequantize_batch(q, out_dtype=jnp.float32))[0]

    for r in range(WS):
        got = rts[r].reshape(WS, 64)[r]
        # correct draw: equal up to last-ulp reconstruction differences
        # between separately compiled programs
        k_r = jax.random.fold_in(jax.random.fold_in(piece_key, 0), r)
        np.testing.assert_allclose(got, oracle(r, k_r), rtol=0, atol=1e-5)
        # negative control: the pre-fix phase-1 SRA key draws a different
        # random field — differences at quantization-unit scale
        k_bad = jax.random.fold_in(jax.random.fold_in(piece_key, 1), r)
        assert np.abs(got - oracle(r, k_bad)).max() > 1e-2


def test_alltoall_roundtrip_matches_wire_layout_and_key(monkeypatch):
    """ALLTOALL quantizes the WHOLE buffer as one row (its own bucket
    boundaries, NOT the (ws, chunk) stage-1 rows) keyed fold_in(key, rank),
    and every peer decodes those bytes — the roundtrip must mirror both the
    layout and the key (advisor r3)."""
    from torch_cgx_tpu.ops import dispatch

    bits, bucket = 4, 96  # 512 elems: 96-elem buckets differ from (8, 64) rows
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, str(bits))
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, str(bucket))
    monkeypatch.setenv(cgx_config.STOCHASTIC_ROUNDING, "1")
    monkeypatch.setenv(cgx_config.INNER_REDUCTION_TYPE, "ALLTOALL")
    mesh = flat_mesh()
    key = jax.random.key(13)
    g = jnp.asarray(np.random.default_rng(9).normal(size=(16, 32)), jnp.float32)
    rts = _per_device_roundtrip(g, mesh, key=key)
    cc = cgx_config.default_compression_config()
    piece_key = jax.random.fold_in(jax.random.fold_in(key, 0), 0)
    flat = jnp.asarray(np.asarray(g).reshape(-1))
    for r in range(WS):
        k_r = jax.random.fold_in(piece_key, r)
        q = dispatch.quantize_batch(flat[None], cc, k_r)
        expect = np.asarray(dispatch.dequantize_batch(q, out_dtype=jnp.float32))[0]
        np.testing.assert_allclose(
            rts[r].reshape(-1), expect, rtol=0, atol=1e-5
        )
        # negative control: the pre-fix (ws, chunk)-row layout restarts
        # buckets every 64 elems instead of 96 — unit-scale differences
        q_bad = dispatch.quantize_batch(
            flat.reshape(WS, 64),
            cc,
            jax.random.fold_in(jax.random.fold_in(piece_key, 1), r),
        )
        bad = np.asarray(
            dispatch.dequantize_batch(q_bad, out_dtype=jnp.float32)
        ).reshape(-1)
        assert np.abs(rts[r].reshape(-1) - bad).max() > 1e-2


def test_hier_leader_psum_intra_still_quantizes_stage1(monkeypatch):
    """The hierarchical leader scheme gates its stage-1 reduce-scatter on
    intra_compress only — intra_reduction=PSUM still quantizes the wire
    (reducers.hierarchical_allreduce), so the roundtrip must not report a
    phantom zero residual (advisor r3)."""
    from torch_cgx_tpu.parallel import mesh as mesh_mod

    bits, bucket = 2, 64
    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, str(bits))
    monkeypatch.setenv(cgx_config.COMPRESSION_BUCKET_SIZE, str(bucket))
    topo = cgx_config.TopologyConfig(intra_reduction="PSUM")
    mesh = mesh_mod.hierarchical_mesh(intra_size=4)
    g = jnp.asarray(np.random.default_rng(10).normal(size=(16, 32)), jnp.float32)
    rts = _per_device_roundtrip(
        g, mesh, topology=topo, axes=("cross", "intra")
    )
    # stage-1 layout: (ws_intra=4, chunk=128) rows, 64-elem buckets.
    rows = np.asarray(g, np.float64).reshape(4, 128)
    buckets = rows.reshape(4, 2, 64)
    unit = (buckets.max(-1) - buckets.min(-1)) / (2**bits - 1)
    bound = np.repeat(unit[..., None], 64, -1).reshape(4, 128) / 2 + 1e-6
    for d in range(8):
        err = np.abs(rts[d].reshape(4, 128) - rows)
        assert err.max() > 0, "phantom zero residual on a quantized wire"
        assert (err <= bound).all()


def test_runtime_wire_metrics(monkeypatch):
    """CGX_METRICS_RUNTIME=1: wire counters bump per EXECUTED step (host
    callback), not once per trace — the runtime observability the
    reference's printf logging lacks (VERDICT r3 weak #5)."""
    from torch_cgx_tpu.utils.logging import metrics

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    monkeypatch.setenv(cgx_config.METRICS_RUNTIME, "1")
    mesh = flat_mesh()
    g = jnp.asarray(np.random.default_rng(2).normal(size=(16, 32)), jnp.float32)
    fn = jax.jit(
        shard_map(
            lambda x: allreduce_tree({"w": x}, mesh=mesh)["w"],
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False,
        )
    )
    metrics.reset()
    jax.block_until_ready(fn(g))
    after_one = metrics.get("cgx.runtime.allreduce.compressed_elems")
    assert after_one > 0 and after_one % g.size == 0
    per_step = after_one
    for _ in range(2):
        jax.block_until_ready(fn(g))
    # io_callback delivery is async: drain all dispatched effects first,
    # then poll with a generous deadline and fail with a diagnostic
    # rather than a bare mismatch (advisor r4: a loaded CI host can
    # exceed a 10 s budget before delivery).
    jax.effects_barrier()
    import time as _time

    deadline = _time.time() + 60
    while (
        metrics.get("cgx.runtime.allreduce.compressed_elems") < 3 * per_step
        and _time.time() < deadline
    ):
        _time.sleep(0.05)
    total = metrics.get("cgx.runtime.allreduce.compressed_elems")
    assert total == 3 * per_step, (
        f"runtime counter {total} != expected {3 * per_step} "
        f"(per_step={per_step}) after effects_barrier + 60 s poll — "
        "a lost io_callback delivery or an over-count"
    )
    # trace counter stays at one program's worth
    assert metrics.get("cgx.trace.allreduce.compressed_elems") == g.size


# ---------------------------------------------------------------------------
# Trace-time layout cache (ISSUE 4): the group/concat/split/slice plan is
# computed once per (treedef, shapes, config state), not per call.
# ---------------------------------------------------------------------------


def _trace_allreduce_once(mesh, tree):
    """One fresh trace of allreduce_tree (new callables each time — the
    shape of a make_train_step retrace or a user re-wrapping the sync)."""
    body = shard_map(
        lambda t: jax.tree.map(
            lambda l: l[None],
            allreduce_tree(
                jax.tree.map(lambda l: l[0], t), mesh=mesh, axes=("dp",)
            ),
        ),
        mesh=mesh,
        in_specs=P("dp"),
        out_specs=P("dp"),
        check_vma=False,
    )
    jax.make_jaxpr(body)(tree)


def test_layout_cache_hits_across_traces(monkeypatch):
    from torch_cgx_tpu.parallel import allreduce as ar

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    mesh = flat_mesh()
    tree = {
        "w": jnp.ones((WS, 64, 64)),
        "b": jnp.ones((WS, 128)),
        "v": jnp.ones((WS, 32, 32)),
    }
    ar.layout_cache_clear()
    _trace_allreduce_once(mesh, tree)
    s1 = ar.layout_cache_stats()
    assert s1 == {"hits": 0, "misses": 1}, s1
    _trace_allreduce_once(mesh, tree)
    s2 = ar.layout_cache_stats()
    assert s2 == {"hits": 1, "misses": 1}, s2
    # a different tree structure is a different plan
    _trace_allreduce_once(mesh, {"w": jnp.ones((WS, 64, 64))})
    assert ar.layout_cache_stats()["misses"] == 2


def test_layout_cache_invalidated_by_registry_and_env(monkeypatch):
    from torch_cgx_tpu.parallel import allreduce as ar

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    mesh = flat_mesh()
    tree = {"w": jnp.ones((WS, 64, 64)), "b": jnp.ones((WS, 128))}
    ar.layout_cache_clear()
    _trace_allreduce_once(mesh, tree)
    # pattern re-registration bumps the registry version -> fresh plan,
    # never a stale hit (the make_train_step trace-cache rule)
    cgx_config.set_layer_pattern_config("w", CompressionConfig(bits=2))
    try:
        _trace_allreduce_once(mesh, tree)
        assert ar.layout_cache_stats() == {"hits": 0, "misses": 2}
    finally:
        cgx_config.clear_registry()
    # env-derived knobs are part of the key too (a fusion-threshold flip
    # between calls must re-slice)
    before = ar.layout_cache_stats()["misses"]
    monkeypatch.setenv("CGX_FUSION_BUFFER_SIZE_MB", "1")
    _trace_allreduce_once(mesh, tree)
    assert ar.layout_cache_stats()["misses"] == before + 1


def test_layout_cache_bounded(monkeypatch):
    from torch_cgx_tpu.parallel import allreduce as ar

    monkeypatch.setenv(cgx_config.COMPRESSION_QUANTIZATION_BITS, "4")
    mesh = flat_mesh()
    ar.layout_cache_clear()
    for i in range(ar._LAYOUT_CACHE_MAX + 8):
        _trace_allreduce_once(mesh, {"w": jnp.ones((WS, 8, 8 + i))})
    assert len(ar._LAYOUT_CACHE) <= ar._LAYOUT_CACHE_MAX
