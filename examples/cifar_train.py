"""ResNet-18 CIFAR training with quantized gradient allreduce — the
TPU-native counterpart of the reference example
(/root/reference/examples/cifar_train.py: ResNet-18, CIFAR-10/100, DDP with
the cgx hook, step-decay LR — SURVEY.md §2.2).

Differences by design: the training loop is JAX SPMD over a device mesh
(flat ``dp`` or hierarchical ``cross x intra``) instead of one process per
GPU under mpirun; gradient compression rides :func:`gradient_sync` inside
``shard_map``; BatchNorm statistics are synchronized with a plain ``pmean``
(dim-1 tensors stay uncompressed, matching the hook's ``should_compress_``
rule, allreduce_hooks.py:42-45).

Data: loads CIFAR-10/100 from ``--data-dir`` (numpy ``.npz`` with keys
``x_train/y_train/x_test/y_test``) when present; ``--dataset digits``
trains on sklearn's bundled REAL handwritten-digit images (1,797 8x8
grayscale scans, upsampled to the 32x32x3 input — available with zero
network egress, so convergence and 4-bit-vs-fp32 top-1 parity are
measured on genuine data, not a synthetic stand-in); otherwise generates
a learnable synthetic stand-in so the example runs end-to-end anywhere.
A held-out test split is evaluated after training and reported as
``test_acc`` in the JSON summary.

Run (single host, virtual 8-device mesh):
    python examples/cifar_train.py --simulate-devices 8 --quantization-bits 4
Run (real TPU):
    python examples/cifar_train.py --epochs 10 --quantization-bits 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Allow `python examples/cifar_train.py` from a source checkout.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torch_cgx_tpu.utils.compat import shard_map  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser(description="CGX-TPU CIFAR training")
    p.add_argument("--dataset", choices=["cifar10", "cifar100", "digits"],
                   default="cifar10")
    p.add_argument("--data-dir", default=None,
                   help=".npz dataset path (synthetic data when absent)")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--steps-per-epoch", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=256,
                   help="global batch (split across data-parallel devices)")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    # Reference run_cifar.sh: 8-bit, bucket 1024; BASELINE.md north star: 4-bit.
    p.add_argument("--quantization-bits", type=int, default=4)
    p.add_argument("--quantization-bucket-size", type=int, default=1024)
    p.add_argument("--arch", choices=["resnet18", "resnet50"],
                   default="resnet18",
                   help="resnet50 = the BASELINE.md ResNet-50 DDP config "
                        "row (pair with --quantization-bucket-size 512 to "
                        "match that row exactly)")
    p.add_argument("--reduction", choices=["SRA", "RING", "ALLTOALL", "PSUM"],
                   default="SRA")
    p.add_argument("--hierarchical", type=int, default=0, metavar="INTRA",
                   help="use a (cross x INTRA) two-level mesh")
    p.add_argument("--simulate-devices", type=int, default=0,
                   help="N virtual CPU devices (testing without a TPU pod)")
    p.add_argument("--bf16", action="store_true", help="bf16 model compute")
    p.add_argument("--seed", type=int, default=42)
    return p.parse_args()


def load_data(args, num_classes: int):
    """(x_train, y_train, x_test, y_test) in normalized 32x32x3 float32."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    if args.data_dir:
        path = os.path.join(args.data_dir, f"{args.dataset}.npz")
        d = np.load(path)

        def norm(x):
            x = x.astype(np.float32) / 255.0
            mean = x.mean(axis=(0, 1, 2), keepdims=True)
            std = x.std(axis=(0, 1, 2), keepdims=True) + 1e-6
            return (x - mean) / std

        x_tr = norm(d["x_train"])
        y_tr = d["y_train"].astype(np.int32).reshape(-1)
        if "x_test" in d:  # train-only npz worked before test eval existed
            return (
                x_tr, y_tr,
                norm(d["x_test"]),
                d["y_test"].astype(np.int32).reshape(-1),
            )
        return x_tr, y_tr, x_tr[:0], y_tr[:0]
    if args.dataset == "digits":
        # Real data with zero egress: sklearn's bundled handwritten-digit
        # scans. 8x8 grayscale -> 4x nearest-neighbor upsample to 32x32,
        # gray replicated to 3 channels; deterministic 80/20 split.
        try:
            from sklearn.datasets import load_digits
        except ImportError:
            raise SystemExit(
                "cifar_train.py: --dataset digits needs scikit-learn "
                "(pip install scikit-learn, or use the synthetic default)"
            )

        d = load_digits()
        x = (d.images.astype(np.float32) / 16.0 - 0.5) * 2.0
        x = np.kron(x, np.ones((1, 4, 4), np.float32))  # (n, 32, 32)
        x = np.repeat(x[..., None], 3, axis=-1)
        y = d.target.astype(np.int32)
        perm = np.random.default_rng(0).permutation(len(y))  # split fixed
        x, y = x[perm], y[perm]
        cut = int(0.8 * len(y))
        return x[:cut], y[:cut], x[cut:], y[cut:]
    # Synthetic CIFAR-shaped data: each class is a fixed random template
    # plus noise — easily separable, so falling loss/rising accuracy
    # demonstrates the training loop works end to end.
    n = 8192
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    templates = rng.normal(size=(num_classes, 32, 32, 3)).astype(np.float32)
    x = templates[y] + rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    n_test = 1024
    y_test = rng.integers(0, num_classes, size=n_test).astype(np.int32)
    x_test = templates[y_test] + rng.normal(
        size=(n_test, 32, 32, 3)
    ).astype(np.float32)
    return x, y, x_test, y_test


def main():
    args = parse_args()
    if args.simulate_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.simulate_devices}"
        )
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.simulate_devices:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    import optax

    from torch_cgx_tpu import CompressionConfig, set_layer_pattern_config
    from torch_cgx_tpu import data as cgx_data
    from torch_cgx_tpu.config import TopologyConfig
    from torch_cgx_tpu.models import ResNet18, ResNet50
    from torch_cgx_tpu.parallel import mesh as mesh_mod
    from torch_cgx_tpu.parallel.grad_sync import gradient_sync, replicate
    from jax.sharding import PartitionSpec as P

    num_classes = 100 if args.dataset == "cifar100" else 10
    if args.dataset == "digits" and args.data_dir:
        raise SystemExit("--dataset digits is built in; drop --data-dir")

    # Per-layer config: conv/dense kernels compressed at the requested bits,
    # everything dim<=1 (biases, BatchNorm scales) uncompressed — the same
    # split the DDP hook applies (allreduce_hooks.py:42-45).
    set_layer_pattern_config(
        r"(kernel|embedding)$",
        CompressionConfig(
            bits=args.quantization_bits,
            bucket_size=args.quantization_bucket_size,
        ),
    )

    if args.hierarchical:
        mesh = mesh_mod.hierarchical_mesh(intra_size=args.hierarchical)
        axes = (mesh_mod.CROSS_AXIS, mesh_mod.INTRA_AXIS)
        topo = TopologyConfig(cross_reduction=args.reduction)
    else:
        mesh = mesh_mod.flat_mesh()
        axes = (mesh_mod.DP_AXIS,)
        topo = TopologyConfig(intra_reduction=args.reduction)
    n_dev = int(np.prod([mesh.shape[a] for a in axes]))
    assert args.batch_size % n_dev == 0, (
        f"global batch {args.batch_size} must divide over {n_dev} devices"
    )

    arch = ResNet50 if args.arch == "resnet50" else ResNet18
    model = arch(
        num_classes=num_classes,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
    )
    x_all, y_all, x_test, y_test = load_data(args, num_classes)

    rng = jax.random.PRNGKey(args.seed)
    variables = model.init(rng, jnp.zeros((1, 32, 32, 3), jnp.float32))
    params, batch_stats = variables["params"], variables["batch_stats"]

    steps_total = args.epochs * args.steps_per_epoch
    # Reference uses step-decay at epoch milestones; cosine is the TPU-era
    # default — keep step-decay for parity.
    lr = optax.piecewise_constant_schedule(
        args.lr,
        {int(steps_total * 0.5): 0.1, int(steps_total * 0.75): 0.1},
    )
    optimizer = optax.sgd(lr, momentum=args.momentum)
    opt_state = optimizer.init(params)

    def loss_fn(params, batch_stats, batch):
        logits, updated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            train=True,
            mutable=["batch_stats"],
        )
        onehot = jax.nn.one_hot(batch["label"], num_classes)
        loss = optax.softmax_cross_entropy(logits, onehot).mean()
        acc = (logits.argmax(-1) == batch["label"]).mean()
        return loss, (updated["batch_stats"], acc)

    def _step(params, batch_stats, opt_state, batch):
        (loss, (batch_stats, acc)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, batch_stats, batch)
        grads = gradient_sync(
            grads, mesh=mesh, axes=axes, topology=topo, average=True
        )
        # BatchNorm running stats: plain mean across replicas (small dim-1
        # tensors — never compressed).
        batch_stats = jax.tree.map(
            lambda x: jax.lax.pmean(x, axes), batch_stats
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.pmean(loss, axes)
        acc = jax.lax.pmean(acc, axes)
        return params, batch_stats, opt_state, loss, acc

    step = jax.jit(
        shard_map(
            _step,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(axes)),
            out_specs=(P(), P(), P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0, 1, 2),
    )

    params = replicate(params, mesh)
    batch_stats = replicate(batch_stats, mesh)
    opt_state = replicate(opt_state, mesh)

    data_rng = np.random.default_rng(args.seed)
    n = x_all.shape[0]

    def sample_batches():
        while True:
            idx = data_rng.integers(0, n, size=args.batch_size)
            yield {"image": x_all[idx], "label": y_all[idx]}

    # Input pipeline: device placement sharded over the dp axes, with
    # background prefetch overlapping H2D transfer and step compute.
    batches = cgx_data.prefetch(
        cgx_data.shard_batches(sample_batches(), mesh, axes)
    )

    first_epoch_loss = last_loss = last_acc = None
    t0 = time.time()
    for epoch in range(args.epochs):
        losses, accs = [], []
        for s in range(args.steps_per_epoch):
            params, batch_stats, opt_state, loss, acc = step(
                params, batch_stats, opt_state, next(batches)
            )
            losses.append(float(loss))
            accs.append(float(acc))
        # Epoch-averaged metrics (the reference example averages with its
        # Metric helper too, cifar_train.py:200-239).
        ep_loss, ep_acc = float(np.mean(losses)), float(np.mean(accs))
        if first_epoch_loss is None:
            first_epoch_loss = ep_loss
        last_loss, last_acc = ep_loss, ep_acc
        print(
            f"epoch {epoch + 1}/{args.epochs}: loss={ep_loss:.4f} "
            f"acc={ep_acc:.4f} ({time.time() - t0:.1f}s)",
            flush=True,
        )
    steps_per_s = steps_total / (time.time() - t0)

    # Held-out evaluation (the reference example reports test top-1 per
    # epoch, cifar_train.py:200-239; one final pass suffices here). Params
    # are replicated, so a plain jit sees them as ordinary inputs.
    @jax.jit
    def eval_logits(params, batch_stats, images):
        return model.apply(
            {"params": params, "batch_stats": batch_stats},
            images,
            train=False,
        )

    correct = total = 0
    eb = 256
    for i in range(0, len(y_test), eb):
        xe, ye = x_test[i:i + eb], y_test[i:i + eb]
        valid = len(ye)
        if valid < eb:  # pad the tail so eval compiles exactly once
            xe = np.concatenate([xe, np.repeat(xe[-1:], eb - valid, axis=0)])
        logits = eval_logits(params, batch_stats, jnp.asarray(xe))
        preds = np.asarray(logits).argmax(-1)[:valid]
        correct += int((preds == ye).sum())
        total += valid
    # None (not a fake 0.0) when the dataset ships no test split.
    test_acc = round(correct / total, 4) if total else None

    print(json.dumps({
        "example": "cifar_train",
        "arch": args.arch,
        "dataset": args.dataset,
        "devices": n_dev,
        # Effective wire: a flat PSUM run moves fp32 regardless of the bits
        # flag. In hierarchical mode --reduction only sets the CROSS level;
        # the intra level still compresses, so the wire stays quantized.
        "reduction": args.reduction,
        "bits": (
            32
            if args.reduction == "PSUM" and not args.hierarchical
            else args.quantization_bits
        ),
        "first_loss": first_epoch_loss,
        "final_loss": last_loss,
        "final_acc": last_acc,
        "test_acc": test_acc,
        "steps_per_s": round(steps_per_s, 3),
    }))
    return 0 if args.epochs < 2 or last_loss < first_epoch_loss else 1


if __name__ == "__main__":
    sys.exit(main())
