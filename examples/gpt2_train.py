"""GPT-2 pretraining with compressed data parallelism — the flagship
composition example (the reference ships only a CIFAR DDP script,
/root/reference/examples/cifar_train.py; SURVEY.md §2.3 lists TP/PP/SP as
absent there).

One mesh, every axis optional:

* ``--dp N``      data parallelism with 1-8 bit quantized gradient allreduce
* ``--cross M``   hierarchical DP: cross x intra axes (DCN x ICI on real
                  pods), INTRA_BROADCAST leader scheme per config
* ``--tp N``      Megatron-style tensor parallelism (GSPMD inserts the
                  collectives from models.gpt2.tp_param_spec)
* ``--sp N``      ring-attention sequence parallelism for long context

Runs on anything: a v5e pod slice, a single chip, or the virtual CPU mesh
(JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8).
Synthetic next-token data keeps it hermetic; loss printed per step.

    python examples/gpt2_train.py --dp 4 --tp 2 --bits 4 --steps 20
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args():
    p = argparse.ArgumentParser(description="GPT-2 compressed-DP training")
    p.add_argument("--dp", type=int, default=0, help="data-parallel ways (0 = all devices)")
    p.add_argument("--cross", type=int, default=1, help="split dp into cross x intra")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel ways")
    p.add_argument("--sp", type=int, default=1, help="sequence-parallel ways (ring attention)")
    p.add_argument("--bits", type=int, default=4)
    p.add_argument("--bucket-size", type=int, default=512)
    p.add_argument("--stochastic", action="store_true", help="QSGD stochastic rounding")
    p.add_argument("--error-feedback", action="store_true",
                   help="accumulate per-device wire-quantization residuals")
    def _rank(v):
        v = int(v)
        if v < 0:
            raise argparse.ArgumentTypeError("powersgd rank must be >= 0")
        return v

    p.add_argument("--powersgd-rank", type=_rank, default=0,
                   help="replace the quantized allreduce with PowerSGD "
                        "low-rank compression at this rank (0 = off)")
    def _ratio(v):
        v = float(v)
        if v and not 0 < v < 1:
            raise argparse.ArgumentTypeError("topk ratio must be in (0, 1)")
        return v

    p.add_argument("--topk-ratio", type=_ratio, default=0,
                   help="replace the quantized allreduce with top-k "
                        "sparsification shipping this fraction of each "
                        "gradient's coordinates (0 = off)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=8, help="global batch (sequences)")
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--data", choices=["synthetic", "text"], default="synthetic",
                   help="'text' = REAL byte-level LM on this repo's own "
                        "documentation (genuine English prose, zero "
                        "egress); vocab forced to 256, 90/10 val split, "
                        "val_loss reported")
    def _avg_bits(v):
        v = float(v)
        if v and not 2 <= v <= 8:  # solve_bit_allocation's bits_range
            raise argparse.ArgumentTypeError("average bits must be in [2, 8]")
        return v

    def _every(v):
        v = int(v)
        if v < 1:
            raise argparse.ArgumentTypeError("--adapt-every must be >= 1")
        return v

    p.add_argument("--adaptive-bits", type=_avg_bits, default=0,
                   help="adaptive per-layer bit allocation at this AVERAGE "
                        "bit budget (parallel/adaptive.py, L-GreCo lineage); "
                        "re-solved every --adapt-every steps; 0 = off")
    p.add_argument("--adapt-every", type=_every, default=50)
    p.add_argument("--checkpoint-dir", default=None,
                   help="save/resume directory (torch_cgx_tpu.checkpoint): "
                        "resumes from the latest step if one exists, saves "
                        "at the end of the run; the per-layer compression "
                        "registry rides inside the checkpoint")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--cpu", action="store_true", help="force the virtual CPU mesh")
    return p.parse_args()


def load_text_corpus(seq: int):
    """Byte-level windows over the repo's Markdown docs — real English
    text available with zero network egress. Returns (train, val) int32
    arrays of (n, seq) token rows (next-token targets are the shifted
    row, as for the synthetic stream).

    The 90/10 split is on CONTIGUOUS BYTES, before any windowing: train
    windows overlap (stride seq/2) for more rows, val windows are
    disjoint (stride seq) and share no bytes with any train window — so
    val_loss is genuinely held out, not memorizable from overlapping
    neighbors."""
    import glob

    import numpy as np

    paths = sorted(
        glob.glob(os.path.join(_REPO, "*.md"))
        + glob.glob(os.path.join(_REPO, "docs", "*.md"))
    )
    blob = b"\n\n".join(open(p, "rb").read() for p in paths)
    tokens = np.frombuffer(blob, np.uint8).astype(np.int32)

    def windows(t, stride):
        n = (len(t) - seq - 1) // stride
        if n <= 0:
            raise SystemExit(
                f"gpt2_train.py: text corpus too small ({len(tokens)} "
                f"bytes across {len(paths)} .md files) for seq {seq} — "
                "run from a repo checkout or shrink --seq"
            )
        return np.stack([t[i * stride : i * stride + seq] for i in range(n)])

    cut = int(0.9 * len(tokens))
    train = windows(tokens[:cut], seq // 2)
    val = windows(tokens[cut:], seq)
    rng = np.random.default_rng(0)
    return train[rng.permutation(len(train))], val


def main():
    args = parse_args()
    picked = [
        f for f, on in (("--powersgd-rank", args.powersgd_rank),
                        ("--topk-ratio", args.topk_ratio),
                        ("--error-feedback", args.error_feedback))
        if on
    ]
    if len(picked) > 1:
        raise SystemExit(
            f"gpt2_train.py: error: {' and '.join(picked)} are mutually "
            "exclusive (each compressor carries its own error feedback)"
        )
    if args.cpu:
        # Force, don't setdefault: append to whatever XLA_FLAGS exists.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.models import GPT2, GPT2Config, lm_loss
    from torch_cgx_tpu.models.gpt2 import sp_lm_loss, tp_param_spec
    from torch_cgx_tpu.parallel import make_train_step, replicate, shard_batch
    from torch_cgx_tpu.parallel.ring_attention import make_sp_attention
    from torch_cgx_tpu.utils.tree import path_str

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = str(args.bits)
    os.environ[cgx_config.COMPRESSION_BUCKET_SIZE] = str(args.bucket_size)
    if args.stochastic:
        os.environ[cgx_config.STOCHASTIC_ROUNDING] = "1"

    devices = jax.devices()
    n = len(devices)
    dp = args.dp or max(1, n // (args.tp * args.sp))
    want = dp * args.tp * args.sp
    if want > n:
        raise SystemExit(f"need {want} devices (dp*tp*sp), have {n}")
    assert dp % args.cross == 0, "--cross must divide dp"
    intra = dp // args.cross

    axis_names = ("cross", "dp", "tp", "sp")
    mesh = Mesh(
        np.asarray(devices[:want]).reshape(args.cross, intra, args.tp, args.sp),
        axis_names,
    )
    dp_axes = ("cross", "dp") if args.cross > 1 else ("dp",)

    if args.sp > 1 and args.cross > 1:
        raise SystemExit("--sp composes with flat --dp only (not --cross)")
    if args.data == "text":
        args.vocab = 256  # byte-level LM
    attn = make_sp_attention("sp", impl="ring") if args.sp > 1 else None
    cfg = GPT2Config.tiny(
        vocab_size=args.vocab,
        n_layer=args.layers,
        n_head=args.heads,
        d_model=args.d_model,
        max_seq=args.seq,
    )
    model = GPT2(cfg, attn_fn=attn) if attn else GPT2(cfg)
    init_model = GPT2(cfg)  # init outside shard_map: plain attention

    val_data = None
    if args.data == "text":
        data, val_data = load_text_corpus(args.seq)
        if len(data) <= args.batch:
            raise SystemExit(
                f"text corpus too small: {len(data)} rows for batch "
                f"{args.batch} at seq {args.seq}"
            )
    else:
        # Synthetic learnable stream: shifted token patterns.
        # Size the synthetic corpus off the batch so any --batch works: the
        # window below needs len(data) > batch, and len(data) - batch must
        # not divide batch or the rotation collapses to one repeated window
        # (2048 and 2049 are coprime, so one of them never divides batch).
        window = 2048 if args.batch % 2048 else 2049
        n_rows = args.batch + window
        data = (np.arange(args.seq)[None, :] + np.arange(n_rows)[:, None]) % args.vocab
        data = data.astype(np.int32)

    tokens0 = jnp.asarray(data[: max(2, args.batch)])
    params = init_model.init(jax.random.PRNGKey(0), tokens0)["params"]

    if args.tp > 1:
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        specs = jax.tree_util.tree_unflatten(
            treedef, [tp_param_spec(path_str(p), l) for p, l in flat]
        )
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params,
            specs,
        )
    else:
        params = replicate(params, mesh)

    opt = optax.adamw(args.lr)
    opt_state = (
        opt.init(params) if args.tp > 1 else replicate(opt.init(params), mesh)
    )

    if args.sp > 1:

        def loss_fn(p, batch):
            # global positions for the local sequence shard
            s_local = batch.shape[1]
            pos = jax.lax.axis_index("sp") * s_local + jnp.arange(s_local)
            logits = model.apply({"params": p}, batch, positions=pos)
            return sp_lm_loss(logits, batch, "sp")

    else:

        def loss_fn(p, batch):
            return lm_loss(model.apply({"params": p}, batch), batch)

    sp_axis = "sp" if args.sp > 1 else None
    step = make_train_step(
        loss_fn,
        opt,
        mesh,
        axes=dp_axes,
        sp_axis=sp_axis,
        stochastic_seed=cgx_config.global_seed() if args.stochastic else None,
        donate=False,
        error_feedback=args.error_feedback,
        powersgd_rank=args.powersgd_rank or None,
        topk_ratio=args.topk_ratio or None,
    )
    state = None
    if args.powersgd_rank:
        from torch_cgx_tpu.parallel import init_powersgd_state

        state = init_powersgd_state(
            params, mesh, rank=args.powersgd_rank, axes=dp_axes,
            sp_axis=sp_axis,
        )
    elif args.topk_ratio:
        from torch_cgx_tpu.parallel import init_topk_state

        state = init_topk_state(
            params, mesh, args.topk_ratio, axes=dp_axes, sp_axis=sp_axis,
        )
    elif args.error_feedback:
        from torch_cgx_tpu.parallel import init_error_feedback

        state = init_error_feedback(
            params, mesh, axes=dp_axes, sp_axis=sp_axis,
        )

    if args.adaptive_bits:
        if args.sp > 1:
            raise SystemExit("--adaptive-bits composes with sp=1 only "
                             "(the measurement grad runs outside shard_map)")
        if args.powersgd_rank or args.topk_ratio:
            raise SystemExit("--adaptive-bits has no effect under "
                             "--powersgd-rank / --topk-ratio (those "
                             "reducers do not consult the quantization "
                             "registry)")
        from torch_cgx_tpu.parallel.adaptive import adapt_bits

        grad_for_stats = jax.jit(jax.grad(loss_fn))

    # Checkpoint/resume (torch_cgx_tpu.checkpoint): restore picks up the
    # training pytree AND the per-layer compression registry — a resumed
    # run compresses from its first step (the restart gap the reference
    # leaves open, SURVEY.md §5.4).
    start_step = 0
    if args.checkpoint_dir:
        if args.tp > 1:
            raise SystemExit("--checkpoint-dir in this example composes "
                             "with tp=1 only (restore re-replicates; tp "
                             "resharding is left to the checkpoint API)")
        if args.error_feedback or args.powersgd_rank or args.topk_ratio:
            raise SystemExit(
                "--checkpoint-dir in this example does not checkpoint the "
                "error-feedback residuals / PowerSGD factors / top-k "
                "residuals; resuming would silently reset that state "
                "(checkpoint the `state` pytree alongside params via "
                "torch_cgx_tpu.checkpoint in real training loops)")
        from torch_cgx_tpu import checkpoint as ckpt

        last = ckpt.latest_step(args.checkpoint_dir)
        if last is not None:
            tree = ckpt.restore(
                args.checkpoint_dir, last,
                target={"params": jax.device_get(params),
                        "opt_state": jax.device_get(opt_state)},
            )
            params = replicate(tree["params"], mesh)
            opt_state = replicate(tree["opt_state"], mesh)
            start_step = last

    losses = []
    bit_allocs = 0
    import time as _time

    t0 = steady0 = _time.time()
    for i in range(start_step, start_step + args.steps):
        lo = (i * args.batch) % (len(data) - args.batch)
        raw = jnp.asarray(data[lo : lo + args.batch])
        if args.adaptive_bits and i % args.adapt_every == 0:
            # One extra backward every --adapt-every steps; the registry
            # version bump retraces the train step with the new per-layer
            # bits (adaptive.py:adapt_bits docstring).
            g = jax.device_get(grad_for_stats(params, raw))
            adapt_bits(g, avg_bits=args.adaptive_bits,
                       bucket_size=args.bucket_size)
            bit_allocs += 1
        batch = shard_batch(raw, mesh, dp_axes, sp_axis=sp_axis)
        if state is not None:
            params, opt_state, state, loss = step(
                params, opt_state, state, batch, jnp.int32(i)
            )
        else:
            params, opt_state, loss = step(params, opt_state, batch, jnp.int32(i))
        losses.append(float(loss))
        if i == start_step:
            steady0 = _time.time()  # exclude the compile from the step rate
        done = i - start_step + 1
        if done % max(1, args.steps // 5) == 0:
            print(f"step {i + 1} ({done}/{args.steps} this run): "
                  f"loss={losses[-1]:.4f}")

    summary = {
        "example": "gpt2_train",
        "mesh": {a: int(mesh.shape[a]) for a in axis_names},
        "data": args.data,
        "bits": args.adaptive_bits or args.bits,
        # Each re-allocation bumps the registry version and retraces the
        # step INSIDE the steady timing window — steps_per_s under
        # adaptive bits includes that recompile cost.
        **({"bit_reallocs": bit_allocs} if args.adaptive_bits else {}),
        **({"powersgd_rank": args.powersgd_rank} if args.powersgd_rank else {}),
        **({"topk_ratio": args.topk_ratio} if args.topk_ratio else {}),
        "first_loss": losses[0],
        "final_loss": losses[-1],
        "compile_s": round(steady0 - t0, 2),
        **({"resumed_from": start_step} if start_step else {}),
    }
    if args.steps > 1:  # steady window needs at least one post-compile step
        summary["steps_per_s"] = round(
            (args.steps - 1) / max(_time.time() - steady0, 1e-9), 3
        )
    if args.checkpoint_dir:
        end = start_step + args.steps
        ckpt.save(args.checkpoint_dir,
                  {"params": params, "opt_state": opt_state}, end)
        summary["saved_step"] = end
    if val_data is not None and args.sp == 1:
        # Held-out loss on real text: one fixed-shape plain jit (loss_fn
        # has no collectives outside sp mode; sharded/replicated params
        # are ordinary jit inputs). sp mode skips val (its loss_fn uses
        # axis_index and must run inside shard_map).
        rows = val_data
        if len(rows) < args.batch:  # tiny corpora: tile up to one batch
            reps = -(-args.batch // len(rows))
            rows = np.concatenate([rows] * reps)
        n_batches = max(1, min(4, len(rows) // args.batch))
        eval_loss = jax.jit(loss_fn)
        vals = [
            float(
                eval_loss(
                    params,
                    jnp.asarray(rows[b * args.batch : (b + 1) * args.batch]),
                )
            )
            for b in range(n_batches)
        ]
        summary["val_loss"] = round(sum(vals) / len(vals), 4)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
