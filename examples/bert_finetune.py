"""BERT MLM fine-tuning with 8-bit compressed data parallelism —
BASELINE.md's "BERT-base fine-tune DDP, 8-bit, layer_min_size filter on
LN/bias" config row as a runnable script (the reference ships only a CIFAR
DDP example, /root/reference/examples/cifar_train.py).

The LN/bias filter is the same two-part gate the reference's DDP hook
applies (cgx_utils/allreduce_hooks.py:42-45): tensors of dim <= 1 stay
uncompressed, and anything smaller than ``CGX_COMPRESSION_MINIMAL_SIZE``
(--min-size) bypasses compression entirely (compressor.cc:421-425). The
summary reports how many parameter leaves each rule left raw, so the
filter's effect is observable, not implied.

    python examples/bert_finetune.py --cpu --steps 10          # smoke
    python examples/bert_finetune.py --layers 12 --d-model 768 \
        --heads 12 --seq 512 --steps 100                        # base-ish
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args():
    p = argparse.ArgumentParser(description="BERT compressed-DP MLM fine-tune")
    p.add_argument("--bits", type=int, default=8)
    p.add_argument("--bucket-size", type=int, default=512)
    p.add_argument("--min-size", type=int, default=16,
                   help="CGX_COMPRESSION_MINIMAL_SIZE: leaves smaller than "
                        "this stay uncompressed")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--mask-every", type=int, default=4,
                   help="mask every Nth position for the MLM objective")
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--cpu", action="store_true",
                   help="force the 8-device virtual CPU mesh")
    return p.parse_args()


def main():
    args = parse_args()
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.models import Bert, BertConfig, mlm_loss
    from torch_cgx_tpu.parallel import (
        flat_mesh,
        make_train_step,
        replicate,
        shard_batch,
    )

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = str(args.bits)
    os.environ[cgx_config.COMPRESSION_BUCKET_SIZE] = str(args.bucket_size)
    os.environ[cgx_config.COMPRESSION_MINIMAL_SIZE] = str(args.min_size)

    cfg = BertConfig.tiny(
        vocab_size=args.vocab,
        n_layer=args.layers,
        n_head=args.heads,
        d_model=args.d_model,
        max_seq=args.seq,
    )
    model = Bert(cfg)

    # Learnable synthetic MLM stream (hermetic): periodic token rows;
    # every --mask-every'th position is replaced by the [MASK] id and must
    # be reconstructed.
    rows = args.batch * 4
    tokens = (
        (np.arange(args.seq)[None, :] + np.arange(rows)[:, None])
        % min(args.vocab, 50)
    ).astype(np.int32)
    mask = np.zeros_like(tokens)
    mask[:, :: args.mask_every] = 1
    inputs = np.where(mask == 1, 3, tokens).astype(np.int32)  # 3 = [MASK]

    mesh = flat_mesh()
    n_dev = int(np.prod(list(mesh.shape.values())))
    if args.batch % n_dev:
        raise SystemExit(f"--batch {args.batch} must divide over {n_dev} devices")
    params = replicate(
        model.init(jax.random.PRNGKey(0), jnp.asarray(inputs[:2]))["params"],
        mesh,
    )
    opt = optax.adamw(args.lr)
    opt_state = replicate(opt.init(params), mesh)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        return mlm_loss(logits, batch["y"], batch["m"])

    step = make_train_step(loss_fn, opt, mesh, donate=False)

    # Observable filter effect: which leaves does the LN/bias + min-size
    # gate leave raw? Counted with the SAME gate the runtime applies
    # (parallel/allreduce.py:is_compressible), so the summary reflects
    # actual wire behavior, not a parallel reimplementation.
    from torch_cgx_tpu.parallel.allreduce import is_compressible

    leaves = jax.tree.leaves(params)
    compressed = sum(1 for l in leaves if is_compressible(l))
    raw_dim = sum(
        1 for l in leaves if not is_compressible(l)
        and is_compressible(l, compress_small=True)
    )  # rejected by the dim<=1 rule alone
    raw_small = len(leaves) - compressed - raw_dim  # size/dtype floor

    import time as _time

    losses = []
    t0 = steady0 = _time.time()
    for i in range(args.steps):
        lo = (i * args.batch) % (rows - args.batch)
        batch = {
            "x": jnp.asarray(inputs[lo : lo + args.batch]),
            "y": jnp.asarray(tokens[lo : lo + args.batch]),
            "m": jnp.asarray(mask[lo : lo + args.batch].astype(np.float32)),
        }
        params, opt_state, loss = step(
            params, opt_state, shard_batch(batch, mesh), jnp.int32(i)
        )
        losses.append(float(loss))
        if i == 0:
            steady0 = _time.time()  # exclude compile from the step rate
        if (i + 1) % max(1, args.steps // 5) == 0:
            print(f"step {i + 1}/{args.steps}: mlm_loss={losses[-1]:.4f}")

    summary = {
        "example": "bert_finetune",
        "devices": n_dev,
        "bits": args.bits,
        "min_size": args.min_size,
        "leaves_compressed": compressed,
        "leaves_raw_dim_filter": raw_dim,
        "leaves_raw_min_size": raw_small,
        "first_loss": losses[0],
        "final_loss": losses[-1],
        "compile_s": round(steady0 - t0, 2),
    }
    if args.steps > 1:
        summary["steps_per_s"] = round(
            (args.steps - 1) / max(_time.time() - steady0, 1e-9), 3
        )
    print(json.dumps(summary))
    return 0 if losses[-1] < losses[0] else 1


if __name__ == "__main__":
    sys.exit(main())
