#!/usr/bin/env bash
# Counterpart of the reference's examples/run_cifar.sh (mpirun -np N ...):
# on TPU the launch is a single SPMD process over the device mesh.
# 4-bit gradients, bucket 1024, ResNet-18 — the BASELINE.md north-star run.
set -e
cd "$(dirname "$0")/.."
python examples/cifar_train.py \
  --epochs 10 \
  --batch-size 512 \
  --quantization-bits "${CGX_BITS:-4}" \
  --quantization-bucket-size 1024 \
  "$@"
