#!/usr/bin/env bash
# Counterpart of the reference's examples/run_cifar.sh (mpirun -np N ...):
# on TPU the launch is a single SPMD process over the device mesh.
# 4-bit gradients, bucket 1024, ResNet-18 — the BASELINE.md north-star run.
#
# Real data: pass --data-dir DIR with a cifar10.npz, or use the bundled
# real handwritten-digit scans (no download): --dataset digits.
# The fp32-vs-quantized A/B (step rate + held-out top-1) is one command:
#   bash tools/pod_ab.sh              # CIFAR_DATA=... for the real npz
set -e
cd "$(dirname "$0")/.."
python examples/cifar_train.py \
  --epochs 10 \
  --batch-size 512 \
  --quantization-bits "${CGX_BITS:-4}" \
  --quantization-bucket-size 1024 \
  "$@"
