"""Shared launcher for the torch-bridge examples: honor a torchrun-style
external launch (RANK / WORLD_SIZE in the env) or self-spawn ``nproc``
ranks rendezvousing over a file store."""

from __future__ import annotations

import os
import tempfile


def run_ranks(train, nproc: int, args, *, prefix: str) -> int:
    """``train(rank, ws, init_method, args)`` per rank; returns exit code."""
    if "RANK" in os.environ and "WORLD_SIZE" in os.environ:
        train(
            int(os.environ["RANK"]),
            int(os.environ["WORLD_SIZE"]),
            "env://",
            args,
        )
        return 0
    import multiprocessing as mp

    initfile = tempfile.mktemp(prefix=prefix)
    ctx = mp.get_context("spawn")
    procs = [
        ctx.Process(target=train, args=(r, nproc, f"file://{initfile}", args))
        for r in range(nproc)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join()
    if os.path.exists(initfile):
        os.unlink(initfile)
    return 0 if all(p.exitcode == 0 for p in procs) else 1
