"""DDP training through the ``"cgx"`` torch.distributed backend — the
counterpart of the reference's mpirun-launched example
(/root/reference/examples/cifar_train.py:61-150: init_process_group('cgx'),
DDP wrap, ``register_comm_hook(CGXState, cgx_hook)``).

The reference bridges OMPI env vars to MASTER_ADDR/RANK; TPU hosts have no
MPI, so this script self-spawns its ranks (or honors torchrun's RANK /
WORLD_SIZE env when present) and rendezvouses over a file store.

Run:
    python examples/torch_ddp_train.py --nproc 2 --quantization-bits 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Allow `python examples/torch_ddp_train.py` from a source checkout.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args():
    p = argparse.ArgumentParser(description="CGX torch-bridge DDP example")
    p.add_argument("--nproc", type=int, default=2)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=64, help="per rank")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--quantization-bits", type=int, default=4)
    p.add_argument("--quantization-bucket-size", type=int, default=1024)
    p.add_argument("--simulate-hosts", type=int, default=1,
                   help="split ranks over N simulated hosts "
                        "(CGX_SHM_HOST_ID override): >1 exercises the "
                        "two-level leader reduction exactly as a real "
                        "multi-host launch would")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def train(rank: int, ws: int, init_method: str, args) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # codec runs on host
    if args.simulate_hosts > 1:
        if "RANK" in os.environ and "WORLD_SIZE" in os.environ:
            # External (torchrun) launch may span REAL machines: a shared
            # simhost id would engage /dev/shm between processes that
            # share no memory. Only the self-spawned single-machine mode
            # may simulate hosts.
            raise SystemExit(
                "--simulate-hosts requires the self-spawned launcher; "
                "under torchrun the real host topology applies"
            )
        # Balanced contiguous split yielding exactly min(hosts, ws)
        # non-empty groups (ceil-division could merge two requested
        # hosts when ws % hosts != 0).
        os.environ["CGX_SHM_HOST_ID"] = (
            f"simhost{rank * args.simulate_hosts // ws}"
        )
    import torch
    import torch.distributed as dist
    import torch.nn as nn

    import torch_cgx_tpu.torch_backend as tb  # registers backend "cgx"

    dist.init_process_group(
        "cgx", init_method=init_method, rank=rank, world_size=ws
    )

    torch.manual_seed(args.seed)
    model = nn.Sequential(
        nn.Flatten(),
        nn.Linear(32 * 32 * 3, 256),
        nn.ReLU(),
        nn.Linear(256, 128),
        nn.ReLU(),
        nn.Linear(128, 10),
    )
    ddp = nn.parallel.DistributedDataParallel(model)
    state = tb.CGXState(
        None,
        compression_params={
            "bits": args.quantization_bits,
            "bucket_size": args.quantization_bucket_size,
        },
    )
    ddp.register_comm_hook(state, tb.cgx_hook)

    opt = torch.optim.SGD(ddp.parameters(), lr=args.lr, momentum=0.9)
    loss_fn = nn.CrossEntropyLoss()

    # Synthetic CIFAR-shaped data with a fixed linear teacher (same trick as
    # examples/cifar_train.py) — rank-local shards.
    g = torch.Generator().manual_seed(args.seed)
    teacher = torch.randn(32 * 32 * 3, 10, generator=g)
    g_local = torch.Generator().manual_seed(args.seed + 1 + rank)

    first = last = None
    for step in range(args.steps):
        x = torch.randn(args.batch_size, 3, 32, 32, generator=g_local)
        y = (x.reshape(args.batch_size, -1) @ teacher).argmax(dim=1)
        opt.zero_grad()
        loss = loss_fn(ddp(x), y)
        loss.backward()
        opt.step()
        if first is None:
            first = loss.item()
        last = loss.item()
        if rank == 0 and (step + 1) % 10 == 0:
            print(f"step {step + 1}/{args.steps}: loss={last:.4f}", flush=True)

    if rank == 0:
        pg = dist.distributed_c10d._get_default_group()
        print(json.dumps({
            "example": "torch_ddp_train",
            "world_size": ws,
            "bits": args.quantization_bits,
            "hosts": len(set(getattr(pg, "_host_by_rank", []) or ["one"])),
            "first_loss": first,
            "final_loss": last,
        }), flush=True)
    dist.barrier()
    dist.destroy_process_group()
    if last >= first:
        raise SystemExit("loss did not decrease")


def main():
    from _launch import run_ranks

    args = parse_args()
    return run_ranks(train, args.nproc, args, prefix="cgx_ddp_example_")


if __name__ == "__main__":
    sys.exit(main())
