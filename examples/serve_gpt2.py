"""GPT-2 serving with a paged quantized KV cache — the serving-plane
composition example (ISSUE 15; docs/SERVING.md).

Disaggregated prefill/decode with continuous batching:

* a prefill worker thread computes each request's KV, cuts it into
  fixed-size pages, quantizes them under the ``kv_page`` wire edge
  (``CGX_KV_BITS`` / ``--bits``) and ships them over publish-after-write
  counter streams;
* the decode scheduler polls those streams without ever blocking,
  admits requests into a fixed lane batch as their pages land, gathers
  each lane's pages with the dequantize fused into the attention read,
  and greedy-decodes one token per lane per step;
* the optional SLO controller (``--ttft-slo-ms`` / ``--tps-slo``)
  re-solves the KV bit budget from the live metric stream.

Runs hermetically on CPU (synthetic prompts, randomly initialized tiny
GPT-2), and on a real chip with the same flags. Per-request outputs plus
tokens/s and TTFT print at the end — the same numbers ``bench.py
--serve`` commits as gated trajectories.

    python examples/serve_gpt2.py --requests 6 --gen 16 --bits 8
    python examples/serve_gpt2.py --local            # no transport hop
    python examples/serve_gpt2.py --kill-prefill 2   # failover demo
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args():
    p = argparse.ArgumentParser(
        description="GPT-2 continuous-batching serving with quantized "
                    "paged KV"
    )
    p.add_argument("--requests", type=int, default=6)
    p.add_argument("--prompt", type=int, default=48,
                   help="synthetic prompt length (tokens)")
    p.add_argument("--gen", type=int, default=16,
                   help="tokens to generate per request")
    p.add_argument("--batch", type=int, default=4, help="decode lanes")
    p.add_argument("--page-tokens", type=int, default=16)
    p.add_argument("--bits", type=int, default=None,
                   help="KV page width (default: CGX_KV_BITS; 0 = raw "
                        "f16 shipping)")
    p.add_argument("--local", action="store_true",
                   help="colocated mode: no transport hop, the "
                        "scheduler prefills in-process")
    p.add_argument("--kill-prefill", type=int, default=None,
                   metavar="N",
                   help="kill the prefill worker after N requests — "
                        "the remaining streams stall and decode fails "
                        "over to local prefill (the recovery demo)")
    p.add_argument("--throttle-mbps", type=float, default=0.0,
                   help="model a bandwidth-bound prefill→decode wire "
                        "(0 = unthrottled)")
    p.add_argument("--ttft-slo-ms", type=float, default=0.0,
                   help="engage the SLO controller at this TTFT target")
    p.add_argument("--tps-slo", type=float, default=0.0,
                   help="engage the SLO controller at this tokens/s "
                        "target")
    p.add_argument("--model", choices=("tiny", "small"), default="tiny")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU platform (CI/laptop runs)")
    p.add_argument("--json", action="store_true",
                   help="print one JSON summary line (harness mode)")
    return p.parse_args()


class DictStore:
    """In-process c10d-Store look-alike for the single-host demo (a real
    deployment passes the group's TCP/File store here)."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def set(self, k, v):
        with self._lock:
            self._d[k] = bytes(v)

    def get(self, k):
        with self._lock:
            if k not in self._d:
                raise KeyError(k)
            return self._d[k]

    def add(self, k, v):
        with self._lock:
            cur = int(self._d.get(k, b"0")) + int(v)
            self._d[k] = str(cur).encode()
            return cur

    def delete_key(self, k):
        with self._lock:
            self._d.pop(k, None)


def main():
    args = parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    if args.bits is not None:
        os.environ["CGX_KV_BITS"] = str(args.bits)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_cgx_tpu.models.gpt2 import GPT2, GPT2Config
    from torch_cgx_tpu.serving import (
        ContinuousBatchScheduler, GPT2Server, KvPageReceiver, Request,
        ServeConfig, ServeSloController,
    )
    from torch_cgx_tpu.serving.prefill import PrefillWorker
    from torch_cgx_tpu.utils.logging import metrics

    cfg = (
        GPT2Config.tiny() if args.model == "tiny" else GPT2Config.small()
    )
    model = GPT2(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32), train=False
    )
    max_seq = args.prompt + args.gen + args.page_tokens
    serve_cfg = ServeConfig(
        page_tokens=args.page_tokens,
        max_batch=args.batch,
        max_pages=max(
            64, args.requests * (max_seq // args.page_tokens + 1)
        ),
        max_seq=max_seq,
        ship_depth=4,
    )
    server = GPT2Server(cfg, params, serve_cfg)

    rng = np.random.default_rng(0)
    requests = [
        Request(
            id=f"req{i}",
            tokens=[int(t) for t in
                    rng.integers(0, cfg.vocab_size, args.prompt)],
            max_new_tokens=args.gen,
        )
        for i in range(args.requests)
    ]

    store = DictStore()
    receiver = None if args.local else KvPageReceiver(store)
    sched = ContinuousBatchScheduler(server, receiver=receiver)
    slo = ServeSloController(
        ttft_slo_ms=args.ttft_slo_ms or None,
        tps_slo=args.tps_slo or None,
        every=20,
    )

    worker_thread = None
    worker = None
    t0 = time.perf_counter()
    if args.local:
        for r in requests:
            sched.submit(r)
    else:
        worker = PrefillWorker(
            server, store,
            throttle_gbps=(args.throttle_mbps / 1e3
                           if args.throttle_mbps else None),
        )
        for r in requests:
            sched.submit(r, remote=True)

        def run_prefill():
            for i, r in enumerate(requests):
                if (args.kill_prefill is not None
                        and i >= args.kill_prefill):
                    print(
                        f"[prefill] worker dying after {i} request(s) — "
                        "watch decode fail over, not wedge",
                        file=sys.stderr,
                    )
                    return  # simulated mid-stream death
                worker.serve(r.id, r.tokens)

        worker_thread = threading.Thread(target=run_prefill, daemon=True)
        worker_thread.start()

    deadline = time.monotonic() + 600.0
    while sched.outstanding() and time.monotonic() < deadline:
        if not sched.step():
            time.sleep(0.002)
        slo.step()
    wall = time.perf_counter() - t0
    if worker_thread is not None:
        worker_thread.join(timeout=30)
    if worker is not None:
        worker.stop()
    if sched.outstanding():
        print("ERROR: serving run left requests outstanding",
              file=sys.stderr)
        return 1

    tokens = sum(len(r.output) for r in requests)
    ttft = metrics.histogram_stats("cgx.serve.ttft_ms") or {}
    summary = {
        "requests": len(requests),
        "tokens": tokens,
        "tokens_per_s": round(tokens / wall, 3),
        "ttft_p50_ms": round(ttft.get("p50", 0.0), 3),
        "ttft_p90_ms": round(ttft.get("p90", 0.0), 3),
        "kv_bits": int(os.environ.get("CGX_KV_BITS", "8") or 0),
        "prefill_failovers": int(
            metrics.get("cgx.serve.prefill_failovers")
        ),
        "pages_allocated": int(metrics.get("cgx.serve.pages_allocated")),
        "kv_bytes_wire": metrics.get("cgx.serve.kv_bytes_wire"),
        "slo_bits_budget": (
            slo.budget if slo.engaged else None
        ),
    }
    if args.json:
        print(json.dumps(summary))
        return 0
    for r in requests:
        head = " ".join(str(t) for t in r.output[:8])
        print(f"{r.id}: {len(r.output)} tokens [{head}"
              + (" ...]" if len(r.output) > 8 else "]"))
    print(json.dumps(summary, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
