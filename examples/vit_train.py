"""ViT training with hierarchical compressed data parallelism —
BASELINE.md's "ViT-L/16 multi-host DDP, INTRA_BROADCAST hierarchical
allreduce" config row as a runnable script (the reference ships only a
CIFAR DDP example, /root/reference/examples/cifar_train.py; its two-level
scheme lives in mpi_allreduce_operations.cc:139-185).

The mesh is cross x intra (DCN x ICI on a real pod): gradients reduce
inside each "host" first, leaders exchange across, and the result
broadcasts back — the INTRA_BROADCAST leader scheme, quantized at every
hop per the per-config gates (CGX_INTRA_COMPRESS, config.py).

    python examples/vit_train.py --cpu --steps 10            # smoke
    python examples/vit_train.py --vit-large --intra 4       # pod slice
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args():
    p = argparse.ArgumentParser(description="ViT hierarchical compressed-DP")
    p.add_argument("--bits", type=int, default=4)
    p.add_argument("--bucket-size", type=int, default=512)
    p.add_argument("--intra", type=int, default=4,
                   help="devices per 'host' (the intra axis; cross = total/intra)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--patch-size", type=int, default=8)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vit-large", action="store_true",
                   help="ViT-L dims (d_model 1024 x 24 layers x 16 heads)")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--cpu", action="store_true",
                   help="force the 8-device virtual CPU mesh")
    return p.parse_args()


def main():
    args = parse_args()
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torch_cgx_tpu import config as cgx_config
    from torch_cgx_tpu.models import ViT, ViTConfig
    from torch_cgx_tpu.parallel import (
        make_train_step,
        mesh as mesh_mod,
        replicate,
        shard_batch,
    )

    os.environ[cgx_config.COMPRESSION_QUANTIZATION_BITS] = str(args.bits)
    os.environ[cgx_config.COMPRESSION_BUCKET_SIZE] = str(args.bucket_size)

    if args.vit_large:
        cfg = ViTConfig.large(
            image_size=args.image_size,
            patch_size=args.patch_size,
            num_classes=args.classes,
        )
    else:
        cfg = ViTConfig.tiny(
            image_size=args.image_size,
            patch_size=args.patch_size,
            num_classes=args.classes,
            d_model=args.d_model,
            n_layer=args.layers,
            n_head=args.heads,
        )
    model = ViT(cfg)

    mesh = mesh_mod.hierarchical_mesh(intra_size=args.intra)
    axes = (mesh_mod.CROSS_AXIS, mesh_mod.INTRA_AXIS)
    n_dev = int(mesh.shape[axes[0]] * mesh.shape[axes[1]])
    if args.batch % n_dev:
        raise SystemExit(f"--batch {args.batch} must divide over {n_dev} devices")

    # Learnable synthetic image stream: class-conditional means + noise.
    rng = np.random.default_rng(0)
    rows = args.batch * 4
    labels = (np.arange(rows) % args.classes).astype(np.int32)
    means = rng.normal(size=(args.classes, 1, 1, 3)).astype(np.float32)
    images = (
        means[labels]
        + 0.3 * rng.normal(size=(rows, args.image_size, args.image_size, 3))
    ).astype(np.float32)

    params = replicate(
        model.init(jax.random.PRNGKey(0), jnp.asarray(images[:2]))["params"],
        mesh,
    )
    opt = optax.adamw(args.lr)
    opt_state = replicate(opt.init(params), mesh)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        onehot = jax.nn.one_hot(batch["y"], args.classes)
        return optax.softmax_cross_entropy(logits, onehot).mean()

    step = make_train_step(loss_fn, opt, mesh, axes=axes, donate=False)

    import time as _time

    losses = []
    t0 = steady0 = _time.time()
    for i in range(args.steps):
        lo = (i * args.batch) % (rows - args.batch)
        batch = {
            "x": jnp.asarray(images[lo : lo + args.batch]),
            "y": jnp.asarray(labels[lo : lo + args.batch]),
        }
        params, opt_state, loss = step(
            params, opt_state, shard_batch(batch, mesh, axes), jnp.int32(i)
        )
        losses.append(float(loss))
        if i == 0:
            steady0 = _time.time()  # exclude compile from the step rate
        if (i + 1) % max(1, args.steps // 5) == 0:
            print(f"step {i + 1}/{args.steps}: loss={losses[-1]:.4f}")

    summary = {
        "example": "vit_train",
        "mesh": {a: int(mesh.shape[a]) for a in axes},
        "bits": args.bits,
        "first_loss": losses[0],
        "final_loss": losses[-1],
        "compile_s": round(steady0 - t0, 2),
    }
    if args.steps > 1:
        summary["steps_per_s"] = round(
            (args.steps - 1) / max(_time.time() - steady0, 1e-9), 3
        )
    print(json.dumps(summary))
    return 0 if losses[-1] < losses[0] else 1


if __name__ == "__main__":
    sys.exit(main())
