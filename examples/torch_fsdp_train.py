"""Fully-sharded (ZeRO-3 style) training through the ``"cgx"``
torch.distributed backend — the workflow the reference CANNOT run: its
ProcessGroup throws on both ``_allgather_base`` and ``_reduce_scatter_base``
(/root/reference/src/ProcessGroupCGX.cc — it only plumbs group names "for
FSPD"), while this bridge implements ``all_gather_into_tensor`` and a
QUANTIZED ``reduce_scatter_tensor``, i.e. both ZeRO-3 traffic directions.

Each rank owns a 1/ws shard of the flat parameters; every step gathers the
full parameters for compute and reduce-scatters averaged gradient shards —
exactly the two collectives torch's FSDP wrapper is built from (the wrapper
itself refuses CPU-only hosts, so this example runs the equivalent manual
loop; on a GPU/TPU-VM host the same process group drops straight into it).

Wire compression:
  * gradient reduce-scatter rides the quantized SRA scatter-reduce half
    (``CGX_COMPRESSION_QUANTIZATION_BITS`` / --bits);
  * the parameter all-gather optionally compresses too
    (``CGX_FSDP_ALLGATHER_BITS`` / --allgather-bits — every rank decodes
    identical bytes, so replicas stay bit-identical).

Run:
    python examples/torch_fsdp_train.py --nproc 2 --bits 8 --allgather-bits 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args():
    p = argparse.ArgumentParser(description="CGX torch-bridge ZeRO-3 example")
    p.add_argument("--nproc", type=int, default=2)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch-size", type=int, default=16, help="per rank")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--bits", type=int, default=8,
                   help="gradient reduce-scatter quantization bits")
    p.add_argument("--allgather-bits", type=int, default=0,
                   help="CGX_FSDP_ALLGATHER_BITS: compress the parameter "
                        "all-gather too (0 = raw)")
    p.add_argument("--d-in", type=int, default=64)
    p.add_argument("--d-hidden", type=int, default=128)
    p.add_argument("--d-out", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def train(rank: int, ws: int, init_method: str, args) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # codec runs on host
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = str(args.bits)
    if args.allgather_bits:
        os.environ["CGX_FSDP_ALLGATHER_BITS"] = str(args.allgather_bits)
    import torch
    import torch.distributed as dist

    import torch_cgx_tpu.torch_backend  # noqa: F401 — registers "cgx"

    dist.init_process_group(
        "cgx", init_method=init_method, rank=rank, world_size=ws
    )

    # Two-layer MLP as ONE flat parameter vector, sharded 1/ws per rank
    # (ZeRO-3's partitioned state). Same init on every rank, then each
    # keeps only its shard.
    torch.manual_seed(args.seed)
    shapes = [
        (args.d_in, args.d_hidden),
        (args.d_hidden,),
        (args.d_hidden, args.d_out),
        (args.d_out,),
    ]
    flat = torch.cat([
        (torch.randn(s) * (0.5 / s[0] ** 0.5) if len(s) > 1
         else torch.zeros(s)).reshape(-1)  # zero-init biases
        for s in shapes
    ])
    n = flat.numel()
    shard_n = -(-n // ws)
    padded = torch.cat([flat, torch.zeros(shard_n * ws - n)])
    my_shard = padded[rank * shard_n : (rank + 1) * shard_n].clone()

    def unflatten(vec):
        out, off = [], 0
        for s in shapes:
            numel = 1
            for d in s:
                numel *= d
            out.append(vec[off : off + numel].reshape(s))
            off += numel
        return out

    # Same teacher on every rank; rank-local batch shards.
    g = torch.Generator().manual_seed(args.seed + 1)
    teacher = torch.randn(args.d_in, args.d_out, generator=g)
    g_local = torch.Generator().manual_seed(args.seed + 2 + rank)

    first = last = None
    for step in range(args.steps):
        # ZeRO-3 forward gather: materialize full params from shards.
        full = torch.zeros(shard_n * ws)
        dist.all_gather_into_tensor(full, my_shard)
        params = [p.detach().requires_grad_(True) for p in unflatten(full[:n])]
        w1, b1, w2, b2 = params

        x = torch.randn(args.batch_size, args.d_in, generator=g_local)
        y = x @ teacher
        pred = torch.relu(x @ w1 + b1) @ w2 + b2
        loss = ((pred - y) ** 2).mean()
        loss.backward()

        # ZeRO-3 backward: reduce-scatter AVERAGED gradient shards
        # (quantized wire; every rank receives its own shard only).
        gflat = torch.cat([p.grad.reshape(-1) for p in params])
        gpad = torch.cat([gflat, torch.zeros(shard_n * ws - n)])
        gshard = torch.zeros(shard_n)
        dist.reduce_scatter_tensor(gshard, gpad, op=dist.ReduceOp.AVG)
        my_shard = my_shard - args.lr * gshard

        if first is None:
            first = loss.item()
        last = loss.item()
        if rank == 0 and (step + 1) % max(1, args.steps // 5) == 0:
            print(f"step {step + 1}/{args.steps}: loss={last:.4f}", flush=True)

    if rank == 0:
        print(json.dumps({
            "example": "torch_fsdp_train",
            "world_size": ws,
            "bits": args.bits,
            "allgather_bits": args.allgather_bits,
            "params": n,
            "shard_per_rank": shard_n,
            "first_loss": first,
            "final_loss": last,
        }), flush=True)
    dist.barrier()
    dist.destroy_process_group()
    if last >= first:
        raise SystemExit("loss did not decrease")


def main():
    from _launch import run_ranks

    args = parse_args()
    return run_ranks(train, args.nproc, args, prefix="cgx_fsdp_example_")


if __name__ == "__main__":
    sys.exit(main())
