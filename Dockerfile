# Deploy recipe — the reference assembles an NGC PyTorch + OpenMPI + ssh
# image (/root/reference/Dockerfile:1-11). The TPU-native equivalent is far
# thinner: TPU VMs already expose the accelerator to any process with
# libtpu, so the image is just Python + jax[tpu] + this package. Run with
# host networking on each host of a pod slice (the TPU runtime and
# jax.distributed discover peers through the metadata the VM provides).
#
#   docker build -t torch-cgx-tpu .
#   docker run --rm --privileged --net=host torch-cgx-tpu \
#       python examples/cifar_train.py --synthetic --steps 100
#
# See README.md "Deploying on Cloud TPU" for the bare-VM (no Docker)
# bootstrap and the multi-host pod-slice launch.

FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        build-essential git \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY torch_cgx_tpu ./torch_cgx_tpu
COPY examples ./examples
COPY tools ./tools

# jax[tpu] pulls libtpu from the Google releases index; torch stays CPU
# (the bridge stages through DLPack — no CUDA anywhere, unlike the
# reference's NGC base).
RUN pip install --no-cache-dir \
        "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
        flax optax orbax-checkpoint chex einops ml_dtypes numpy \
    && pip install --no-cache-dir torch --index-url https://download.pytorch.org/whl/cpu \
    && pip install --no-cache-dir -e .

ENV JAX_PLATFORMS=tpu
CMD ["python", "-c", "import jax, torch_cgx_tpu; print(jax.devices())"]
