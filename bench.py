"""Benchmark harness — prints ONE JSON line.

Adaptive to available hardware:

* single device (the driver's real-TPU run): fused Pallas codec throughput
  (quantize and dequantize timed separately, plus ``pct_hbm_roofline``
  against the chip's HBM bandwidth) and a north-star proxy — a jitted
  GPT-2 train step with the codec round trip on its gradients vs the plain
  step, bounding the achievable compressed-DP speedup (BASELINE.md).
  ``vs_baseline`` = XLA-codec round-trip time / Pallas round-trip time.
* multi-device: quantized 4-bit SRA allreduce of a 64 MB fp32 gradient
  buffer vs XLA's native fp32 ``psum``; ``vs_baseline`` = fp32-psum time /
  quantized time (>1 = faster than fp32).

Timing methodology: per-dispatch overhead through the device transport is
~4 ms — larger than most ops measured here — so every single-device number
uses a *slope* method: run K operand sets through ``lax.scan`` inside one
jit and report (t_K - t_1)/(K - 1). Round-1/2 numbers used per-call wall
clock and were overhead-dominated (BENCH_r01's 15.9 GB/s is mostly
dispatch latency).

A lint pre-flight (tools/lint.py) aborts the bench if any undefined name is
present — a broken hot path must fail loudly here, not measure garbage
(VERDICT r2 #2).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from torch_cgx_tpu.utils.compat import shard_map

# Persistent compile cache: the GPT-2 proxy's scans are the bulk of bench
# wall time on a cold process; cache them across runs.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

BITS = 4
BUCKET = 512

# HBM bandwidth per chip generation (GB/s) — jax-ml.github.io/scaling-book.
HBM_GBPS = {
    "TPU v3": 900.0,
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}


BENCH_LOG = Path(__file__).resolve().parent / "BENCH_LOG.jsonl"


def log_jsonl(record: dict) -> None:
    """Append a structured perf record to the committed BENCH_LOG.jsonl so
    round-over-round performance is diffable as data, not prose (VERDICT r3
    missing #1 / next #5 — the round-3 transport incident erased a whole
    round's evidence because nothing persisted per-variant results)."""
    rec = dict(record)
    rec.setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%S"))
    # Counter context rides along with every perf row: which paths ran,
    # how many elements traveled compressed vs raw, any faults — the BENCH
    # trajectory is then diffable against the registry, not just wall
    # clock. Never let the snapshot break (or bloat) the record itself.
    try:
        from torch_cgx_tpu.utils.logging import metrics as _metrics

        snap = _metrics.snapshot()
        if snap and "metrics" not in rec:
            rec["metrics"] = snap
    except Exception:
        pass
    # Memory trajectory (ISSUE 18): the ledger's peak-bytes high-water
    # rides on every record when CGX_MEMLEDGER is on, so bench_gate can
    # fail a memory regression exactly like a throughput regression
    # (the <metric>:peak_mb trajectory). None/off = no key, no gate.
    try:
        from torch_cgx_tpu.observability import memledger as _memledger

        pk = _memledger.peak_mb()
        if pk is not None and pk > 0 and "peak_mb" not in rec:
            rec["peak_mb"] = pk
    except Exception:
        pass
    # NOT setdefault: its default argument evaluates eagerly, which would
    # probe jax.devices() even when the caller pre-filled the keys (the
    # watchdog must never touch the backend).
    try:
        if "chip" not in rec:
            rec["chip"] = jax.devices()[0].device_kind
        if "backend" not in rec:
            rec["backend"] = jax.default_backend()
    except Exception:
        pass  # never let logging break (or hang) the measurement itself
    try:
        with open(BENCH_LOG, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def _preflight_lint() -> None:
    proc = subprocess.run(
        [sys.executable, str(Path(__file__).parent / "tools" / "lint.py")],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        print(json.dumps({
            "metric": "lint_failure",
            "value": 0,
            "unit": "findings",
            "vs_baseline": 0,
            "detail": {"findings": proc.stdout.strip().splitlines()[:20]},
        }))
        sys.exit(1)


def _chip() -> tuple[str, float]:
    kind = jax.devices()[0].device_kind
    bw = next((v for k, v in HBM_GBPS.items() if k in kind), 0.0)
    return kind, bw


def scan_time(fn, stack, iters: int = 6) -> float:
    """Marginal per-execution seconds: slope between a K-length and a
    1-length scan over stacked operand sets (dispatch overhead cancels)."""

    def runner(s):
        def body(c, x):
            out = fn(x)
            leaf = jax.tree.leaves(out)[0]
            return c + leaf.ravel()[0].astype(jnp.float32), 0

        return lax.scan(body, jnp.float32(0), s)[0]

    jr = jax.jit(runner)

    def timed(s):
        np.asarray(jr(s))  # warm + sync
        t0 = time.perf_counter()
        for _ in range(iters):
            o = jr(s)
        np.asarray(o)
        return (time.perf_counter() - t0) / iters

    k = jax.tree.leaves(stack)[0].shape[0]
    t_k = timed(stack)
    t_1 = timed(jax.tree.map(lambda a: a[:1], stack))
    return max((t_k - t_1) / (k - 1), 1e-9)


def bench_codec(on_tpu: bool) -> dict:
    from torch_cgx_tpu.ops import codec, codec_pallas

    # 512 MB on real hardware so the op dwarfs noise; small in interpret
    # mode (CPU fallback) where the Pallas path runs in pure Python.
    n = 128 * 1024 * 1024 if on_tpu else 1024 * 1024
    k = 4 if on_tpu else 2
    # Generate operands on-device: shipping 2 GB of host-generated data
    # through the device transport is slow and has wedged the tunnel under
    # load; a device-side PRNG draw moves no bytes.
    stack = jax.jit(
        lambda key: jax.random.normal(key, (k, 1, n), jnp.float32)
    )(jax.random.PRNGKey(1))
    stack.block_until_ready()

    def q_pallas(x):
        q = codec_pallas.quantize_batch(
            x, BITS, BUCKET, stochastic=False, interpret=not on_tpu
        )
        return (q.packed, q.meta)

    def q_xla(x):
        q = jax.vmap(lambda r: codec.quantize(r, BITS, BUCKET))(x)
        return (q.packed, q.meta)

    # genuinely distinct payloads per scan slot
    qts = [
        codec_pallas.quantize_batch(
            stack[i], BITS, BUCKET, interpret=not on_tpu
        )
        for i in range(k)
    ]
    q_stack = jax.tree.map(
        lambda *xs: jnp.stack(xs) if isinstance(xs[0], jax.Array) else xs[0],
        *qts,
    )

    def d_pallas(q):
        return codec_pallas.dequantize_batch(
            q, out_dtype=jnp.float32, interpret=not on_tpu
        )

    def d_xla(q):
        return jax.vmap(
            lambda qq: codec.dequantize(qq, out_dtype=jnp.float32)
        )(q)

    tpq = scan_time(q_pallas, stack)
    tpd = scan_time(d_pallas, q_stack)
    txq = scan_time(q_xla, stack)
    txd = scan_time(d_xla, q_stack)

    gbytes = n * 4 / 1e9
    nb = n // BUCKET
    # Actual HBM traffic: quantize reads 4n, writes n*bits/8 payload +
    # 8*nb meta; dequantize is the mirror image.
    moved = (n * 4 + n * BITS / 8 + nb * 8) / 1e9
    chip, hbm = _chip()
    tp, tx = tpq + tpd, txq + txd

    def pct(t):
        return round(moved / t / hbm * 100, 1) if hbm else None

    return {
        "metric": f"pallas_codec_{BITS}bit_{n * 4 // 2**20}MB_roundtrip",
        "value": round(gbytes / tp, 3),
        "unit": "GB/s",
        "vs_baseline": round(tx / tp, 3),
        "detail": {
            "quantize_GBps": round(gbytes / tpq, 1),
            "dequantize_GBps": round(gbytes / tpd, 1),
            "quantize_pct_hbm_roofline": pct(tpq),
            "dequantize_pct_hbm_roofline": pct(tpd),
            "t_pallas_quantize_ms": round(tpq * 1e3, 3),
            "t_pallas_dequantize_ms": round(tpd * 1e3, 3),
            "t_xla_quantize_ms": round(txq * 1e3, 3),
            "t_xla_dequantize_ms": round(txd * 1e3, 3),
            "chip": chip,
            "hbm_GBps": hbm,
            "timing": "scan-slope (dispatch overhead cancelled)",
        },
    }


def bench_sra_epilogue(on_tpu: bool, ws: int = 8) -> dict:
    """Staged vs fused SRA epilogue: the dequantize-accumulate-requantize
    of the ws peer payloads a rank runs between the all_to_all and the
    all_gather (the second codec round trip of PERF_NOTES.md's round-5
    analysis). The staged form materializes the decoded (ws, chunk) f32
    rows in HBM and re-reads them through an XLA select/sum and a separate
    quantize kernel; the fused Pallas kernel does all of it in one HBM
    pass. Both produce bit-identical wire bytes (asserted before timing)."""
    from torch_cgx_tpu.ops import codec_pallas, dispatch

    total = 128 * 1024 * 1024 if on_tpu else 256 * 1024
    chunk = total // ws
    k = 4 if on_tpu else 2
    own = jnp.int32(ws // 2)
    stack = jax.jit(
        lambda key: jax.random.normal(key, (k, ws, chunk), jnp.float32)
    )(jax.random.PRNGKey(2))
    stack.block_until_ready()
    qts = [
        codec_pallas.quantize_batch(stack[i], BITS, BUCKET, interpret=not on_tpu)
        for i in range(k)
    ]
    q_stack = jax.tree.map(
        lambda *xs: jnp.stack(xs) if isinstance(xs[0], jax.Array) else xs[0],
        *qts,
    )

    def staged(args):
        q, xs = args
        vals = codec_pallas.dequantize_batch(
            q, out_dtype=jnp.float32, interpret=not on_tpu
        )
        mask = (jnp.arange(ws) == own)[:, None]
        red = dispatch.ordered_rowsum(
            jnp.where(mask, xs.astype(jnp.float32), vals)
        )
        q2 = codec_pallas.quantize_batch(
            red[None], BITS, BUCKET, interpret=not on_tpu
        )
        return (q2.packed, q2.meta)

    def fused(args):
        q, xs = args
        q2 = codec_pallas.sra_epilogue_batch(
            q, raw_row=xs[ws // 2], own_idx=own, interpret=not on_tpu
        )
        return (q2.packed, q2.meta)

    # Wire-identity pre-flight: a fused epilogue that changes bytes must
    # fail loudly here, never be timed (the qbench byte-check discipline).
    ws_s, ms_s = jax.jit(staged)((qts[0], stack[0]))
    ws_f, ms_f = jax.jit(fused)((qts[0], stack[0]))
    assert bool(jnp.array_equal(ws_s, ws_f)) and bool(
        jnp.array_equal(ms_s, ms_f)
    ), "fused SRA epilogue wire bytes diverge from the staged path"

    t_staged = scan_time(staged, (q_stack, stack))
    t_fused = scan_time(fused, (q_stack, stack))
    gbytes = total * 4 / 1e9
    return {
        "metric": (
            f"sra_epilogue_fused_vs_staged_{BITS}bit_"
            f"{total * 4 // 2**20}MB_x{ws}"
        ),
        "value": round(gbytes / t_fused, 3),
        "unit": "GB/s",
        "vs_baseline": round(t_staged / t_fused, 3),
        "detail": {
            "t_staged_ms": round(t_staged * 1e3, 3),
            "t_fused_ms": round(t_fused * 1e3, 3),
            "ws": ws,
            "chunk_elems": chunk,
            "wire_identity": "bit-identical (asserted)",
            "timing": "scan-slope (dispatch overhead cancelled)",
        },
    }


def bench_codec_roofline(
    mb: int = 64, ws: int = 4, bits: int = BITS, iters: int = 5
) -> list:
    """ISSUE 11 records: (a) ``quantize_roofline_frac_*`` — the flat
    quantize kernel's achieved HBM-roofline fraction (vs the chip table
    on TPU, vs a measured same-backend read floor on CPU — the ``@cpu``
    trajectory bench_gate quarantines); (b)
    ``producer_fused_vs_staged_*`` — the fused matmul+quantize producer
    kernel vs the staged matmul-then-quantize pair, wire-byte pre-flighted
    (bit-equal where the two matmuls agree, quantization-envelope
    allclose otherwise — the producer-fuse contract). With
    ``CGX_AUTOTUNE=on`` a short tile sweep runs first and persists the
    winners (ops/autotune.py), so the timed rows measure the tuned
    configs a production run would use."""
    from torch_cgx_tpu import config as cfg_mod
    from torch_cgx_tpu.config import CompressionConfig
    from torch_cgx_tpu.ops import autotune, codec_pallas, dispatch
    from torch_cgx_tpu.ops import fused_producer as fp
    from torch_cgx_tpu.parallel import reducers

    on_tpu = jax.default_backend() == "tpu"
    n = (mb * 2**20 // 4) if on_tpu else 2**20
    n -= n % (ws * 32 * BUCKET)
    mb_eff = n * 4 // 2**20
    chip, hbm = _chip()
    cc = CompressionConfig(bits=bits, bucket_size=BUCKET)

    k = 4 if on_tpu else 2
    stack = jax.jit(
        lambda key: jax.random.normal(key, (k, 1, n), jnp.float32)
    )(jax.random.PRNGKey(3))
    stack.block_until_ready()

    def quantize(x):
        q = codec_pallas.quantize_batch(
            x, bits, BUCKET, interpret=not on_tpu
        )
        return (q.packed, q.meta)

    # --- optional autotune sweep (hardware sessions set CGX_AUTOTUNE=on;
    # CI/auto only consults, never measures) -----------------------------
    tuned = None
    if cfg_mod.autotune_mode() == "on":
        n_chunks = n // (32 * BUCKET)

        def measure(cand):
            os.environ["CGX_PALLAS_TILE_CHUNKS"] = str(cand.tc)
            os.environ["CGX_PALLAS_DB"] = "on" if cand.db else "off"
            try:
                return scan_time(quantize, stack, iters=max(2, iters // 2))
            finally:
                os.environ.pop("CGX_PALLAS_TILE_CHUNKS", None)
                os.environ.pop("CGX_PALLAS_DB", None)

        cands = [
            autotune.TunedConfig(tc=tc, db=db)
            for tc in (4, 8, 16)
            for db in (False, True)
            if autotune.snap_to_divisor(tc, n_chunks, 64) == tc
        ]
        tuned = autotune.tune(
            autotune.KIND_FLAT, cands, measure,
            n_chunks=n_chunks, bucket_size=BUCKET, bits=bits,
            input_bytes=n * 4,
        )

    t_q = scan_time(quantize, stack, iters=iters)
    nb = n // BUCKET
    moved = (n * 4 + n * bits / 8 + nb * 8) / 1e9
    if hbm:
        denom, denom_src = hbm, "chip_table"
    else:
        # Same-backend read floor: a max-reduce over the identical
        # operand — the achievable-memory-bandwidth proxy for @cpu rows.
        t_floor = scan_time(
            lambda x: jnp.max(x), stack, iters=iters
        )
        denom = (n * 4 / 1e9) / t_floor
        denom_src = "measured_read_floor"
    frac = (moved / t_q) / denom if denom else 0.0
    from torch_cgx_tpu.utils.logging import metrics as _metrics

    _metrics.set("cgx.codec.roofline_frac", round(frac, 4))
    roofline_rec = {
        "metric": f"quantize_roofline_frac_{bits}bit_{mb_eff}MB",
        "value": round(frac, 4),
        "unit": "frac",
        "vs_baseline": round(moved / t_q, 2),
        "detail": {
            "quantize_GBps_moved": round(moved / t_q, 2),
            "roofline_GBps": round(denom, 2),
            "roofline_source": denom_src,
            "t_quantize_ms": round(t_q * 1e3, 3),
            "chip": chip,
            "autotuned": None if tuned is None else {
                "tc": tuned.tc, "db": tuned.db, "gbps": tuned.gbps,
            },
            "timing": "scan-slope (dispatch overhead cancelled)",
        },
    }

    # --- producer-fused vs staged quantize-after-grad -------------------
    # Shapes: dw = x2^T @ g2 of exactly the wire-aligned size; CPU keeps
    # the interpret-mode kernel small.
    if on_tpu:
        din, o = 1024, max(128, n // 1024 - (n // 1024) % 128)
        din = n // o
    else:
        din, o = 256, 512
    K = 256 if on_tpu else 64
    n_p = din * o
    chunk = n_p // ws
    rng = jax.random.PRNGKey(7)
    x2 = jax.random.normal(rng, (K, din), jnp.float32)
    g2 = jax.random.normal(jax.random.fold_in(rng, 1), (K, o), jnp.float32)
    geo = fp._kernel_geometry(K, din, o, ws, chunk, cc)
    if geo is None:
        return [roofline_rec]
    tm, tk = geo

    def staged(args):
        x2, g2 = args
        dw = (
            jax.lax.dot_general(x2, g2, (((0,), (0,)), ((), ()))) / ws
        ).reshape(ws, chunk)
        q = reducers._quantize_rows(dw, cc, None)
        return (q.packed, q.meta)

    def fused(args):
        x2, g2 = args
        q = fp._matmul_quantize_q(
            x2, g2, cc, ws=ws, chunk=chunk, div=ws, tm=tm, tk=tk,
            interpret=not on_tpu,
        )
        return (q.packed, q.meta)

    # Pre-flight: byte-equal when the two matmul lowerings agree on this
    # backend; otherwise the decoded payloads must sit inside the
    # quantization envelope (2 * unit per coordinate).
    ps, ms = jax.jit(staged)((x2, g2))
    pf, mf = jax.jit(fused)((x2, g2))
    bit_equal = bool(jnp.array_equal(ps, pf)) and bool(
        jnp.array_equal(ms, mf)
    )
    if not bit_equal:
        qs = reducers._quantize_rows(
            (jax.lax.dot_general(x2, g2, (((0,), (0,)), ((), ()))) / ws
             ).reshape(ws, chunk), cc, None,
        )
        d_s = dispatch.dequantize_batch(qs)
        qf = fp._matmul_quantize_q(
            x2, g2, cc, ws=ws, chunk=chunk, div=ws, tm=tm, tk=tk,
            interpret=not on_tpu,
        )
        d_f = dispatch.dequantize_batch(qf)
        unit = jnp.max(jnp.abs(d_s)) / ((1 << bits) - 1)
        assert bool(jnp.all(jnp.abs(d_s - d_f) <= 2 * unit + 1e-6)), (
            "producer-fused payload outside the quantization envelope"
        )

    k2 = 4 if on_tpu else 2
    xs_stack = (
        jnp.stack([x2 + i for i in range(k2)]),
        jnp.stack([g2 + i for i in range(k2)]),
    )
    t_staged = scan_time(staged, xs_stack, iters=iters)
    t_fused = scan_time(fused, xs_stack, iters=iters)
    producer_rec = {
        "metric": (
            f"producer_fused_vs_staged_{bits}bit_{n_p * 4 // 2**20}MB"
        ),
        "value": round(n_p * 4 / 1e9 / t_fused, 3),
        "unit": "GB/s",
        "vs_baseline": round(t_staged / t_fused, 3),
        "detail": {
            "t_staged_ms": round(t_staged * 1e3, 3),
            "t_fused_ms": round(t_fused * 1e3, 3),
            "din": din, "o": o, "K": K, "ws": ws,
            "tm": tm, "tk": tk,
            "wire_identity": (
                "bit-identical (asserted)" if bit_equal
                else "quantization-envelope (matmul association differs)"
            ),
            # HBM byte accounting (PERF_NOTES "Producer-fused quantize"):
            # staged writes + re-reads the f32 gradient; fused writes
            # only packed+meta.
            "hbm_bytes_staged": int(n_p * 4 * 2 + n_p * bits / 8),
            "hbm_bytes_fused": int(n_p * bits / 8 + (n_p // BUCKET) * 8),
            "timing": "scan-slope (dispatch overhead cancelled)",
        },
    }
    return [roofline_rec, producer_rec]


def bench_train_step(on_tpu: bool) -> dict:
    """North-star proxy on one chip: jitted GPT-2 train step with the codec
    round trip applied to its gradients (the per-rank work of a compressed
    DP sync) vs the plain step. Bounds the achievable multi-chip speedup:
    codec overhead must stay a small fraction of step time for the wire
    savings to win (BASELINE.md north star)."""
    _bench_env = {
        "CGX_DEBUG_FORCE_CODEC": "1",
        "CGX_COMPRESSION_QUANTIZATION_BITS": str(BITS),
        "CGX_COMPRESSION_BUCKET_SIZE": str(BUCKET),
    }
    _saved_env = {k: os.environ.get(k) for k in _bench_env}
    os.environ.update(_bench_env)
    try:
        return _bench_train_step_inner(on_tpu, mesh1=Mesh(
            np.asarray(jax.devices()[:1]), ("dp",)
        ))
    finally:
        for key, prior in _saved_env.items():
            if prior is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = prior


def _bench_train_step_inner(on_tpu: bool, mesh1) -> dict:
    import optax

    from torch_cgx_tpu.models import GPT2, GPT2Config, lm_loss
    from torch_cgx_tpu.parallel import gradient_sync

    cfg = (
        GPT2Config(n_layer=12, n_head=12, d_model=768, vocab_size=50257,
                   max_seq=512)
        if on_tpu
        else GPT2Config.tiny()
    )
    batch, seq = (8, 512) if on_tpu else (2, 64)
    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch, seq)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), tokens[:1])["params"]
    opt = optax.adam(1e-4)
    opt_state = opt.init(params)
    n_params = sum(x.size for x in jax.tree.leaves(params))

    def loss_fn(p):
        return lm_loss(model.apply({"params": p}, tokens), tokens)

    def plain_step(carry):
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, s = opt.update(grads, s, p)
        return (optax.apply_updates(p, updates), s), loss

    def codec_step(carry):
        # The PRODUCTION gradient-sync path on a 1-device mesh with
        # CGX_DEBUG_FORCE_CODEC: allreduce_tree's grouping (large leaves
        # standalone — zero-copy flat views; small leaves fused) + the
        # per-rank codec round trip of SRA. This measures what a real rank
        # pays, including the framework's own glue.
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p)
        grads = shard_map(
            lambda g: gradient_sync(g, mesh=mesh1, average=False),
            mesh=mesh1,
            in_specs=P(),
            out_specs=P(),
            check_vma=False,
        )(grads)
        updates, s = opt.update(grads, s, p)
        return (optax.apply_updates(p, updates), s), loss

    def steps_time(step, k: int, iters: int = 3) -> float:
        def runner(p, s):
            def body(carry, _):
                carry, loss = step(carry)
                return carry, loss

            (_, _), losses = lax.scan(body, (p, s), None, length=k)
            return losses[-1]

        jr = jax.jit(runner)

        def timed():
            np.asarray(jr(params, opt_state))
            t0 = time.perf_counter()
            for _ in range(iters):
                o = jr(params, opt_state)
            np.asarray(o)
            return (time.perf_counter() - t0) / iters

        return timed()

    k = 6 if on_tpu else 3
    t_plain = (steps_time(plain_step, k) - steps_time(plain_step, 1)) / (k - 1)
    t_codec = (steps_time(codec_step, k) - steps_time(codec_step, 1)) / (k - 1)
    overhead = (t_codec - t_plain) / t_plain * 100
    return {
        "model": "gpt2-small" if on_tpu else "gpt2-tiny",
        "params_M": round(n_params / 1e6, 1),
        "batch": batch,
        "seq": seq,
        "step_plain_ms": round(t_plain * 1e3, 2),
        "step_with_codec_ms": round(t_codec * 1e3, 2),
        "codec_overhead_pct": round(overhead, 1),
        "grad_bytes_MB": round(n_params * 4 / 2**20, 1),
    }


def bench_allreduce(devices) -> dict:
    from torch_cgx_tpu.config import CompressionConfig
    from torch_cgx_tpu.parallel.reducers import quantized_allreduce

    n_elems = 16 * 1024 * 1024  # 64 MB fp32
    mesh = Mesh(np.asarray(devices), ("dp",))
    ws = len(devices)
    cc = CompressionConfig(bits=BITS, bucket_size=BUCKET)
    x = jax.device_put(
        jnp.arange(n_elems, dtype=jnp.float32) / n_elems,
        NamedSharding(mesh, P()),
    )

    def q_allreduce(x):
        return quantized_allreduce(x, "dp", ws, cc, "SRA")

    def f32_allreduce(x):
        return jax.lax.psum(x, "dp")

    shard = dict(mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
    q = jax.jit(shard_map(q_allreduce, **shard))
    f = jax.jit(shard_map(f32_allreduce, **shard))

    def fetch(out):
        for leaf in jax.tree.leaves(out):
            np.asarray(jax.device_get(leaf.ravel()[:1]))

    def t(fn, *args):
        for _ in range(3):
            fetch(fn(*args))
        t0 = time.perf_counter()
        for _ in range(10):
            out = fn(*args)
        fetch(out)
        return (time.perf_counter() - t0) / 10

    tq, tf = t(q, x), t(f, x)
    gbytes = n_elems * 4 / 1e9
    return {
        "metric": f"sra_allreduce_{BITS}bit_64MB_x{ws}",
        "value": round(gbytes / tq, 3),
        "unit": "GB/s",
        "vs_baseline": round(tf / tq, 3),
        "detail": {
            "t_quantized_ms": round(tq * 1e3, 3),
            "t_fp32_psum_ms": round(tf * 1e3, 3),
            "devices": ws,
        },
    }


# ---------------------------------------------------------------------------
# In-XLA single-program allreduce vs the host bridge (ISSUE 8): the same
# payload through (a) one staged XLA program on a ws-device mesh
# (parallel/xla_allreduce.py — quantize -> all_to_all -> fused epilogue ->
# all_gather, zero host hops) and (b) the production torch bridge
# (ProcessGroupCGX over shm/store — ws real OS processes). Both children run
# in fresh subprocesses so the parent's backend state never leaks; on a box
# without ws real accelerators the staged child runs on a forced CPU
# multi-device platform and the record keys into the `@cpu` trajectory
# (bench_gate separates placeholder from chip truth).
# ---------------------------------------------------------------------------


def _xla_payload(n: int, ws: int) -> np.ndarray:
    base = (np.arange(n, dtype=np.float32) / n) - 0.5
    return np.stack([(r + 1) * base for r in range(ws)])


def _xla_staged_child(mb: int, ws: int, iters: int) -> None:
    """Child: time the staged single-program allreduce; one JSON line."""
    from torch_cgx_tpu.config import CompressionConfig
    from torch_cgx_tpu.parallel import xla_allreduce

    n = mb * 2**20 // 4
    cc = CompressionConfig(bits=BITS, bucket_size=BUCKET)
    mesh = Mesh(np.asarray(jax.devices()[:ws]), ("dp",))
    per = _xla_payload(n, ws)
    out = xla_allreduce.staged_allreduce(per, mesh=mesh, cc=cc)  # build+warm
    head = np.asarray(out)[0, :16].tolist()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = xla_allreduce.staged_allreduce(per, mesh=mesh, cc=cc)
        np.asarray(jax.device_get(out[0, :1]))  # sync
    dt = (time.perf_counter() - t0) / iters
    print(json.dumps({
        "t_staged_ms": dt * 1e3,
        "head": head,
        "backend": jax.default_backend(),
        "chip": jax.devices()[0].device_kind,
        "program_cache": xla_allreduce.program_cache_stats(),
    }))


def _xla_bridge_rank(rank: int, ws: int, initfile: str, mb: int,
                     iters: int, q) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    import torch
    import torch.distributed as dist

    import torch_cgx_tpu.torch_backend  # noqa: F401 — registers "cgx"

    n = mb * 2**20 // 4
    base = torch.arange(n, dtype=torch.float32) / n - 0.5
    t = (rank + 1) * base
    dist.init_process_group(
        "cgx", init_method=f"file://{initfile}", rank=rank, world_size=ws
    )
    try:
        res = t.clone()
        dist.all_reduce(res)  # warm (arena growth) + correctness capture
        dist.barrier()
        t0 = time.perf_counter()
        for _ in range(iters):
            dist.all_reduce(t)
        dist.barrier()
        dt = (time.perf_counter() - t0) / iters
        if rank == 0:
            q.put({"t_bridge_ms": dt * 1e3, "head": res[:16].tolist()})
    finally:
        dist.destroy_process_group()


def _xla_bridge_child(mb: int, ws: int, iters: int) -> None:
    """Child: time the production bridge allreduce (ws real processes
    over the shm/store plane); one JSON line."""
    import multiprocessing as mp
    import tempfile

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    with tempfile.TemporaryDirectory() as d:
        initfile = os.path.join(d, "init")
        procs = [
            ctx.Process(
                target=_xla_bridge_rank, args=(r, ws, initfile, mb, iters, q)
            )
            for r in range(ws)
        ]
        for p in procs:
            p.start()
        try:
            rec = q.get(timeout=600)
        finally:
            for p in procs:
                p.join(timeout=60)
                if p.is_alive():
                    p.terminate()
    print(json.dumps(rec))


def _run_json_child(args: list, env: dict, timeout: float = 900.0) -> dict:
    proc = subprocess.run(
        args, env=env, capture_output=True, text=True, timeout=timeout,
    )
    tail = (proc.stdout.strip().splitlines() or [""])[-1]
    if proc.returncode != 0 or not tail.startswith("{"):
        raise RuntimeError(
            f"child {args[2:]} failed rc={proc.returncode}: "
            f"{proc.stderr.strip()[-800:]}"
        )
    return json.loads(tail)


def bench_xla_allreduce(mb: int = 8, ws: int = 4, iters: int = 5) -> dict:
    """Staged single-program allreduce vs the production bridge on the
    same ``mb``-MB fp32 payload at ``ws`` ranks (the ISSUE 8 acceptance
    record). Staged child uses real accelerators when >= ws exist, else a
    forced CPU multi-device platform (record then keys ``@cpu``)."""
    base_env = {
        **os.environ,
        "CGX_XLA_ALLREDUCE": "on",
        "CGX_COMPRESSION_QUANTIZATION_BITS": str(BITS),
        "CGX_COMPRESSION_BUCKET_SIZE": str(BUCKET),
    }
    env_staged = dict(base_env)
    # Probe in a throwaway subprocess: initializing the TPU client here
    # would hold the chips the staged child must itself acquire (libtpu
    # refuses a second claimant in the same process tree).
    use_real = False
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import json, jax; print(json.dumps("
             "[jax.default_backend(), len(jax.devices())]))"],
            env=dict(base_env), capture_output=True, text=True, timeout=180,
        )
        backend, n_dev = json.loads(
            (probe.stdout.strip().splitlines() or ["[]"])[-1]
        )
        use_real = backend != "cpu" and n_dev >= ws
    except Exception:
        pass
    if not use_real:
        env_staged["JAX_PLATFORMS"] = "cpu"
        env_staged["XLA_FLAGS"] = (
            env_staged.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={ws}"
        )
    me = str(Path(__file__).resolve())
    staged = _run_json_child(
        [sys.executable, me, "--xla-allreduce-staged-child",
         str(mb), str(ws), str(iters)], env_staged,
    )
    env_bridge = dict(base_env)
    env_bridge["JAX_PLATFORMS"] = "cpu"
    bridge = _run_json_child(
        [sys.executable, me, "--xla-allreduce-bridge-child",
         str(mb), str(ws), str(iters)], env_bridge,
    )
    t_s, t_b = staged["t_staged_ms"], bridge["t_bridge_ms"]
    head_diff = max(
        abs(a - b) for a, b in zip(staged["head"], bridge["head"])
    )
    gbytes = mb * 2**20 / 1e9  # fp32 payload bytes per rank
    return {
        "metric": f"xla_allreduce_vs_bridge_{BITS}bit_{mb}MB_x{ws}",
        "value": round(gbytes / (t_s / 1e3), 3),
        "unit": "GB/s",
        "vs_baseline": round(t_b / t_s, 3),
        "chip": staged.get("chip", "unknown"),
        "backend": staged.get("backend", "unknown"),
        "detail": {
            "t_staged_ms": round(t_s, 3),
            "t_bridge_ms": round(t_b, 3),
            "ws": ws,
            "payload_MB": mb,
            "iters": iters,
            "results_head_max_abs_diff": head_diff,
            "staged_backend": staged.get("backend"),
            "bridge": "ProcessGroupCGX shm/store, ws real processes",
            "program_cache": staged.get("program_cache"),
        },
    }


# ---------------------------------------------------------------------------
# Compiled-schedule pipeline vs the monolithic path (ISSUE 9): the same
# payload through the production bridge twice — CGX_SCHEDULE=on (chunked
# encode/put/take/epilogue with the double-buffered in-flight window) vs
# unset (monolithic phase barriers) — with a bit-equality pre-flight on the
# full reduced tensor and the cgx_trace overlap_frac attribution of both
# runs attached (the pipelined run must report overlap > 0 where the
# monolithic run reports ~0). Host-plane measurement (the bridge always
# runs on host CPU), tagged backend "host" like shm_bench.
# ---------------------------------------------------------------------------


def _sched_bridge_rank(rank, ws, initfile, mb, iters, chunks, mode, mdir, q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = str(BITS)
    os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = str(BUCKET)
    os.environ["CGX_METRICS_DIR"] = mdir
    if mode == "plan":
        # Planner mode (bench.py --planner): the step planner owns the
        # depth decision through the ENV-ONLY bridge plane — CGX_PLANNER
        # plus (for the calibrated run) the CGX_PLANNER_MODEL file the
        # parent wrote. The rank process deliberately does NOT import
        # the parallel package: it exercises exactly the pure-bridge
        # path (backend._plan_bridge_chunks, the dependency-light
        # mirror), and stays import-symmetric with the static ranks so
        # the A/B measures the decision, not the process footprint.
        os.environ["CGX_PLANNER"] = "on"
        os.environ.pop("CGX_SCHEDULE", None)
        os.environ.pop("CGX_SCHED_CHUNKS", None)
    else:
        os.environ["CGX_SCHED_CHUNKS"] = str(chunks)
        os.environ["CGX_SCHEDULE"] = "on" if mode == "pipe" else "off"
    import zlib

    import torch
    import torch.distributed as dist

    import torch_cgx_tpu.torch_backend  # noqa: F401 — registers "cgx"
    from torch_cgx_tpu.observability import timeline
    from torch_cgx_tpu.utils.logging import metrics as _m

    n = mb * 2**20 // 4
    base = torch.arange(n, dtype=torch.float32) / n - 0.5
    t = (rank + 1) * base
    dist.init_process_group(
        "cgx", init_method=f"file://{initfile}", rank=rank, world_size=ws
    )
    try:
        res = t.clone()
        dist.all_reduce(res)  # warm (arena growth) + bit-equality capture
        dist.barrier()
        t0 = time.perf_counter()
        for _ in range(iters):
            work = t.clone()
            dist.all_reduce(work)
        dist.barrier()
        dt = (time.perf_counter() - t0) / iters
        timeline.flush()
        if rank == 0:
            wall = _m.get("cgx.sched.wall_s")
            rec = {
                "t_ms": dt * 1e3,
                "crc": zlib.crc32(res.numpy().tobytes()),
                "live_overlap": (
                    _m.get("cgx.sched.overlap_s") / wall if wall else 0.0
                ),
            }
            if mode == "plan":
                # the depth the mirror actually ran (gauge set per call)
                rec["chunks"] = int(_m.get("cgx.plan.bridge_chunks") or 1)
            q.put(rec)
    finally:
        dist.destroy_process_group()


def _sched_bridge_child(mb: int, ws: int, iters: int, chunks: int,
                        mode: str) -> None:
    """Child: one bridge run (ws real processes) in the given mode; prints
    one JSON line with timing, the full-result crc32 and the cgx_trace
    per-rank overlap_frac attribution of the run's own metrics dir."""
    import multiprocessing as mp
    import tempfile

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    with tempfile.TemporaryDirectory() as d:
        initfile = os.path.join(d, "init")
        mdir = os.path.join(d, "metrics")
        os.makedirs(mdir, exist_ok=True)
        procs = [
            ctx.Process(
                target=_sched_bridge_rank,
                args=(r, ws, initfile, mb, iters, chunks, mode, mdir, q),
            )
            for r in range(ws)
        ]
        for p in procs:
            p.start()
        try:
            rec = q.get(timeout=600)
        finally:
            for p in procs:
                p.join(timeout=60)
                if p.is_alive():
                    p.terminate()
        # Attribution over the run's own span files (tools/cgx_trace.py):
        # the committed record carries the overlap measurement, not just
        # wall clock — bench_gate's overlap floor gates on it.
        sys.path.insert(0, str(Path(__file__).parent / "tools"))
        import cgx_trace

        per_rank = cgx_trace.load_spans(mdir)
        att = cgx_trace.attribution(per_rank) if per_rank else {"per_rank": {}}
        fracs = [
            c.get("overlap_frac", 0.0) for c in att["per_rank"].values()
        ]
        rec["overlap_frac"] = (
            round(sum(fracs) / len(fracs), 4) if fracs else 0.0
        )
        # Mean per-rank measured stage seconds (ISSUE 17): the parent
        # turns these into per-component prediction ratios
        # (bench_gate's <metric>:pred_ratio:<component> trajectories).
        if att["per_rank"]:
            n_ranks = len(att["per_rank"])
            rec["measured_components"] = {
                k: round(
                    sum(c.get(k, 0.0) for c in att["per_rank"].values())
                    / n_ranks, 6,
                )
                for k in ("quantize", "wire", "wait", "other")
            }
        if mode == "plan":
            # span-calibrated cost model of THIS run (rates + overlap):
            # computed post-measurement in the child, never in a rank —
            # the parent fits the per-chunk overhead across runs and
            # persists the result for the calibrated planner run.
            from torch_cgx_tpu.parallel import planner as _planner

            rec["model"] = _planner.CostModel.from_spans(mdir).as_dict()
    print(json.dumps(rec))


def bench_schedule(mb: int = 32, ws: int = 4, iters: int = 4,
                   chunks: int = 8) -> dict:
    """Pipelined vs monolithic bridge allreduce on the same ``mb``-MB fp32
    payload (the ISSUE 9 acceptance record): bit-equality pre-flight on
    the full result, then wall-clock + overlap_frac of both runs. The
    payload is chosen bucket-aligned (mb*2^20/4 divisible by ws*512) so
    the deterministic pipelined run is bit-equal by the schedule
    compiler's contract."""
    n = mb * 2**20 // 4
    if (-(-n // ws)) % BUCKET:
        raise ValueError(
            f"--mb {mb} at ws {ws} is not bucket-aligned (ceil(n/ws) must "
            f"divide by {BUCKET}) — the bit-equality pre-flight needs an "
            "aligned payload"
        )
    me = str(Path(__file__).resolve())
    env = {**os.environ}
    env.pop("CGX_SCHEDULE", None)
    mono = _run_json_child(
        [sys.executable, me, "--schedule-bridge-child",
         str(mb), str(ws), str(iters), str(chunks), "mono"], env,
    )
    pipe = _run_json_child(
        [sys.executable, me, "--schedule-bridge-child",
         str(mb), str(ws), str(iters), str(chunks), "pipe"], env,
    )
    if mono["crc"] != pipe["crc"]:
        raise AssertionError(
            "schedule bench: pipelined result diverges from monolithic "
            f"(crc {pipe['crc']:#x} vs {mono['crc']:#x}) — the bit-"
            "equality contract of parallel/schedule.py is broken"
        )
    t_m, t_p = mono["t_ms"], pipe["t_ms"]
    gbytes = mb * 2**20 / 1e9
    return {
        "metric": (
            f"sched_pipelined_vs_monolithic_{BITS}bit_{mb}MB_x{ws}"
        ),
        "value": round(gbytes / (t_p / 1e3), 3),
        "unit": "GB/s",
        "vs_baseline": round(t_m / t_p, 3),
        # Top-level so bench_gate's overlap floor gates it (the pipelined
        # run's cgx_trace attribution; the monolithic run's is in detail
        # for the ~0 contrast).
        "overlap_frac": pipe["overlap_frac"],
        # Host-plane measurement: the bridge always runs on host CPU, on
        # any box — a genuine trajectory (shm_bench's convention), not a
        # CPU placeholder for a chip number.
        "backend": "host",
        "chip": "host",
        "detail": {
            "t_pipelined_ms": round(t_p, 3),
            "t_monolithic_ms": round(t_m, 3),
            "ws": ws,
            "payload_MB": mb,
            "iters": iters,
            "sched_chunks": chunks,
            "results": "bit-equal (crc32 of full tensor asserted)",
            "overlap_frac_monolithic": mono["overlap_frac"],
            "overlap_frac_pipelined": pipe["overlap_frac"],
            "live_overlap_pipelined": round(pipe.get("live_overlap", 0.0), 4),
            "bridge": "ProcessGroupCGX shm/store, ws real processes",
        },
    }


def _planner_pred_components(
    fitted, n: int, ws: int, iters: int, measured,
) -> dict:
    """{component: predicted/measured ratio} for the calibrated model's
    per-stage raw-work predictions vs the run's span attribution —
    empty when the child attached no measurement (spanless run)."""
    if not isinstance(measured, dict):
        return {}
    per_slice = fitted.predict_slice_components(
        n, ws, BITS, BUCKET, chunks=1, route="bridge"
    )
    out = {}
    for comp in ("quantize", "wire"):
        m = float(measured.get(comp, 0.0))
        p = per_slice.get(comp, 0.0) * iters
        if m > 1e-9 and p > 0:
            out[comp] = round(p / m, 4)
    return out


def bench_planner(mb: int = 32, ws: int = 4, iters: int = 4) -> dict:
    """Planner-vs-static record (the ISSUE 12 acceptance row): the full
    closed loop on the production bridge —

    1. **static baseline**: ``CGX_SCHEDULE=on`` at the default
       ``CGX_SCHED_CHUNKS`` (the configuration a hand-tuned job runs);
    2. **calibration run**: ``CGX_PLANNER=on`` under the built-in
       default model (the mirror's depth), leaving span telemetry;
    3. the parent builds the span-calibrated ``CostModel`` and fits the
       per-chunk overhead from the TWO measured (depth, time) points —
       the rates say how the exposed stage amortizes, the two
       measurements pin what each extra chunk really costs on this box;
    4. **planner run**: the calibrated model persisted to a
       ``CGX_PLANNER_MODEL`` file every rank loads (the group-consistent
       channel) — the planner's OWN depth decision, measured.

    Static and planner configs take the min of two child runs each (the
    least-contended estimate — see ``_best_of``). Bit-equality
    pre-flight across all runs (the deterministic schedule contract:
    any depth, same bytes), ``overlap_frac`` attached, and
    predicted-vs-measured carried for ``bench_gate``'s prediction floor
    (``pred_ratio`` trajectory + ``CGX_GATE_PRED_SLACK`` hard check).
    ``vs_baseline`` >= 1.0 = the planner's calibrated decision beats
    (or ties) the static configuration."""
    import dataclasses
    import tempfile

    from torch_cgx_tpu.config import DEFAULT_SCHED_CHUNKS
    from torch_cgx_tpu.parallel import planner as planner_mod

    n = mb * 2**20 // 4
    if (-(-n // ws)) % BUCKET:
        raise ValueError(
            f"--mb {mb} at ws {ws} is not bucket-aligned (ceil(n/ws) must "
            f"divide by {BUCKET}) — the bit-equality pre-flight needs an "
            "aligned payload"
        )
    me = str(Path(__file__).resolve())
    env = {**os.environ}
    for k in ("CGX_SCHEDULE", "CGX_SCHED_CHUNKS", "CGX_PLANNER",
              "CGX_PLANNER_MODEL"):
        env.pop(k, None)

    def _best_of(n_runs, extra_env, *args):
        """min-t_ms of repeated child runs — the least-contended
        estimate; a shared box's load spikes inflate individual runs by
        ±25%, and a single-sample A/B would measure the scheduler, not
        the schedule."""
        recs = [
            _run_json_child(
                [sys.executable, me, "--schedule-bridge-child", *args],
                {**env, **extra_env},
            )
            for _ in range(n_runs)
        ]
        return min(recs, key=lambda r: r["t_ms"])

    static = _best_of(
        2, {}, str(mb), str(ws), str(iters), str(DEFAULT_SCHED_CHUNKS),
        "pipe",
    )
    cal = _run_json_child(
        [sys.executable, me, "--schedule-bridge-child",
         str(mb), str(ws), str(iters), "0", "plan"], env,
    )
    # Two-point overhead fit: t(c) = B + E/c + c*O with E (the exposed
    # non-bottleneck stage) from the calibrated rates; the static and
    # calibration runs measured t at two depths, so O falls out of the
    # difference (B cancels). Guarded to stay positive.
    model = planner_mod.CostModel.from_dict(cal["model"])
    rates_only = dataclasses.replace(model, chunk_overhead_s=0.0)
    exposed = rates_only.predict_slice(
        n, ws, BITS, BUCKET, chunks=1, route="bridge"
    ) - rates_only.predict_slice(
        n, ws, BITS, BUCKET, chunks=10**9, route="bridge"
    )
    c_s, t_s = DEFAULT_SCHED_CHUNKS, static["t_ms"] / 1e3
    c_c, t_c = max(1, int(cal["chunks"])), cal["t_ms"] / 1e3
    if c_c != c_s:
        overhead = ((t_c - t_s) - exposed * (1 / c_c - 1 / c_s)) / (c_c - c_s)
    else:
        overhead = model.chunk_overhead_s
    overhead = max(overhead, 1e-6)
    fitted = dataclasses.replace(
        model, chunk_overhead_s=overhead, source=model.source + "+2pt"
    )
    with tempfile.TemporaryDirectory() as d:
        mpath = os.path.join(d, "cost_model.json")
        fitted.save(mpath)
        plan = _best_of(
            2, {"CGX_PLANNER_MODEL": mpath},
            str(mb), str(ws), str(iters), "0", "plan",
        )
    crcs = {static["crc"], cal["crc"], plan["crc"]}
    if len(crcs) != 1:
        raise AssertionError(
            "planner bench: results diverge across runs "
            f"(crcs {sorted(crcs)}) — the planner must only pick knobs, "
            "never change bytes"
        )
    t_p = plan["t_ms"]
    depth = max(1, int(plan["chunks"]))
    # The model's own prediction for the depth it chose, anchored at the
    # measured calibration point (B from t_c at depth c_c).
    predicted_ms = (
        t_c + exposed * (1 / depth - 1 / c_c) + (depth - c_c) * overhead
    ) * 1e3
    gbytes = mb * 2**20 / 1e9
    return {
        "metric": f"planner_vs_static_{BITS}bit_{mb}MB_x{ws}",
        "value": round(gbytes / (t_p / 1e3), 3),
        "unit": "GB/s",
        # >= 1.0 = the planner's calibrated decision beats the static
        # configuration — the acceptance bar.
        "vs_baseline": round(static["t_ms"] / t_p, 3),
        "overlap_frac": plan["overlap_frac"],
        # bench_gate's prediction floor: the trajectory key
        # planner_vs_static_*:pred_ratio plus the hard slack pair.
        "predicted_step_ms": round(predicted_ms, 3),
        "measured_step_ms": round(t_p, 3),
        "pred_ratio": round(predicted_ms / t_p, 4) if t_p else 0.0,
        # Per-component prediction accuracy (ISSUE 17): raw per-stage
        # work (chunks=1 — span durations measure work, not exposure)
        # against the planner run's measured span attribution. Gated as
        # <metric>:pred_ratio:<component> trajectories by bench_gate.
        "pred_components": _planner_pred_components(
            fitted, n, ws, iters, plan.get("measured_components")
        ),
        # Host-plane measurement (the bridge always runs on host CPU) —
        # a genuine trajectory, like bench_schedule/shm_bench.
        "backend": "host",
        "chip": "host",
        "detail": {
            "t_planned_ms": round(t_p, 3),
            "t_static_ms": round(static["t_ms"], 3),
            "t_calibration_ms": round(cal["t_ms"], 3),
            "planner_chunks": depth,
            "static_chunks": DEFAULT_SCHED_CHUNKS,
            "calibration_chunks": c_c,
            "fitted_overhead_ms": round(overhead * 1e3, 3),
            "cost_model": fitted.source,
            "ws": ws,
            "payload_MB": mb,
            "iters": iters,
            "results": "bit-equal (crc32 of full tensor asserted, 3 runs)",
            "overlap_frac_static": static["overlap_frac"],
            "overlap_frac_planned": plan["overlap_frac"],
            "bridge": "ProcessGroupCGX shm/store, ws real processes",
        },
    }


# ---------------------------------------------------------------------------
# bench.py --async-dcn (ISSUE 13): asynchronous cross-slice plane vs the
# synchronous two-level path under an injected slow DCN edge. 2 fake
# slices (CGX_SHM_HOST_ID) x ws/2 ranks; the slow edge is a
# `slow_rank:<10x step>@rank=<sliceB leader>@edge=dcn` fault — on the
# sync path it sits on the critical path (every rank stalls behind the
# cross exchange), on the async path the same fault fires inside the
# dedicated sender thread and the step never feels it. The committed
# record carries the speedup, a convergence-proxy loss delta (distance
# to the global optimum of a deterministic quadratic), and the round-0
# delta crc of two repeated async runs (bit-reproducible under the
# fixed seed). Host-plane measurement, tagged backend "host".
# ---------------------------------------------------------------------------


def _async_dcn_rank(rank, ws, initfile, mb, iters, h, mode, delay_ms, q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["CGX_COMPRESSION_QUANTIZATION_BITS"] = str(BITS)
    os.environ["CGX_COMPRESSION_BUCKET_SIZE"] = str(BUCKET)
    half = ws // 2
    # two fake slices on one real box; the byte plane stays off so the
    # intra stage rides the store deterministically on any CI box
    os.environ["CGX_SHM_HOST_ID"] = f"slice{rank // half}"
    os.environ["CGX_SHM"] = "0"
    if delay_ms > 0:
        os.environ["CGX_FAULTS"] = (
            f"slow_rank:{delay_ms}ms@rank={half}@edge=dcn"
        )
    if mode == "async":
        os.environ["CGX_ASYNC"] = "on"
        os.environ["CGX_ASYNC_H"] = str(h)
        # speed bench, not a staleness trial: the slow edge may lag many
        # rounds and must not trip the bound mid-measurement
        os.environ["CGX_ASYNC_MAX_LAG"] = str(1 << 20)
    import datetime

    import torch
    import torch.distributed as dist

    from torch_cgx_tpu.torch_backend.backend import ProcessGroupCGX

    n = mb * 2**20 // 4
    store = dist.FileStore(initfile, ws)
    pg = ProcessGroupCGX(store, rank, ws, datetime.timedelta(seconds=120))
    plane = None
    if mode == "async":
        from torch_cgx_tpu.parallel import async_plane as ap

        def mem():
            si, ns_, leaders, lg, gen = pg.async_slice_info()
            return ap.Membership(
                slice_idx=si, n_slices=ns_, leaders=tuple(leaders),
                global_ranks=tuple(lg), generation=gen,
            )

        si0, _n2, leaders0, _lg0, _g0 = pg.async_slice_info()
        # transport_fn/intra_fn: re-resolved per generation (the sender
        # is rebuilt after a reconfigure); leaders fold + publish, the
        # slice's other ranks apply the leader's exact fold bytes.
        plane = ap.AsyncPlane(
            membership_fn=mem,
            transport_fn=pg.async_sender,
            intra_fn=pg.async_intra,
            is_leader=(rank == leaders0[si0]),
        )
    # deterministic quadratic: per-rank target t_r, loss 0.5||p - t_r||^2,
    # global optimum mean(t_r); params start identical on every rank
    rng = np.random.default_rng(7)
    targets = rng.standard_normal((ws, n)).astype(np.float32)
    p = np.zeros(n, np.float32)
    denom = ws if mode == "sync" else half  # async: intra-slice mean
    lr = 0.5
    t0 = time.perf_counter()
    for step in range(iters):
        if step == 1:
            t0 = time.perf_counter()  # exclude the warm step
        g = p - targets[rank]
        t = torch.from_numpy(g.copy())
        pg.allreduce([t]).wait()
        p = p - lr * (t.numpy() / denom)
        if plane is not None:
            p = plane.maybe_outer_step(step, p)
    dt = (time.perf_counter() - t0) / max(1, iters - 1)
    if rank == 0:
        opt = targets.mean(axis=0)
        rec = {
            "t_ms": dt * 1e3,
            "opt_dist": float(
                np.linalg.norm(p - opt) / max(np.linalg.norm(opt), 1e-9)
            ),
        }
        if plane is not None and plane.first_delta_crc is not None:
            rec["delta_crc"] = int(plane.first_delta_crc)
        q.put(rec)
    pg.shutdown()


def _async_dcn_child(mb: int, ws: int, iters: int, h: int, mode: str,
                     delay_ms: int) -> None:
    """Child: one 2-slice bridge run (ws real processes) in the given
    mode; prints one JSON line with timing + the convergence proxy."""
    import multiprocessing as mp
    import tempfile

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    with tempfile.TemporaryDirectory() as d:
        initfile = os.path.join(d, "init")
        procs = [
            ctx.Process(
                target=_async_dcn_rank,
                args=(r, ws, initfile, mb, iters, h, mode, delay_ms, q),
            )
            for r in range(ws)
        ]
        for p in procs:
            p.start()
        try:
            rec = q.get(timeout=600)
        finally:
            for p in procs:
                p.join(timeout=60)
                if p.is_alive():
                    p.terminate()
    print(json.dumps(rec))


def bench_async_dcn(mb: int = 8, ws: int = 4, iters: int = 6,
                    h: int = 2) -> dict:
    """Async-vs-sync cross-slice record (the ISSUE 13 acceptance row):

    1. unfaulted sync run → the base step time the fault scales from;
    2. sync run with a 10x ``slow_rank@edge=dcn`` fault on slice B's
       leader — the synchronous two-level path stalls every step;
    3. async run (``CGX_ASYNC=on``) under the SAME fault — the cross
       stage leaves the critical path, deltas ship every ``h`` steps
       through the sender thread;
    4. a repeat of (3): the round-0 delta crc must match byte-for-byte
       (deterministic codec under the fixed seed).

    ``vs_baseline`` = faulted-sync / faulted-async step time (the
    acceptance floor is 1.5x); the convergence proxy (distance to the
    quadratic's global optimum after the same number of steps) rides in
    ``detail`` as ``loss_delta``."""
    if ws % 2 or ws < 4:
        raise ValueError(f"--ws {ws} must be even and >= 4 (2 slices)")
    me = str(Path(__file__).resolve())
    env = {**os.environ}
    for k in ("CGX_ASYNC", "CGX_ASYNC_H", "CGX_FAULTS", "CGX_SHM_HOST_ID"):
        env.pop(k, None)

    def run(mode: str, delay_ms: int) -> dict:
        return _run_json_child(
            [sys.executable, me, "--async-dcn-child", str(mb), str(ws),
             str(iters), str(h), mode, str(delay_ms)], env,
        )

    base = run("sync", 0)
    delay_ms = max(50, int(round(10 * base["t_ms"])))
    sync_f = run("sync", delay_ms)
    async_f = run("async", delay_ms)
    async_r = run("async", delay_ms)
    crc_a, crc_r = async_f.get("delta_crc"), async_r.get("delta_crc")
    if crc_a is None or crc_r is None:
        # A missing crc means NO outer round ever fired — the async arm
        # did zero cross-slice work and the "speedup" would really
        # measure skipping reconciliation entirely. Fail loudly instead
        # of committing a vacuous record.
        raise AssertionError(
            f"async-dcn bench: no outer round fired in the async run "
            f"(h={h} vs iters={iters}?) — raise --iters or lower --h"
        )
    if crc_a != crc_r:
        raise AssertionError(
            "async-dcn bench: round-0 delta crc differs across repeated "
            f"runs ({crc_a:#x} vs {crc_r:#x}) — the deterministic-delta "
            "contract of parallel/async_plane.py is broken"
        )
    t_sync, t_async = sync_f["t_ms"], async_f["t_ms"]
    gbytes = mb * 2**20 / 1e9
    return {
        "metric": f"async_vs_sync_xslice_{BITS}bit_{mb}MB_x{ws}",
        "value": round(gbytes / (t_async / 1e3), 3),
        "unit": "GB/s",
        "vs_baseline": round(t_sync / t_async, 3),
        # Host-plane measurement (the bridge always runs on host CPU) —
        # a genuine trajectory, like bench_schedule/shm_bench.
        "backend": "host",
        "chip": "host",
        "detail": {
            "t_sync_faulted_ms": round(t_sync, 3),
            "t_async_faulted_ms": round(t_async, 3),
            "t_sync_clean_ms": round(base["t_ms"], 3),
            "slow_edge_ms": delay_ms,
            "ws": ws,
            "slices": 2,
            "payload_MB": mb,
            "iters": iters,
            "async_h": h,
            "opt_dist_sync": sync_f["opt_dist"],
            "opt_dist_async": async_f["opt_dist"],
            "loss_delta": round(
                async_f["opt_dist"] - sync_f["opt_dist"], 6
            ),
            "delta_crc": async_f.get("delta_crc"),
            "deltas": "bit-reproducible (round-0 wire crc equal across "
                      "2 runs under the fixed seed)",
            "bridge": "ProcessGroupCGX store bridge, ws real processes, "
                      "2 fake slices via CGX_SHM_HOST_ID",
        },
    }


# ---------------------------------------------------------------------------
# Unified wire plane (ISSUE 10): each routed edge's collective raw vs
# compressed on the same payload — ring-attention/pipeline ppermute hops and
# the MoE dispatch all_to_all through wire.dispatch, with a bit-equality
# pre-flight on the unconfigured edge (it must lower to the plain lax
# collective) and a quantization-envelope allclose on the compressed one.
# Runs on real chips when >= ws exist, else a forced CPU multi-device
# platform (records then key into the `@cpu` trajectories).
# ---------------------------------------------------------------------------


def _wire_child(mb: int, ws: int, bits: int, iters: int) -> None:
    """Child: per-edge raw-vs-compressed timings; one JSON line."""
    import re as _re

    from torch_cgx_tpu.config import CompressionConfig
    from torch_cgx_tpu.wire import EdgeConfig
    from torch_cgx_tpu.wire import dispatch as wire_dispatch
    from torch_cgx_tpu.wire import edges as wire_edges

    n = mb * 2**20 // 4  # per-device fp32 elements
    mesh = Mesh(np.asarray(jax.devices()[:ws]), ("d",))
    perm = [(i, (i + 1) % ws) for i in range(ws)]
    cc = CompressionConfig(bits=bits, bucket_size=BUCKET)
    rng = np.random.default_rng(0)

    def timed(fn, x):
        def sync(o):
            np.asarray(jax.device_get(jax.tree.leaves(o)[0].ravel()[:1]))

        for _ in range(2):
            sync(fn(x))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(x)
        sync(out)
        return (time.perf_counter() - t0) / iters

    out = {
        "backend": jax.default_backend(),
        "chip": jax.devices()[0].device_kind,
        "edges": {},
    }

    def measure(kind, name, edge_fn, plain_fn, payload, specs):
        shard = dict(mesh=mesh, in_specs=specs, out_specs=specs,
                     check_vma=False)
        f_raw = jax.jit(shard_map(edge_fn, **shard))
        f_plain = jax.jit(shard_map(plain_fn, **shard))
        r_raw, r_plain = np.asarray(f_raw(payload)), np.asarray(f_plain(payload))
        if not (r_raw == r_plain).all():
            raise AssertionError(
                f"wire bench pre-flight: unconfigured {kind} edge is not "
                "bit-equal to the plain collective"
            )
        wire_edges.set_edge_config(
            kind, "^" + _re.escape(name) + "$", EdgeConfig(cc=cc)
        )
        f_comp = jax.jit(shard_map(edge_fn, **shard))  # fresh trace
        r_comp = np.asarray(f_comp(payload))
        envelope = 2.0 * float(np.abs(payload).max()) / (2**bits - 1)
        if not np.allclose(r_comp, r_raw, atol=envelope):
            raise AssertionError(
                f"wire bench pre-flight: {kind} compressed result outside "
                f"the {bits}-bit envelope "
                f"(max diff {np.abs(r_comp - r_raw).max():.3g} > {envelope:.3g})"
            )
        out["edges"][kind] = {
            "t_raw_ms": timed(f_raw, payload) * 1e3,
            "t_compressed_ms": timed(f_comp, payload) * 1e3,
            "max_abs_diff": float(np.abs(r_comp - r_raw).max()),
            "envelope": envelope,
        }

    per = _xla_payload(n, ws)  # (ws, n), one row per device
    for kind, name in (("ring_kv", "bench.kv"), ("pp_act", "bench.act")):
        measure(
            kind, name,
            lambda xs, k=kind, nm=name: wire_dispatch.wire_ppermute(
                xs, "d", perm, kind=k, name=nm
            ),
            lambda xs: lax.ppermute(xs, "d", perm),
            per, P("d"),
        )
    # MoE dispatch buffer (E, C, D), E % ws == 0, replicated input: the
    # all_to_all splits the expert dim locally like the EP helpers do.
    e_dim, cap = ws * 4, 64
    d_model = max(32, n // (e_dim * cap))
    buf = rng.normal(size=(e_dim, cap, d_model)).astype(np.float32)
    measure(
        "moe_a2a", "bench.a2a",
        lambda t: wire_dispatch.wire_all_to_all(
            t, "d", split_axis=0, concat_axis=1, kind="moe_a2a",
            name="bench.a2a",
        ),
        lambda t: lax.all_to_all(
            t, "d", split_axis=0, concat_axis=1, tiled=True
        ),
        buf, P(),
    )
    print(json.dumps(out))


def bench_wire(mb: int = 8, ws: int = 4, bits: int = 4,
               iters: int = 5) -> list:
    """Per-edge compressed-vs-raw records for the unified wire plane (the
    ISSUE 10 acceptance bench): one BENCH_LOG row per edge kind, each
    carrying the pre-flight evidence (unconfigured edge bit-equal to the
    plain collective; compressed within the quantization envelope)."""
    env = {
        **os.environ,
        "CGX_WIRE": "on",
        "CGX_COMPRESSION_BUCKET_SIZE": str(BUCKET),
    }
    use_real = False
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import json, jax; print(json.dumps("
             "[jax.default_backend(), len(jax.devices())]))"],
            env=dict(env), capture_output=True, text=True, timeout=180,
        )
        backend, n_dev = json.loads(
            (probe.stdout.strip().splitlines() or ["[]"])[-1]
        )
        use_real = backend != "cpu" and n_dev >= ws
    except Exception:
        pass
    if not use_real:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={ws}"
        )
    me = str(Path(__file__).resolve())
    child = _run_json_child(
        [sys.executable, me, "--wire-child",
         str(mb), str(ws), str(bits), str(iters)], env,
    )
    gbytes = mb * 2**20 / 1e9
    results = []
    for kind, d in child["edges"].items():
        t_r, t_c = d["t_raw_ms"], d["t_compressed_ms"]
        results.append({
            "metric": f"wire_{kind}_compressed_vs_raw_{bits}bit_{mb}MB_x{ws}",
            "value": round(gbytes / (t_c / 1e3), 3),
            "unit": "GB/s",
            "vs_baseline": round(t_r / t_c, 3),
            "chip": child.get("chip", "unknown"),
            "backend": child.get("backend", "unknown"),
            "detail": {
                "t_raw_ms": round(t_r, 3),
                "t_compressed_ms": round(t_c, 3),
                "ws": ws,
                "payload_MB": mb,
                "bits": bits,
                "iters": iters,
                "preflight": (
                    "raw edge bit-equal to plain collective; compressed "
                    f"max|diff| {d['max_abs_diff']:.3g} within envelope "
                    f"{d['envelope']:.3g}"
                ),
            },
        })
    return results


def _device_watchdog(seconds: float = 300.0):
    """Backend init can hang indefinitely when the device transport is
    wedged (observed: a dead client's claim blocking the service). Emit a
    diagnosable JSON line and exit instead of hanging the driver."""
    import threading

    done = threading.Event()

    def fire():
        if done.wait(seconds):
            return
        failure = {
            "metric": "device_init_failure",
            "value": 0,
            "unit": "none",
            "vs_baseline": 0,
            "detail": {
                "error": f"jax.devices() not ready in {seconds:.0f}s "
                         "(device transport unreachable?)",
                "escalation": "the transport is intermittent (it answered "
                              "2026-07-31 and the sweep captured live-chip "
                              "numbers before re-wedging — BASELINE.md "
                              "round-5 status); the full measurement "
                              "program is one command on a live chip: "
                              "tools/hw_session.sh",
            },
        }
        # Freshest REAL-CHIP measurements already in the log (the transport
        # is intermittent, not absent): surface them in the failure record
        # so a wedged round end still reports driver-era hardware evidence.
        try:
            chip_recs = []
            with open(BENCH_LOG) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("backend") == "tpu" and not rec.get("unresolved"):
                        chip_recs.append(rec)
            if chip_recs:
                failure["detail"]["latest_hardware_evidence"] = chip_recs[-3:]
        except Exception as e:
            failure["detail"]["hardware_evidence_error"] = str(e)
        # Secondary evidence that needs no chip: the bridge transport A/B
        # (tools/shm_bench.py appends its own BENCH_LOG line). Run it in a
        # fresh CPU-pinned process BEFORE reporting, bounded so a wedged
        # subprocess can't stall the failure report by more than its
        # timeout.
        try:
            env = {**os.environ, "JAX_PLATFORMS": "cpu"}
            env.pop("PYTHONPATH", None)
            proc = subprocess.run(
                [sys.executable, os.path.join("tools", "shm_bench.py"),
                 "--mb", "16", "--iters", "3"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env=env, capture_output=True, text=True, timeout=240,
            )
            tail = (proc.stdout.strip().splitlines() or [""])[-1]
            if proc.returncode == 0 and tail.startswith("{"):
                failure["detail"]["host_side_evidence"] = json.loads(tail)
        except Exception as e:  # never let evidence-gathering mask failure
            failure["detail"]["host_side_evidence_error"] = str(e)
        if done.is_set():
            # The transport came up while evidence was being gathered (the
            # subprocess widened the timeout->exit window to minutes): the
            # real benchmark is running — do NOT kill it or log a failure.
            return
        # Driver-visible line FIRST: a blocking filesystem write must not
        # suppress the very failure report the watchdog exists to emit.
        print(json.dumps(failure), flush=True)
        # Best-effort incident record; chip/backend pre-filled so log_jsonl
        # never probes the (wedged) backend.
        log_jsonl({"tool": "bench", "chip": "unreachable",
                   "backend": "unreachable", **failure})
        # Sentinel exit code (not 1/2, which python tracebacks and argparse
        # usage errors use): lets callers (tools/hw_session.sh) distinguish
        # "transport wedged during init" from ordinary failures.
        os._exit(97)

    threading.Thread(target=fire, daemon=True).start()
    return done


def _maybe_gate(results: list) -> tuple:
    """CGX_BENCH_GATE=1: run tools/bench_gate.py on the fresh records
    against the committed trajectory BEFORE they are logged — a regressed
    run exits nonzero, and the offending rows land in BENCH_LOG flagged
    ``unresolved`` (the gate's normalizer skips such rows), so a cliff
    neither passes silently nor ratchets its own baseline median down.
    Returns ``(exit code, regressed metric names)`` — only the named
    metrics are flagged, so a healthy family measured in the same run
    keeps feeding its own baseline history."""
    if os.environ.get("CGX_BENCH_GATE", "0") != "1":
        return 0, set()
    proc = subprocess.run(
        [sys.executable,
         str(Path(__file__).parent / "tools" / "bench_gate.py"),
         "--candidate", "-", "--json"],
        input="".join(
            json.dumps({"tool": "bench", **r}) + "\n" for r in results
        ),
        capture_output=True, text=True,
    )
    sys.stderr.write(proc.stdout + proc.stderr)
    regressed = set()
    try:
        verdict = json.loads(proc.stdout)
        regressed = {r["metric"] for r in verdict.get("regressions", [])}
    except (ValueError, TypeError, AttributeError):
        pass
    return proc.returncode, regressed


def _gate_and_log(results: list) -> int:
    """The shared bench epilogue: gate BEFORE logging — the candidate must
    not be part of the history it is judged against, and a regressed row
    must not poison future baseline medians (it is logged, but flagged out
    of the gate's view). Only rc == 1 is a regression VERDICT; any other
    nonzero is a gate infrastructure error (missing log, bad args) — the
    measurement is healthy, so log it clean and don't fail the bench.
    Returns the exit code the caller should propagate."""
    rc, regressed = _maybe_gate(results)
    if rc not in (0, 1):
        print(f"bench: bench_gate errored (exit {rc}); measurement "
              "logged ungated", file=sys.stderr)
        rc = 0
    for r in results:
        rec = {"tool": "bench", **r}
        # Flag only the metrics the gate named (a JSON-parse failure with
        # rc==1 degrades to flagging everything — never let a regressed
        # row slip into the baselines clean).
        if rc == 1 and (not regressed or r.get("metric") in regressed):
            rec["unresolved"] = (
                "bench_gate: regression vs the committed trajectory "
                "(see gate output); excluded from future baselines"
            )
        log_jsonl(rec)
    return rc


# ---------------------------------------------------------------------------
# Serving plane (ISSUE 15): quantized vs raw-f16 KV shipping under a
# bandwidth-modeled prefill→decode wire, measured as continuous-batching
# tokens/s and TTFT. The child is CPU-pinned (the decode program runs on
# the test backend — rows key into the `@cpu` trajectories); the wire
# model is the sender thread's byte-proportional throttle, so wire-byte
# savings translate to admission latency exactly as on a real
# bandwidth-bound interconnect (the --async-dcn injected-delay
# methodology, applied to serving).
# ---------------------------------------------------------------------------


def _serve_child(
    bits: int, requests: int, prompt: int, gen: int, batch: int,
    throttle_mbps: float,
) -> None:
    """Child: one serving run at CGX_KV_BITS=`bits`; one JSON line."""
    import tempfile
    import threading
    import zlib

    # Span telemetry for the run (ISSUE 17): the critical-path engine
    # decomposes the measured TTFT post-hoc from these — set before any
    # serving object records a span.
    mdir = tempfile.mkdtemp(prefix="cgx-serve-bench-")
    os.environ["CGX_METRICS_DIR"] = mdir

    from torch_cgx_tpu.models.gpt2 import GPT2, GPT2Config
    from torch_cgx_tpu.serving.prefill import PrefillWorker
    from torch_cgx_tpu.serving.scheduler import (
        ContinuousBatchScheduler, GPT2Server, Request, ServeConfig,
    )
    from torch_cgx_tpu.serving.transport import KvPageReceiver
    from torch_cgx_tpu.utils.logging import metrics

    class _DictStore:
        """Minimal c10d-Store look-alike (the test-suite FakeStore)."""

        def __init__(self):
            import threading as _t

            self._d, self._l = {}, _t.Lock()

        def set(self, k, v):
            with self._l:
                self._d[k] = bytes(v)

        def get(self, k):
            with self._l:
                if k not in self._d:
                    raise KeyError(k)
                return self._d[k]

        def add(self, k, v):
            with self._l:
                cur = int(self._d.get(k, b"0")) + int(v)
                self._d[k] = str(cur).encode()
                return cur

        def delete_key(self, k):
            with self._l:
                self._d.pop(k, None)

    from torch_cgx_tpu import config as cfg_mod

    # The serving stack resolves the width from CGX_KV_BITS; the argv
    # copy exists only for the process list — they must agree or the
    # row would label a width it never measured.
    assert bits == cfg_mod.kv_bits(), (bits, cfg_mod.kv_bits())
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32), train=False
    )
    page_tokens = 16
    sv = ServeConfig(
        page_tokens=page_tokens, max_batch=batch,
        max_pages=max(64, requests * ((prompt + gen) // page_tokens + 2)),
        max_seq=prompt + gen + page_tokens, ship_depth=4,
    )
    server = GPT2Server(cfg, params, sv)
    store = _DictStore()
    recv = KvPageReceiver(store)
    sched = ContinuousBatchScheduler(server, receiver=recv)
    worker = PrefillWorker(
        server, store, throttle_gbps=throttle_mbps / 1e3
    )
    rng = np.random.default_rng(0)
    prompts = [
        [int(t) for t in rng.integers(0, cfg.vocab_size, prompt)]
        for _ in range(requests)
    ]
    # Warm-up: compile prefill/decode/commit programs outside the timed
    # window (a cold jit would otherwise stall the first streams into
    # the failover rung and measure the compiler, not the wire).
    warm = Request(id="warm", tokens=list(prompts[0]),
                   max_new_tokens=page_tokens + 2)
    sched.submit(warm)
    assert sched.run(deadline_s=600), "serve bench warm-up wedged"
    metrics.reset()
    reqs = [
        Request(id=f"r{i}", tokens=list(p), max_new_tokens=gen)
        for i, p in enumerate(prompts)
    ]
    t0 = time.perf_counter()
    for r in reqs:
        sched.submit(r, remote=True)
    t = threading.Thread(
        target=lambda: [worker.serve(r.id, r.tokens) for r in reqs]
    )
    t.start()
    ok = sched.run(deadline_s=600)
    wall = time.perf_counter() - t0
    t.join(timeout=30)
    worker.stop()
    assert ok, "serve bench run left outstanding requests"
    failovers = metrics.get("cgx.serve.prefill_failovers")
    assert failovers == 0, (
        f"serve bench: {failovers} prefill failover(s) fired — the "
        "measurement would mix local-prefill admissions into the wire "
        "contrast; raise CGX_SERVE_PREFILL_TIMEOUT_MS"
    )
    tokens = sum(len(r.output) for r in reqs)
    ttft = metrics.histogram_stats("cgx.serve.ttft_ms") or {}
    crc = zlib.crc32(
        b"".join(
            np.asarray(r.output, np.int32).tobytes() for r in reqs
        )
    )
    # Post-hoc TTFT decomposition over the run's own span files: mean
    # per-request admission/prefill/ship/decode ms (the warm-up request
    # is excluded — its spans predate the timed window), plus the total
    # kv.ship wall time the pred-ratio contrast below needs.
    from torch_cgx_tpu.observability import critpath as critpath_mod
    from torch_cgx_tpu.observability import timeline as timeline_mod

    timeline_mod.flush()
    timed_ids = {r.id for r in reqs}
    ttft_components = {}
    ship_wall_s = 0.0
    try:
        rep = critpath_mod.analyze(mdir, use_cache=False)
        sums: dict = {}
        n_req = 0
        for rid, rr in rep["requests"].items():
            if rid not in timed_ids or rr["ttft_s"] is None:
                continue
            n_req += 1
            for k, v in rr["components"].items():
                sums[k] = sums.get(k, 0.0) + v
        if n_req:
            ttft_components = {
                k: round(v / n_req * 1e3, 3) for k, v in sorted(sums.items())
            }
        for tr in critpath_mod.load_tracks(mdir).values():
            for ev in tr["events"]:
                if ev.get("name") == "kv.ship" and ev.get("req") in timed_ids:
                    ship_wall_s += float(ev.get("dur_s", 0.0))
    except Exception:
        pass  # a breakdown failure must not kill the bench row
    print(json.dumps({
        "tok_s": tokens / wall,
        "wall_s": wall,
        "tokens": tokens,
        "ttft_p50_ms": ttft.get("p50", 0.0),
        "ttft_mean_ms": ttft.get("mean", 0.0),
        "ttft_components": ttft_components,
        "ship_wall_s": round(ship_wall_s, 6),
        "tokens_crc": crc,
        "kv_bytes_wire": metrics.get("cgx.serve.kv_bytes_wire"),
        "backend": jax.default_backend(),
        "chip": jax.devices()[0].device_kind,
    }))


def _serve_pred_components(rec: dict, throttle_mbps: float) -> dict:
    """{"ship": predicted/measured} for a serve child record: the
    modeled link makes the ship prediction exact arithmetic
    (bytes / rate), so the ratio gates transport efficiency itself."""
    ship_wall = float(rec.get("ship_wall_s") or 0.0)
    wire_bytes = float(rec.get("kv_bytes_wire") or 0.0)
    if ship_wall <= 1e-9 or wire_bytes <= 0 or throttle_mbps <= 0:
        return {}
    predicted_s = wire_bytes / (throttle_mbps / 1e3 * 1e9)
    return {"ship": round(predicted_s / ship_wall, 4)}


def bench_serve(
    requests: int = 10, prompt: int = 96, gen: int = 24, batch: int = 8,
    bits: int = 8, throttle_mbps: float = 0.5,
) -> list:
    """Quantized-vs-raw KV shipping records (the ISSUE 15 acceptance
    rows): the same request stream served twice under a
    ``throttle_mbps``-modeled prefill→decode wire — once with raw-f16 KV
    pages (``CGX_KV_BITS=0``, the baseline) and once quantized at
    ``bits``. ``vs_baseline`` on the tokens/s row is quantized/f16
    (acceptance floor 1.3x at 8 bits); the TTFT row gates through the
    inverse-latency trajectory. Greedy outputs must be token-identical
    between the arms (crc over every generated token) — the wire saves
    bytes, never answers."""
    me = str(Path(__file__).resolve())
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for k in ("CGX_KV_BITS", "CGX_KV_PAGE_TOKENS", "CGX_WIRE"):
        env.pop(k, None)
    env["CGX_SERVE_PREFILL_TIMEOUT_MS"] = "60000"

    def run(kv_bits: int) -> dict:
        child_env = dict(env, CGX_KV_BITS=str(kv_bits))
        return _run_json_child(
            [sys.executable, me, "--serve-child", str(kv_bits),
             str(requests), str(prompt), str(gen), str(batch),
             str(throttle_mbps)], child_env,
        )

    f16 = run(0)
    quant = run(bits)
    if quant["tokens_crc"] != f16["tokens_crc"]:
        raise AssertionError(
            f"serve bench: greedy outputs differ between {bits}-bit and "
            f"f16 KV (crc {quant['tokens_crc']:#x} vs "
            f"{f16['tokens_crc']:#x}) — the quantized-KV bit envelope "
            "flipped an argmax on the bench model"
        )
    shared_detail = {
        "requests": requests,
        "prompt_tokens": prompt,
        "gen_tokens": gen,
        "max_batch": batch,
        "kv_bits": bits,
        "wire_model_MBps": throttle_mbps,
        "t_f16_wall_s": round(f16["wall_s"], 3),
        "t_quant_wall_s": round(quant["wall_s"], 3),
        "kv_bytes_wire_f16": f16["kv_bytes_wire"],
        "kv_bytes_wire_quant": quant["kv_bytes_wire"],
        "greedy_token_identity": True,
        "transport": "store counter streams (publish-after-write), "
                     "sender throttled to the modeled wire rate",
        "backend": f16["backend"],
        "chip": f16["chip"],
    }
    tag = f"{bits}bit_p{prompt}_g{gen}_b{batch}"
    return [
        {
            "metric": f"serve_tokens_per_s_{tag}",
            "value": round(quant["tok_s"], 3),
            "unit": "tok/s",
            "vs_baseline": round(quant["tok_s"] / f16["tok_s"], 3),
            "backend": f16["backend"],
            "chip": f16["chip"],
            "detail": dict(shared_detail,
                           tok_s_f16=round(f16["tok_s"], 3)),
        },
        {
            "metric": f"serve_ttft_ms_{tag}",
            "value": round(quant["ttft_p50_ms"], 3),
            "unit": "ms",
            "ttft_ms": round(quant["ttft_p50_ms"], 3),
            "vs_baseline": round(
                f16["ttft_p50_ms"] / quant["ttft_p50_ms"], 3
            ) if quant["ttft_p50_ms"] else 0.0,
            # Critical-path TTFT decomposition of the quantized arm
            # (mean ms per request) + the wire-model prediction ratio
            # for the ship stage: the modeled link rate is exact by
            # construction, so predicted ship time is bytes/rate — the
            # trajectory catches a transport regression that inflates
            # ship wall time beyond what the bytes explain.
            "ttft_components": quant.get("ttft_components") or {},
            "pred_components": _serve_pred_components(
                quant, throttle_mbps
            ),
            "backend": f16["backend"],
            "chip": f16["chip"],
            "detail": dict(
                shared_detail,
                ttft_p50_ms_f16=round(f16["ttft_p50_ms"], 3),
                ttft_components_f16=f16.get("ttft_components") or {},
            ),
        },
    ]


# ---------------------------------------------------------------------------
# Elastic rejoin (ISSUE 16): announce-to-step-loop latency of a
# checkpoint-free rank join. ws survivor processes run a live bridge
# step loop under the elastic coordinator; one joiner process announces,
# receives the snapshot pages over the counter-stream wire, and re-enters
# the step loop at the bumped generation. The committed number is the
# joiner's full join() wall clock — no checkpoint file is ever written or
# read. Lower is better: bench_gate trajects the inverse (joins/s) via
# the top-level ``rejoin_latency_ms`` field.
# ---------------------------------------------------------------------------

_REJOIN_TAIL = 4  # post-join steps everyone runs together before exiting
_REJOIN_MAX_STEPS = 400
_REJOIN_STEP_S = 0.05
_REJOIN_GRAD_N = 4096  # tiny allreduce: steps pace on the sleep, not bytes


def _rejoin_env(donors: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["CGX_ELASTIC"] = "1"
    os.environ["CGX_JOIN_DONORS"] = str(donors)


def _rejoin_step_fn():
    import torch

    def step_fn(group, state, idx):
        g = np.full(_REJOIN_GRAD_N, 1e-3 * (idx + 1), np.float32)
        t = torch.from_numpy(g)
        group.allreduce([t]).wait()
        time.sleep(_REJOIN_STEP_S)
        return state

    return step_fn


def _rejoin_rank(rank, ws, initfile, mb, donors, q):
    import traceback

    try:
        _rejoin_env(donors)
        import datetime

        import torch.distributed as dist

        from torch_cgx_tpu.robustness import elastic as el
        from torch_cgx_tpu.robustness.supervisor import RecoverySupervisor
        from torch_cgx_tpu.torch_backend.backend import ProcessGroupCGX

        n = mb * 2**20 // 4
        store = dist.FileStore(initfile, ws + 1)
        pg = ProcessGroupCGX(
            store, rank, ws, datetime.timedelta(seconds=120)
        )
        sup = RecoverySupervisor(store, pg)
        el.ElasticCoordinator(store, sup)
        rng = np.random.default_rng(11)
        state = rng.standard_normal(n).astype(np.float32)
        fn = _rejoin_step_fn()
        step, end = 0, None
        while True:
            state = sup.run_steps(state, 1, fn, start_step=step)
            step += 1
            if end is None and sup.generation >= 1:
                # The grow fired at the entry of the step just run, so
                # the join step is step-1; the joiner replays from there
                # and everyone stops at the same index.
                end = (step - 1) + _REJOIN_TAIL
            if end is not None and step >= end:
                break
            if step >= _REJOIN_MAX_STEPS:
                raise RuntimeError(
                    f"rank {rank}: joiner never admitted within "
                    f"{_REJOIN_MAX_STEPS} steps"
                )
        pg.shutdown()
        q.put((rank, None, None))
    except Exception:
        q.put((rank, traceback.format_exc(), None))


def _rejoin_joiner(ws, initfile, mb, donors, q):
    import traceback

    try:
        _rejoin_env(donors)
        from torch_cgx_tpu.robustness import elastic as el
        from torch_cgx_tpu.robustness.supervisor import RecoverySupervisor
        from torch_cgx_tpu.utils.logging import metrics as m

        import torch.distributed as dist

        n = mb * 2**20 // 4
        store = dist.FileStore(initfile, ws + 1)
        t0 = time.perf_counter()
        res = el.join(store, np.zeros(n, np.float32), global_rank=ws)
        join_ms = (time.perf_counter() - t0) * 1e3
        sup = RecoverySupervisor(store, res.group)
        el.ElasticCoordinator(store, sup, consumed=res.decision.intents_n)
        sup.run_steps(res.state, _REJOIN_TAIL, _rejoin_step_fn(),
                      start_step=res.step)
        res.group.shutdown()
        q.put(("joiner", None, {
            "join_ms": join_ms,
            "step": res.step,
            "generation": res.generation,
            "members": res.members,
            "pages": m.get("cgx.elastic.pages_received"),
        }))
    except Exception:
        q.put(("joiner", traceback.format_exc(), None))


def _rejoin_child(mb: int, ws: int, donors: int) -> None:
    """Child: one live-bridge join round (ws survivors + 1 joiner, all
    real processes); prints one JSON line with the join latency."""
    import multiprocessing as mp
    import tempfile

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    with tempfile.TemporaryDirectory() as d:
        initfile = os.path.join(d, "init")
        procs = [
            ctx.Process(target=_rejoin_rank,
                        args=(r, ws, initfile, mb, donors, q))
            for r in range(ws)
        ]
        for p in procs:
            p.start()
        time.sleep(0.5)  # survivors enter the step loop first
        jp = ctx.Process(target=_rejoin_joiner,
                         args=(ws, initfile, mb, donors, q))
        jp.start()
        procs.append(jp)
        try:
            rec, errs = None, []
            for _ in range(ws + 1):
                tag, err, payload = q.get(timeout=300)
                if err:
                    errs.append(f"{tag}: {err}")
                if payload is not None:
                    rec = payload
        finally:
            for p in procs:
                p.join(timeout=60)
                if p.is_alive():
                    p.terminate()
    if errs or rec is None:
        raise RuntimeError("rejoin bench failed:\n" + "\n".join(errs))
    print(json.dumps(rec))


def bench_rejoin(mb: int = 8, ws: int = 2, donors: int = 1,
                 iters: int = 3) -> dict:
    """Elastic rejoin record (the ISSUE 16 acceptance row): median over
    `iters` fresh join rounds of the joiner's announce-to-step-loop wall
    clock. The joiner holds zero state at start — everything it resumes
    with arrived as snapshot pages over the store wire; the run writes
    no checkpoint file."""
    me = str(Path(__file__).resolve())
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for k in ("CGX_FAULTS", "CGX_ELASTIC", "CGX_JOIN_DONORS",
              "CGX_SHM_HOST_ID"):
        env.pop(k, None)
    runs = [
        _run_json_child(
            [sys.executable, me, "--rejoin-child",
             str(mb), str(ws), str(donors)], env,
        )
        for _ in range(iters)
    ]
    lat = sorted(r["join_ms"] for r in runs)
    med = lat[len(lat) // 2]
    rep = min(runs, key=lambda r: abs(r["join_ms"] - med))
    return {
        "metric": f"elastic_rejoin_{mb}MB_ws{ws}",
        "value": round(med, 3),
        "unit": "ms",
        "rejoin_latency_ms": round(med, 3),
        "backend": "host",
        "chip": "host",
        "detail": {
            "ws_before": ws,
            "ws_after": ws + 1,
            "donors": donors,
            "payload_MB": mb,
            "runs_ms": [round(x, 3) for x in lat],
            "join_step": rep["step"],
            "generation": rep["generation"],
            "members": rep["members"],
            "snapshot_pages": rep["pages"],
            "checkpoint_files": 0,
            "bridge": "ProcessGroupCGX store bridge, ws+1 real "
                      "processes; join() timed announce -> admitted -> "
                      "pages received -> step-loop re-entry",
        },
    }


# ---------------------------------------------------------------------------
# Socket transport vs store fallback (ISSUE 20): the same bridge
# allreduce through both cross-process byte planes — CGX_TRANSPORT=socket
# (push-mode frames over supervised TCP links) vs the legacy store path
# (publish + bounded-poll get) — with CGX_SHM=0 in both children so the
# contrast is purely the transport, a crc bit-equality pre-flight (the
# socket plane must be a byte-identical carrier), and a small-message
# latency contrast: the store path pays a poll tick per take, the socket
# plane wakes on frame arrival, so small collectives are expected >= 2x
# faster. A LinkThrottle-modeled slow-link row prices the same payload
# through a constrained link (the serving plane's byte-proportional
# model) against the model's own serialization time.
# ---------------------------------------------------------------------------


def _transport_bridge_rank(rank, ws, initfile, mb, iters, small_iters,
                           mode, q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["CGX_SHM"] = "0"  # isolate the cross-process byte plane
    if mode == "socket":
        os.environ["CGX_TRANSPORT"] = "socket"
    else:
        os.environ.pop("CGX_TRANSPORT", None)
    import zlib

    import torch
    import torch.distributed as dist

    import torch_cgx_tpu.torch_backend  # noqa: F401 — registers "cgx"

    n = mb * 2**20 // 4
    base = torch.arange(n, dtype=torch.float32) / n - 0.5
    big = (rank + 1) * base
    small = ((rank + 1) * base[:1024]).clone()
    dist.init_process_group(
        "cgx", init_method=f"file://{initfile}", rank=rank, world_size=ws
    )
    try:
        res = big.clone()
        dist.all_reduce(res)  # warm (arena growth) + crc capture
        dist.barrier()
        t0 = time.perf_counter()
        for _ in range(iters):
            dist.all_reduce(big)
        dist.barrier()
        t_big = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(small_iters):
            dist.all_reduce(small)
        dist.barrier()
        t_small = (time.perf_counter() - t0) / small_iters
        if rank == 0:
            q.put({
                "t_big_ms": t_big * 1e3,
                "t_small_ms": t_small * 1e3,
                "crc": zlib.crc32(res.numpy().tobytes()),
            })
    finally:
        dist.destroy_process_group()


def _transport_bridge_child(mb, ws, iters, small_iters, mode):
    """Child: time the bridge allreduce over one transport mode (ws real
    processes); one JSON line."""
    import multiprocessing as mp
    import tempfile

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    with tempfile.TemporaryDirectory() as d:
        initfile = os.path.join(d, "init")
        procs = [
            ctx.Process(
                target=_transport_bridge_rank,
                args=(r, ws, initfile, mb, iters, small_iters, mode, q),
            )
            for r in range(ws)
        ]
        for p in procs:
            p.start()
        try:
            rec = q.get(timeout=600)
        finally:
            for p in procs:
                p.join(timeout=60)
                if p.is_alive():
                    p.terminate()
    print(json.dumps(rec))


def _transport_throttle_row(mb: int = 4, gbps: float = 0.5) -> dict:
    """LinkThrottle-modeled slow-link row: one SocketTransport pair in
    this process, the sender constrained by the serving plane's
    byte-proportional LinkThrottle at ``gbps`` — measured wall clock for
    an ``mb``-MB post+fetch vs the model's own serialization time."""
    import threading as _threading

    from torch_cgx_tpu.serving.transport import LinkThrottle
    from torch_cgx_tpu.torch_backend import transport as _tp

    class _DictStore:
        def __init__(self):
            self._d = {}
            self._lock = _threading.Lock()

        def set(self, k, v):
            with self._lock:
                self._d[k] = bytes(v)

        def get(self, k):
            with self._lock:
                return self._d[k]

        def check(self, keys):
            with self._lock:
                return all(k in self._d for k in keys)

    store = _DictStore()

    def addr(p):
        return f"tpbench/addr/{p}"

    tx = _tp.SocketTransport(
        store, "0", addr, rank=0, io_timeout_s=10.0,
        throttle=LinkThrottle(gbps),
    )
    rx = _tp.SocketTransport(store, "1", addr, rank=1, io_timeout_s=10.0)
    payload = os.urandom(mb * 2**20)
    try:
        tx.post("tpbench/warm", b"x" * 64, to=("1",))
        rx.fetch("tpbench/warm", timeout_s=10.0, peer="0")
        t0 = time.perf_counter()
        tx.post("tpbench/pay", payload, to=("1",))
        got = rx.fetch("tpbench/pay", timeout_s=120.0, peer="0")
        dt = time.perf_counter() - t0
    finally:
        tx.close()
        rx.close()
    if got != payload:
        raise RuntimeError("throttled socket roundtrip corrupted payload")
    modeled_s = len(payload) / (gbps * 1e9)
    return {
        "gbps": gbps,
        "payload_MB": mb,
        "measured_ms": round(dt * 1e3, 3),
        "modeled_ms": round(modeled_s * 1e3, 3),
        "measured_gbps": round(len(payload) / 1e9 / dt, 4),
    }


def bench_transport(mb: int = 4, ws: int = 2, iters: int = 10,
                    small_iters: int = 40) -> dict:
    """Socket-vs-store data-plane record (the ISSUE 20 acceptance row).
    Children are fresh spawned process groups (the transport engages at
    backend construction, so the mode must be in the env before init)."""
    me = str(Path(__file__).resolve())
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for k in ("CGX_FAULTS", "CGX_TRANSPORT", "CGX_SHM",
              "CGX_SHM_HOST_ID"):
        env.pop(k, None)
    args = [str(mb), str(ws), str(iters), str(small_iters)]
    store = _run_json_child(
        [sys.executable, me, "--transport-bridge-child", *args, "store"],
        env,
    )
    sock = _run_json_child(
        [sys.executable, me, "--transport-bridge-child", *args, "socket"],
        env,
    )
    if store["crc"] != sock["crc"]:
        raise RuntimeError(
            "transport crc pre-flight failed: store crc "
            f"{store['crc']:#010x} != socket crc {sock['crc']:#010x} — "
            "the socket plane must be a byte-identical carrier"
        )
    small_speedup = (
        store["t_small_ms"] / sock["t_small_ms"]
        if sock["t_small_ms"] else 0.0
    )
    big_speedup = (
        store["t_big_ms"] / sock["t_big_ms"] if sock["t_big_ms"] else 0.0
    )
    gbytes = mb * 2**20 / 1e9
    return {
        "metric": f"transport_socket_vs_store_{mb}MB_x{ws}",
        "value": round(gbytes / (sock["t_big_ms"] / 1e3), 3),
        "unit": "GB/s",
        "vs_baseline": round(big_speedup, 3),
        "backend": "host",
        "chip": "host",
        "detail": {
            "ws": ws,
            "payload_MB": mb,
            "iters": iters,
            "small_iters": small_iters,
            "t_big_socket_ms": round(sock["t_big_ms"], 3),
            "t_big_store_ms": round(store["t_big_ms"], 3),
            "t_small_socket_ms": round(sock["t_small_ms"], 3),
            "t_small_store_ms": round(store["t_small_ms"], 3),
            "small_msg_speedup": round(small_speedup, 3),
            "small_msg_expectation": ">=2x — the store take pays a poll "
                                     "tick, the socket fetch wakes on "
                                     "frame arrival",
            "crc_preflight": "bit-identical",
            "slow_link": _transport_throttle_row(mb=min(mb, 4)),
            "bridge": "ProcessGroupCGX, ws real processes, CGX_SHM=0 "
                      "both modes; socket mode adds CGX_TRANSPORT=socket",
        },
    }


def main() -> None:
    argv = sys.argv[1:]
    if argv and argv[0] == "--xla-allreduce-staged-child":
        _xla_staged_child(int(argv[1]), int(argv[2]), int(argv[3]))
        return
    if argv and argv[0] == "--xla-allreduce-bridge-child":
        _xla_bridge_child(int(argv[1]), int(argv[2]), int(argv[3]))
        return
    if argv and argv[0] == "--schedule-bridge-child":
        _sched_bridge_child(
            int(argv[1]), int(argv[2]), int(argv[3]), int(argv[4]), argv[5]
        )
        return
    if argv and argv[0] == "--wire-child":
        _wire_child(int(argv[1]), int(argv[2]), int(argv[3]), int(argv[4]))
        return
    if argv and argv[0] == "--serve-child":
        _serve_child(
            int(argv[1]), int(argv[2]), int(argv[3]), int(argv[4]),
            int(argv[5]), float(argv[6]),
        )
        return
    if argv and argv[0] == "--serve":
        # Serving-plane record (tools/hw_session.sh queues this): both
        # children are CPU-pinned single-process runs — never touches
        # the device transport.
        _preflight_lint()
        kw = {}
        for flag, name, cast in (
            ("--requests", "requests", int), ("--prompt", "prompt", int),
            ("--gen", "gen", int), ("--batch", "batch", int),
            ("--bits", "bits", int),
            ("--throttle-mbps", "throttle_mbps", float),
        ):
            if flag in argv:
                idx = argv.index(flag) + 1
                val = argv[idx] if idx < len(argv) else ""
                try:
                    kw[name] = cast(val)
                except ValueError:
                    sys.exit(
                        f"bench: {flag} requires a {cast.__name__} "
                        f"value, got {val!r}"
                    )
        results = bench_serve(**kw)
        rc = _gate_and_log(results)
        print(json.dumps(results))
        sys.exit(rc)
    if argv and argv[0] == "--transport-bridge-child":
        _transport_bridge_child(
            int(argv[1]), int(argv[2]), int(argv[3]), int(argv[4]), argv[5]
        )
        return
    if argv and argv[0] == "--transport":
        # Socket-vs-store transport record (tools/hw_session.sh queues
        # this): bridge children are fresh CPU-pinned process groups —
        # runs on any box without touching the device transport.
        _preflight_lint()
        kw = {}
        for flag, name in (("--mb", "mb"), ("--ws", "ws"),
                           ("--iters", "iters"),
                           ("--small-iters", "small_iters")):
            if flag in argv:
                idx = argv.index(flag) + 1
                val = argv[idx] if idx < len(argv) else ""
                try:
                    kw[name] = int(val)
                except ValueError:
                    sys.exit(
                        f"bench: {flag} requires an integer value, "
                        f"got {val!r}"
                    )
        result = bench_transport(**kw)
        rc = _gate_and_log([result])
        print(json.dumps(result))
        sys.exit(rc)
    if argv and argv[0] == "--rejoin-child":
        _rejoin_child(int(argv[1]), int(argv[2]), int(argv[3]))
        return
    if argv and argv[0] == "--rejoin":
        # Elastic rejoin record (tools/hw_session.sh can queue this):
        # all ranks are fresh CPU-pinned processes on the store bridge —
        # runs on any box without touching the device transport.
        _preflight_lint()
        kw = {}
        for flag, name in (("--mb", "mb"), ("--ws", "ws"),
                           ("--donors", "donors"), ("--iters", "iters")):
            if flag in argv:
                idx = argv.index(flag) + 1
                val = argv[idx] if idx < len(argv) else ""
                try:
                    kw[name] = int(val)
                except ValueError:
                    sys.exit(
                        f"bench: {flag} requires an integer value, "
                        f"got {val!r}"
                    )
        result = bench_rejoin(**kw)
        rc = _gate_and_log([result])
        print(json.dumps(result))
        sys.exit(rc)
    if argv and argv[0] == "--async-dcn-child":
        _async_dcn_child(
            int(argv[1]), int(argv[2]), int(argv[3]), int(argv[4]),
            argv[5], int(argv[6]),
        )
        return
    if argv and argv[0] == "--async-dcn":
        # Async-vs-sync cross-slice record (tools/hw_session.sh queues
        # this): bridge children are fresh CPU-pinned process groups —
        # runs on any box without touching the device transport.
        _preflight_lint()
        kw = {}
        for flag, name in (("--mb", "mb"), ("--ws", "ws"),
                           ("--iters", "iters"), ("--h", "h")):
            if flag in argv:
                idx = argv.index(flag) + 1
                val = argv[idx] if idx < len(argv) else ""
                try:
                    kw[name] = int(val)
                except ValueError:
                    sys.exit(
                        f"bench: {flag} requires an integer value, "
                        f"got {val!r}"
                    )
        result = bench_async_dcn(**kw)
        rc = _gate_and_log([result])
        print(json.dumps(result))
        sys.exit(rc)
    if argv and argv[0] == "--wire":
        # Per-edge wire-plane records (tools/hw_session.sh queues this):
        # the child is a fresh subprocess (real chips when available, a
        # forced CPU multi-device platform otherwise).
        _preflight_lint()
        kw = {}
        for flag, name in (("--mb", "mb"), ("--ws", "ws"),
                           ("--bits", "bits"), ("--iters", "iters")):
            if flag in argv:
                idx = argv.index(flag) + 1
                val = argv[idx] if idx < len(argv) else ""
                try:
                    kw[name] = int(val)
                except ValueError:
                    sys.exit(
                        f"bench: {flag} requires an integer value, "
                        f"got {val!r}"
                    )
        results = bench_wire(**kw)
        rc = _gate_and_log(results)
        print(json.dumps(results))
        sys.exit(rc)
    if argv and argv[0] == "--codec-roofline":
        # Codec roofline round-2 records (tools/hw_session.sh queues
        # this): quantize roofline fraction + producer-fused vs staged,
        # both wire pre-flighted and gated like every trajectory.
        _preflight_lint()
        kw = {}
        for flag, name in (("--mb", "mb"), ("--ws", "ws"),
                           ("--bits", "bits"), ("--iters", "iters")):
            if flag in argv:
                idx = argv.index(flag) + 1
                val = argv[idx] if idx < len(argv) else ""
                try:
                    kw[name] = int(val)
                except ValueError:
                    sys.exit(
                        f"bench: {flag} requires an integer value, "
                        f"got {val!r}"
                    )
        results = bench_codec_roofline(**kw)
        rc = _gate_and_log(results)
        print(json.dumps(results))
        sys.exit(rc)
    if argv and argv[0] == "--schedule":
        # Pipelined-vs-monolithic schedule record (tools/hw_session.sh
        # queues this): bridge children are fresh CPU-pinned process
        # groups, so it runs on any box without touching the device.
        _preflight_lint()
        kw = {}
        for flag, name in (("--mb", "mb"), ("--ws", "ws"),
                           ("--iters", "iters"), ("--chunks", "chunks")):
            if flag in argv:
                idx = argv.index(flag) + 1
                val = argv[idx] if idx < len(argv) else ""
                try:
                    kw[name] = int(val)
                except ValueError:
                    sys.exit(
                        f"bench: {flag} requires an integer value, "
                        f"got {val!r}"
                    )
        result = bench_schedule(**kw)
        rc = _gate_and_log([result])
        print(json.dumps(result))
        sys.exit(rc)
    if argv and argv[0] == "--planner":
        # Planner-vs-static record (tools/hw_session.sh queues this):
        # bridge children are fresh CPU-pinned process groups — the
        # planner calibrates from the run's own telemetry, the static
        # child reruns its chosen knobs by hand, and the committed row
        # carries predicted-vs-measured for the bench_gate floor.
        _preflight_lint()
        kw = {}
        for flag, name in (("--mb", "mb"), ("--ws", "ws"),
                           ("--iters", "iters")):
            if flag in argv:
                idx = argv.index(flag) + 1
                val = argv[idx] if idx < len(argv) else ""
                try:
                    kw[name] = int(val)
                except ValueError:
                    sys.exit(
                        f"bench: {flag} requires an integer value, "
                        f"got {val!r}"
                    )
        result = bench_planner(**kw)
        rc = _gate_and_log([result])
        print(json.dumps(result))
        sys.exit(rc)
    if argv and argv[0] == "--xla-allreduce":
        # Standalone staged-vs-bridge record (tools/hw_session.sh queues
        # this): children are fresh subprocesses, so the parent's backend
        # never wedges; the record lands in BENCH_LOG like every metric.
        _preflight_lint()
        kw = {}
        for flag, name in (("--mb", "mb"), ("--ws", "ws"),
                           ("--iters", "iters")):
            if flag in argv:
                idx = argv.index(flag) + 1
                val = argv[idx] if idx < len(argv) else ""
                try:
                    kw[name] = int(val)
                except ValueError:
                    sys.exit(
                        f"bench: {flag} requires an integer value, "
                        f"got {val!r}"
                    )
        result = bench_xla_allreduce(**kw)
        rc = _gate_and_log([result])
        print(json.dumps(result))
        sys.exit(rc)
    _preflight_lint()
    ready = _device_watchdog()
    devices = jax.devices()
    ready.set()
    extra = []
    if len(devices) > 1:
        result = bench_allreduce(devices)
    else:
        on_tpu = jax.default_backend() == "tpu"
        result = bench_codec(on_tpu)
        result["detail"]["train_step"] = bench_train_step(on_tpu)
        # The second codec round trip of the production SRA path, staged
        # vs fused — its own BENCH_LOG record so the fused-path trajectory
        # is gate-able independently of the raw kernel numbers.
        extra.append(bench_sra_epilogue(on_tpu))
    rc = _gate_and_log([result] + extra)
    print(json.dumps(result))
    if rc:
        sys.exit(rc)


if __name__ == "__main__":
    main()
