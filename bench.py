"""Benchmark harness — prints ONE JSON line.

Adaptive to available hardware:

* multi-device: quantized 4-bit SRA allreduce of a 64 MB fp32 gradient
  buffer vs XLA's native fp32 ``psum`` (the reference's headline: compressed
  allreduce speedup over full-precision, BASELINE.md north star).
  ``vs_baseline`` = fp32-psum time / quantized time (>1 = faster than fp32).
* single device: fused Pallas codec throughput (quantize+dequantize round
  trip, the TPU work this framework adds to the hot path), with
  ``vs_baseline`` = speedup over the pure-XLA lax-ops codec on the same chip.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

N_ELEMS = 16 * 1024 * 1024  # 64 MB fp32
BITS = 4
BUCKET = 512
WARMUP = 3
ITERS = 20


def _fetch(out) -> None:
    # Pull one element of every output to host: device queues are in-order,
    # so this forces completion of all queued executions (block_until_ready
    # alone does not reliably synchronize through the axon tunnel).
    for leaf in jax.tree.leaves(out):
        np.asarray(jax.device_get(leaf.ravel()[:1]))


def _time(fn, *args) -> float:
    for _ in range(WARMUP):
        _fetch(fn(*args))
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    _fetch(out)
    return (time.perf_counter() - t0) / ITERS


def bench_allreduce(devices) -> dict:
    from torch_cgx_tpu.config import CompressionConfig
    from torch_cgx_tpu.parallel.reducers import quantized_allreduce

    mesh = Mesh(np.asarray(devices), ("dp",))
    ws = len(devices)
    cc = CompressionConfig(bits=BITS, bucket_size=BUCKET)
    x = jax.device_put(
        jnp.arange(N_ELEMS, dtype=jnp.float32) / N_ELEMS,
        NamedSharding(mesh, P()),
    )

    def q_allreduce(x):
        return quantized_allreduce(x, "dp", ws, cc, "SRA")

    def f32_allreduce(x):
        return jax.lax.psum(x, "dp")

    shard = dict(mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
    q = jax.jit(jax.shard_map(q_allreduce, **shard))
    f = jax.jit(jax.shard_map(f32_allreduce, **shard))
    tq, tf = _time(q, x), _time(f, x)
    gbytes = N_ELEMS * 4 / 1e9
    return {
        "metric": f"sra_allreduce_{BITS}bit_64MB_x{ws}",
        "value": round(gbytes / tq, 3),
        "unit": "GB/s",
        "vs_baseline": round(tf / tq, 3),
        "detail": {
            "t_quantized_ms": round(tq * 1e3, 3),
            "t_fp32_psum_ms": round(tf * 1e3, 3),
            "devices": ws,
        },
    }


def bench_codec() -> dict:
    """Quantize and dequantize timed separately (a fused round trip lets XLA
    simplify the whole pipeline away — not what runs inside the reducers,
    where the packed payload crosses a collective boundary)."""
    from torch_cgx_tpu.ops import codec, codec_pallas

    on_tpu = jax.default_backend() == "tpu"
    # 512 MB on real hardware so the op dwarfs timing noise; small in
    # interpreter mode (CPU fallback) where the Pallas path runs in pure
    # Python.
    n = 128 * 1024 * 1024 if on_tpu else 1024 * 1024
    x = (jnp.arange(n, dtype=jnp.float32) / n)[None]

    def q_pallas(x):
        return codec_pallas.quantize_batch(
            x, BITS, BUCKET, stochastic=False, interpret=not on_tpu
        )

    def q_xla(x):
        return jax.vmap(lambda r: codec.quantize(r, BITS, BUCKET))(x)

    def d_pallas(q):
        return codec_pallas.dequantize_batch(
            q, out_dtype=jnp.float32, interpret=not on_tpu
        )

    def d_xla(q):
        return jax.vmap(lambda qq: codec.dequantize(qq, out_dtype=jnp.float32))(q)

    qt = jax.block_until_ready(jax.jit(q_pallas)(x))
    tpq = _time(jax.jit(q_pallas), x)
    tpd = _time(jax.jit(d_pallas), qt)
    txq = _time(jax.jit(q_xla), x)
    txd = _time(jax.jit(d_xla), qt)
    gbytes = n * 4 / 1e9
    tp, tx = tpq + tpd, txq + txd
    return {
        "metric": f"pallas_codec_{BITS}bit_{n * 4 // 2**20}MB",
        "value": round(gbytes / tp, 3),
        "unit": "GB/s",
        "vs_baseline": round(tx / tp, 3),
        "detail": {
            "t_pallas_quantize_ms": round(tpq * 1e3, 3),
            "t_pallas_dequantize_ms": round(tpd * 1e3, 3),
            "t_xla_quantize_ms": round(txq * 1e3, 3),
            "t_xla_dequantize_ms": round(txd * 1e3, 3),
            "backend": jax.default_backend(),
        },
    }


def main() -> None:
    devices = jax.devices()
    result = bench_allreduce(devices) if len(devices) > 1 else bench_codec()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
